"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).

Appended as ops after the backward marker: grad = grad + coeff-term(param),
exactly Fluid's append_regularization_ops.
"""

from __future__ import annotations

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        from .layers.layer_helper import LayerHelper

        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op("scale", inputs={"X": param}, outputs={"Out": decay},
                        attrs={"scale": float(self._coeff)})
        block.append_op("sum", inputs={"X": [grad, decay]}, outputs={"Out": grad})
        return grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        from .layers.layer_helper import LayerHelper

        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        block.append_op("sign", inputs={"X": param}, outputs={"Out": sign})
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op("scale", inputs={"X": sign}, outputs={"Out": decay},
                        attrs={"scale": float(self._coeff)})
        block.append_op("sum", inputs={"X": [grad, decay]}, outputs={"Out": grad})
        return grad


def append_regularization_ops(parameters_and_grads, regularization=None):
    """reference: regularizer.py append_regularization_ops — per-param
    regularizer wins over the optimizer-level one."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        regularizer = getattr(param, "regularizer", None) or regularization
        if regularizer is not None:
            block = grad.block
            grad = regularizer.append_regularization_op(param, grad, block) or grad
        params_and_grads.append((param, grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
