"""Tunable registrations: the knobs ``tools/autotune.py`` can sweep.

Each :class:`Tunable` names a table kernel key and supplies (a) the default
shape points to sweep on this backend, (b) the candidate config space at a
shape, (c) the hardcoded-default config (so every sweep reports a
before/after against what the code would have done untuned), (d) a
``build`` that returns a timeable ``(fn, args)`` and (e) analytic cost
features for the pre-timing prune.

Registered here:

* ``flash_attention`` — Pallas flash BlockSizes (block_q x block_k), the
  knob the round-4 hand sweep found 3.57x in;
* ``sparse_adam`` — ids-per-grid-step of the row-DMA sparse Adam/SGD
  kernel (how many row DMAs ride one gather wave);
* ``softmax_xent`` — (batch, vocab) tile sizes of the streamed
  softmax-with-cross-entropy kernel;
* ``pass_gates`` — per-program ``PADDLE_TPU_PASS_*`` gate selection,
  measured END-TO-END on the optimized clone's step time (a pass that
  costs more than it saves on a given program gets turned off for it);
* ``paged_attention`` — ``block_pages`` of the ragged paged-attention
  decode kernel (KV pages DMA'd per online-softmax wave);
* ``serving.decode_fuse`` — how many serving decode steps fuse into one
  dispatched scan (host dispatch overhead vs admission latency);
* ``serving.speculation_k`` — draft length of the speculative
  draft-verify fast path (tokens-per-dispatch vs rejected-verify waste).

On CPU every tunable still builds and times (Pallas interpret mode / XLA
CPU) so CI exercises the full mechanism; TPU numbers land via the same CLI
on hardware. Heavy imports stay inside methods — this module must be cheap
to import and cycle-free (ops import ``tune.table`` lazily at trace time).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Sequence

from . import table as _table

__all__ = ["Tunable", "register_tunable", "get_tunable",
           "registered_tunables"]


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


class Tunable:
    """One searchable knob. Subclasses define the space; the driver
    (:func:`paddle_tpu.tune.search`) does the measuring and persisting."""

    kernel: str = "?"

    def default_shapes(self) -> List[dict]:
        """Shape points ``tools/autotune.py --all`` sweeps on this backend
        (small on CPU — mechanism coverage; realistic on TPU)."""
        raise NotImplementedError

    def bucket(self, shape: dict) -> str:
        raise NotImplementedError

    def candidates(self, shape: dict) -> List[dict]:
        raise NotImplementedError

    def default_config(self, shape: dict) -> dict:
        """What the code does today with no table — the sweep's baseline."""
        raise NotImplementedError

    def build(self, shape: dict, config: dict):
        """``(fn, args)`` such that ``fn(*args)`` executes one measurable
        unit of work under ``config`` (first call may trace+compile; the
        driver excludes it from timing)."""
        raise NotImplementedError

    def cost(self, shape: dict, config: dict) -> dict:
        """Analytic features for pruning (``vmem_bytes`` is the one the
        driver acts on)."""
        return {}

    def cleanup(self) -> None:
        """Release anything ``build`` left open (engines, scopes)."""

    def shape_label(self, shape: dict) -> str:
        return ",".join("%s=%s" % (k, shape[k]) for k in sorted(shape))


_REGISTRY: Dict[str, Callable[[], "Tunable"]] = {}


def register_tunable(name: str):
    """Class decorator: make ``name`` resolvable via :func:`get_tunable`
    (and sweepable via ``tools/autotune.py --kernel name``)."""

    def deco(factory):
        _REGISTRY[name] = factory
        return factory

    return deco


def get_tunable(name: str) -> Tunable:
    if name not in _REGISTRY:
        raise KeyError("unknown tunable %r (registered: %s)"
                       % (name, ", ".join(sorted(_REGISTRY))))
    return _REGISTRY[name]()


def registered_tunables() -> List[str]:
    return sorted(_REGISTRY)


# -- flash attention BlockSizes ----------------------------------------------


@register_tunable("flash_attention")
class FlashAttentionTunable(Tunable):
    """block_q x block_k tiles of the vendored Pallas flash kernel. The
    space mirrors the round-4 hand sweep (benchmarks/sweep_flash_blocks.py)
    that found 512x512; oversized tiles whose f32 probs block would blow
    VMEM are pruned analytically before timing."""

    kernel = "flash_attention"
    _BLOCKS = (128, 256, 512, 1024, 2048)

    def default_shapes(self):
        if _on_tpu():
            return [dict(b=1, h=8, s=s, d=64, causal=True, dtype="bfloat16")
                    for s in (2048, 4096, 8192)]
        # interpret-mode mechanism shapes: small enough for seconds on CPU
        return [dict(b=1, h=1, s=256, d=64, causal=True, dtype="float32"),
                dict(b=1, h=1, s=512, d=64, causal=True, dtype="float32")]

    def bucket(self, shape):
        return _table.bucket_seq(shape["s"], shape["s"])

    def _blocks_for(self, s: int):
        return [bq for bq in self._BLOCKS if s % bq == 0 and bq <= s]

    def candidates(self, shape):
        blocks = self._blocks_for(shape["s"])
        return [{"block_q": bq, "block_k": bk}
                for bq in blocks for bk in blocks]

    def default_config(self, shape):
        # the untuned fallback: largest of (512, 256, 128) dividing s —
        # attention_ops._pick_block, NOT the table-consulting lookup
        from ..ops.attention_ops import _pick_block

        b = _pick_block(shape["s"])
        return {"block_q": b, "block_k": b}

    def cost(self, shape, config):
        bq, bk, d = config["block_q"], config["block_k"], shape["d"]
        # per-grid-step VMEM working set, f32: the probs/ds block (bq x bk)
        # plus q/o tiles (bq x d) and k/v tiles (bk x d)
        return {"vmem_bytes": 4 * (bq * bk + 2 * bq * d + 2 * bk * d)}

    def make_block_sizes(self, config, sq: int, sk: int):
        # the SHARED (bq, bk) -> BlockSizes mapping — candidates are
        # measured under exactly the assignment _tuned_block_sizes serves
        from ..ops.attention_ops import _block_sizes_for

        return _block_sizes_for(min(int(config["block_q"]), sq),
                                min(int(config["block_k"]), sk))

    def build(self, shape, config):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..ops.pallas_kernels import flash_attention as fa

        b, h, s, d = shape["b"], shape["h"], shape["s"], shape["d"]
        dtype = jnp.dtype(shape.get("dtype", "float32"))
        causal = bool(shape.get("causal", True))
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d), dtype)
                   for _ in range(3))
        bs = self.make_block_sizes(config, s, s)
        sm = 1.0 / float(d) ** 0.5
        if _on_tpu():
            # fwd+bwd — the hand-tuned numbers this subsystem replaces were
            # fwd+bwd medians, so the table ranks the same quantity
            def loss(q, k, v):
                o = fa.flash_attention(q, k, v, causal=causal, sm_scale=sm,
                                       block_sizes=bs)
                return o.astype(jnp.float32).sum()

            step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
            return step, (q, k, v)

        # CPU: interpret-mode forward (the interpreter runs the REAL kernel
        # body; bwd interpret is minutes-slow, and mechanism coverage only
        # needs the config to flow into a measured, parity-checkable call)
        def fwd(q, k, v):
            prev = fa.INTERPRET
            fa.INTERPRET = True
            try:
                return fa.flash_attention(q, k, v, causal=causal,
                                          sm_scale=sm, block_sizes=bs)
            finally:
                fa.INTERPRET = prev

        return fwd, (q, k, v)


# -- sparse-adam row blocks ---------------------------------------------------


@register_tunable("sparse_adam")
class SparseAdamTunable(Tunable):
    """ids-per-grid-step of the row-DMA sparse Adam kernel: how many
    3-table row gathers ride one DMA wave before the VPU block runs."""

    kernel = "sparse_adam"
    _BLOCKS = (8, 16, 32, 64, 128, 256)

    def default_shapes(self):
        if _on_tpu():
            return [dict(vocab=1_000_000, dim=64, n=4096),
                    dict(vocab=1_000_000, dim=64, n=16384)]
        return [dict(vocab=512, dim=16, n=256),
                dict(vocab=2048, dim=16, n=1024)]

    def bucket(self, shape):
        return _table.bucket_rows(shape["n"], shape["dim"])

    def candidates(self, shape):
        cap = max(8, -(-shape["n"] // 8) * 8)
        return [{"block": b} for b in self._BLOCKS if b <= cap]

    def default_config(self, shape):
        from ..ops.pallas_kernels.sparse_adam import _BLOCK

        return {"block": min(_BLOCK, max(8, -(-shape["n"] // 8) * 8))}

    def cost(self, shape, config):
        # 4 VMEM scratch tiles of [block, dim] f32 (p/m/v + grad rows)
        return {"vmem_bytes": 4 * 4 * config["block"] * shape["dim"]}

    def build(self, shape, config):
        import jax.numpy as jnp
        import numpy as np

        from ..core.sparse import merge_rows
        from ..ops.pallas_kernels.sparse_adam import sparse_adam_rows

        vocab, dim, n = shape["vocab"], shape["dim"], shape["n"]
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, vocab, (n,)).astype(np.int32))
        rows = jnp.asarray(rng.randn(n, dim).astype(np.float32))
        uniq, merged = merge_rows(ids, rows, vocab)
        p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
        m = jnp.asarray(rng.randn(vocab, dim).astype(np.float32) * 0.1)
        v = jnp.asarray(np.abs(rng.randn(vocab, dim)).astype(np.float32))
        fn = functools.partial(
            sparse_adam_rows, lr_t=0.01, interpret=not _on_tpu(),
            block=int(config["block"]))
        return (lambda: fn(p, m, v, uniq, merged)), ()


# -- softmax-xent tiles -------------------------------------------------------


@register_tunable("softmax_xent")
class SoftmaxXentTunable(Tunable):
    """(batch-rows, vocab-lanes) tile of the streamed softmax-with-
    cross-entropy kernel — the knob trading VMEM residency of the running
    max/sumexp accumulators against per-tile grid overhead at V=32k."""

    kernel = "softmax_xent"
    _BN = (64, 128, 256, 512)
    _BV = (512, 1024, 2048, 4096)

    def default_shapes(self):
        if _on_tpu():
            return [dict(n=4096, v=32768)]
        return [dict(n=128, v=1024)]

    def bucket(self, shape):
        return _table.bucket_nv(shape["n"], shape["v"])

    def candidates(self, shape):
        n, v = shape["n"], shape["v"]
        return [{"block_n": bn, "block_v": bv}
                for bn in self._BN if bn <= max(8, n)
                for bv in self._BV if bv <= max(128, v)]

    def default_config(self, shape):
        from ..ops.pallas_kernels import softmax_xent as sx

        bn, bv = sx._shrink_tiles(shape["n"], shape["v"], sx._BN, sx._BV)
        return {"block_n": bn, "block_v": bv}

    def cost(self, shape, config):
        bn, bv = config["block_n"], config["block_v"]
        # the [bn, bv] f32 logits tile + three [bn, 1] accumulators
        return {"vmem_bytes": 4 * (bn * bv + 3 * bn)}

    def build(self, shape, config):
        import jax.numpy as jnp
        import numpy as np

        from ..ops.pallas_kernels import softmax_xent as sx

        n, v = shape["n"], shape["v"]
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(n, v).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, v, (n, 1)).astype(np.int32))
        bn, bv = sx._shrink_tiles(n, v, int(config["block_n"]),
                                  int(config["block_v"]))
        plog, plab, n_pad, v_pad = sx._pad_to(logits, labels, bn, bv)
        interp = not _on_tpu()

        def fwd():
            return sx._call_fwd(plog, plab, bn, bv, interp, 0.0, v)

        return fwd, ()


# -- paged-attention wave width ----------------------------------------------


@register_tunable("paged_attention")
class PagedAttentionTunable(Tunable):
    """``block_pages`` of the ragged paged-attention decode kernel: KV
    pages DMA'd per online-softmax wave. Wider waves amortize DMA issue
    and rescale cost but grow the K/V VMEM scratch (and waste work on
    short ragged contexts whose last wave is mostly masked); the engine's
    trace-time ``_block_pages`` lookup serves whatever this sweep
    persists."""

    kernel = "paged_attention"

    def default_shapes(self):
        if _on_tpu():
            return [dict(slots=8, max_ctx=2048, page_size=16, n_head=8,
                         d_head=64),
                    dict(slots=16, max_ctx=1024, page_size=16, n_head=8,
                         d_head=64)]
        # interpret-mode mechanism shape: seconds on CPU
        return [dict(slots=4, max_ctx=64, page_size=8, n_head=2, d_head=16)]

    def bucket(self, shape):
        return _table.bucket_ctx(shape["max_ctx"],
                                 shape["n_head"] * shape["d_head"])

    def candidates(self, shape):
        pps = shape["max_ctx"] // shape["page_size"]
        out, bp = [], 1
        while bp <= pps:
            out.append({"block_pages": bp})
            bp *= 2
        return out

    def default_config(self, shape):
        from ..ops.pallas_kernels.paged_attention import _default_block_pages

        pps = shape["max_ctx"] // shape["page_size"]
        return {"block_pages": _default_block_pages(
            shape["page_size"], pps, shape["n_head"] * shape["d_head"])}

    def cost(self, shape, config):
        # the K + V scratch one wave holds resident (f32 worst case)
        return {"vmem_bytes": 2 * 4 * config["block_pages"]
                * shape["page_size"] * shape["n_head"] * shape["d_head"]}

    def build(self, shape, config):
        import jax.numpy as jnp
        import numpy as np

        from ..ops.pallas_kernels.paged_attention import paged_decode_attention

        slots, ps = shape["slots"], shape["page_size"]
        h, d, max_ctx = shape["n_head"], shape["d_head"], shape["max_ctx"]
        pps = max_ctx // ps
        num_pages = slots * pps  # full-occupancy pool, like the engine's
        rng = np.random.RandomState(0)
        k_pool = jnp.asarray(rng.randn(num_pages * ps, h, d), jnp.float32)
        v_pool = jnp.asarray(rng.randn(num_pages * ps, h, d), jnp.float32)
        q = jnp.asarray(rng.randn(slots, h, d), jnp.float32)
        pt = jnp.asarray(rng.permutation(num_pages)[:slots * pps]
                         .reshape(slots, pps).astype(np.int32))
        # the ragged mix the engine actually sees: a spread of live lengths
        ctx = jnp.asarray(
            np.linspace(1, max_ctx, slots).round().astype(np.int32))
        fn = functools.partial(
            paged_decode_attention, page_size=ps,
            sm_scale=1.0 / float(d) ** 0.5,
            block_pages=int(config["block_pages"]),
            interpret=not _on_tpu())
        return (lambda: fn(q, k_pool, v_pool, pt, ctx)), ()


# -- pass gates (end-to-end measured) ----------------------------------------


@register_tunable("pass_gates")
class PassGatesTunable(Tunable):
    """Per-program ``PADDLE_TPU_PASS_*`` gate selection. Unlike the kernel
    tunables this measures END-TO-END step time of the optimized clone —
    the only honest metric for graph passes, whose value depends on what
    the rest of the pipeline and XLA do with their output. The memo in
    ``passes.pipeline.maybe_optimize`` keys on the active gate set, so each
    candidate gets its own optimized clone + compile (warmup, excluded) and
    cache-hit steady-state timing.

    Shapes are workload descriptors (JSON-safe): the canned MLP demo, or
    ``{"workload": "model", "model_dir": DIR}`` for a saved inference model
    (``tools/autotune.py --model``)."""

    kernel = "pass_gates"

    def __init__(self):
        self._built: Dict[str, tuple] = {}

    def default_shapes(self):
        return [dict(workload="mlp_demo", batch=32)]

    def _workload(self, shape):
        """(scope, exe, program, feed, fetch_list) for the descriptor,
        built once per shape and reused across candidates so every gate
        set sees identical work."""
        key = repr(sorted(shape.items()))
        if key in self._built:
            return self._built[key]
        import numpy as np

        import paddle_tpu as fluid

        batch = int(shape.get("batch", 32))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            if shape.get("workload") == "model":
                prog, feed_names, fetch_targets = fluid.io.load_inference_model(
                    shape["model_dir"], exe)
                rng = np.random.RandomState(0)
                feed = {}
                for nm in feed_names:
                    var = prog.global_block.var(nm)
                    shp = tuple(batch if (d or 0) < 0 else d
                                for d in (var.shape or ()))
                    feed[nm] = rng.randn(*shp).astype("float32")
                fetch = [t.name for t in fetch_targets]
            else:
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    x = fluid.layers.data("x", shape=[32])
                    y = fluid.layers.data("y", shape=[1], dtype="int64")
                    h = fluid.layers.fc(x, size=64, act="relu")
                    logits = fluid.layers.fc(h, size=10)
                    loss = fluid.layers.mean(
                        fluid.layers.softmax_with_cross_entropy(logits, y))
                    fluid.optimizer.SGD(0.1).minimize(loss)
                exe.run(startup)
                rng = np.random.RandomState(0)
                feed = {"x": rng.randn(batch, 32).astype("float32"),
                        "y": rng.randint(0, 10, (batch, 1)).astype("int64")}
                prog, fetch = main, [loss]
        built = (scope, exe, prog, feed, fetch)
        self._built[key] = built
        return built

    def bucket(self, shape):
        from ..monitor.device import program_fingerprint

        _, _, prog, _, _ = self._workload(shape)
        return "prog" + program_fingerprint(prog)[:12]

    def candidates(self, shape):
        from ..passes.pipeline import DEFAULT_PASS_NAMES

        # all-on plus each-single-off: enough to catch "this pass costs
        # more than it saves HERE" without a 2^6 sweep; a full subset
        # search can ride the same driver later if a workload warrants it
        return ([{"disable": []}]
                + [{"disable": [n]} for n in DEFAULT_PASS_NAMES])

    def default_config(self, shape):
        return {"disable": []}

    def build(self, shape, config):
        import paddle_tpu as fluid
        from ..passes.pipeline import pass_gate_overrides

        scope, exe, prog, feed, fetch = self._workload(shape)
        disabled = tuple(config.get("disable") or ())

        def step():
            with pass_gate_overrides(disabled):
                with fluid.scope_guard(scope):
                    return exe.run(prog, feed=feed, fetch_list=fetch)

        return step, ()

    def cleanup(self):
        self._built.clear()


# -- serving decode_fuse ------------------------------------------------------


@register_tunable("serving.decode_fuse")
class DecodeFuseTunable(Tunable):
    """How many decode steps the serving engine fuses into one dispatched
    scan. Measured as end-to-end drain time of a fixed mixed-length request
    stream — fusing amortizes host dispatch but coarsens admission/
    retirement granularity, so the winner is stream- and device-dependent
    (exactly why it is a measured knob, not a constant)."""

    kernel = "serving.decode_fuse"

    def __init__(self):
        self._open: list = []
        self._models: Dict[str, object] = {}

    def default_shapes(self):
        return [dict(slots=4, vocab=64, n_layer=2, d_model=32, n_head=2,
                     max_seq=64, page_size=8, n_requests=10, max_prompt=20,
                     max_new=8)]

    def bucket(self, shape):
        return _table.bucket_slots(shape["slots"])

    def candidates(self, shape):
        return [{"decode_fuse": k} for k in (1, 2, 4)
                if k <= shape.get("max_new", 8)]

    def default_config(self, shape):
        return {"decode_fuse": 1}  # ServingConfig's untuned default

    def _stream(self, shape):
        import numpy as np

        rng = np.random.RandomState(int(shape.get("seed", 0)))
        return [(list(rng.randint(0, shape["vocab"],
                                  int(rng.randint(3, shape["max_prompt"])))),
                 int(rng.randint(2, shape["max_new"] + 1)))
                for _ in range(shape["n_requests"])]

    def build(self, shape, config):
        from .. import serving
        from ..models import decoder_lm

        mkey = repr(sorted(shape.items()))
        model = self._models.get(mkey)
        if model is None:
            cfg = decoder_lm.DecoderConfig(
                vocab_size=shape["vocab"], n_layer=shape["n_layer"],
                d_model=shape["d_model"], n_head=shape["n_head"],
                max_seq=shape["max_seq"])
            model = decoder_lm.DecoderLM(cfg, seed=0)
            self._models[mkey] = model
        eng = serving.ServingEngine(model, serving.ServingConfig(
            slots=shape["slots"], page_size=shape["page_size"],
            max_seq=shape["max_seq"],
            decode_fuse=int(config["decode_fuse"])))
        eng.warmup()
        self._open.append(eng)
        stream = self._stream(shape)

        def drain():
            reqs = [eng.submit(p, m) for p, m in stream]
            done = eng.run()
            assert len(done) == len(reqs)
            return len(done)

        return drain, ()

    def cleanup(self):
        for eng in self._open:
            try:
                eng.close()
            except Exception:
                pass
        self._open.clear()
        self._models.clear()


@register_tunable("serving.speculation_k")
class SpeculationKTunable(Tunable):
    """Draft length k of the speculative draft-verify fast path
    (serving.speculative). Measured as end-to-end drain time of a fixed
    repetitive request stream — longer drafts emit more tokens per verify
    dispatch while acceptance holds, but every rejected tail is verify
    compute thrown away, so the winner tracks the traffic's repetitiveness
    and the device's marginal cost of a wider ragged window (near-free on
    the memory-bound paged kernel, real on CPU). ``k=0`` (plain decode) is
    in the space, so a stream speculation cannot help reports an honest
    "leave it off"."""

    kernel = "serving.speculation_k"

    def __init__(self):
        self._open: list = []
        self._models: Dict[str, object] = {}

    def default_shapes(self):
        return [dict(slots=4, vocab=48, n_layer=2, d_model=32, n_head=2,
                     max_seq=64, page_size=8, n_requests=8, max_new=24)]

    def bucket(self, shape):
        return _table.bucket_slots(shape["slots"])

    def candidates(self, shape):
        return [{"k": k} for k in (0, 2, 4, 8)
                if k < shape.get("max_new", 8)]

    def default_config(self, shape):
        return {"k": 4}  # tune.resolve_speculation_k's untuned default

    def _stream(self, shape):
        import numpy as np

        # repetitive prompts (repeated trigrams) — the traffic class the
        # n-gram drafter serves; greedy tiny-model loops extend the pattern
        rng = np.random.RandomState(int(shape.get("seed", 0)))
        out = []
        for _ in range(shape["n_requests"]):
            motif = list(rng.randint(0, shape["vocab"], 3))
            out.append((motif * 4, int(shape["max_new"])))
        return out

    def build(self, shape, config):
        from .. import serving
        from ..models import decoder_lm

        mkey = repr(sorted(shape.items()))
        model = self._models.get(mkey)
        if model is None:
            cfg = decoder_lm.DecoderConfig(
                vocab_size=shape["vocab"], n_layer=shape["n_layer"],
                d_model=shape["d_model"], n_head=shape["n_head"],
                max_seq=shape["max_seq"])
            model = decoder_lm.DecoderLM(cfg, seed=0)
            self._models[mkey] = model
        eng = serving.ServingEngine(model, serving.ServingConfig(
            slots=shape["slots"], page_size=shape["page_size"],
            max_seq=shape["max_seq"], speculation=int(config["k"])))
        eng.warmup()
        self._open.append(eng)
        stream = self._stream(shape)

        def drain():
            reqs = [eng.submit(p, m) for p, m in stream]
            done = eng.run()
            assert len(done) == len(reqs)
            return len(done)

        return drain, ()

    def cleanup(self):
        for eng in self._open:
            try:
                eng.close()
            except Exception:
                pass
        self._open.clear()
        self._models.clear()


@register_tunable("fleet.router")
class FleetRouterTunable(Tunable):
    """Replica count + affinity policy for the fleet router. Measured as
    end-to-end drain time of a fixed request stream through an in-process
    sim fleet (device-latency model): more replicas overlap more modeled
    device wait but add routing/protocol overhead, and prefix affinity
    trades spread for locality — host- and stream-dependent, so measured.
    Bucketed by host CPU count (replica workers are processes)."""

    kernel = "fleet.router"

    def __init__(self):
        self._open: list = []

    def default_shapes(self):
        import os as _os

        return [dict(cpus=_os.cpu_count() or 1, slots=4, step_ms=2.0,
                     n_requests=32, max_new=8)]

    def bucket(self, shape):
        return _table.bucket_slots(shape["cpus"])

    def candidates(self, shape):
        return [{"replicas": n, "affinity": a}
                for n in (1, 2, 4)
                for a in ("prefix", "round_robin")]

    def default_config(self, shape):
        return {"replicas": 2, "affinity": "prefix"}

    def build(self, shape, config):
        from ..fleet import FleetConfig, Router, SimConfig, SimEngine

        router = Router(FleetConfig(
            replicas=int(config["replicas"]),
            mode="inprocess", affinity=config["affinity"],
            engine_factory=lambda i: SimEngine(SimConfig(
                slots=shape["slots"], step_ms=shape["step_ms"]))))
        self._open.append(router)
        n_requests = int(shape["n_requests"])
        max_new = int(shape["max_new"])

        def drive():
            frs = [router.submit([1, 2, 3, i % 7], max_new)
                   for i in range(n_requests)]
            ok = router.wait_all(60.0)
            assert ok and all(f.state == "finished" for f in frs)
            return len(frs)

        return drive, ()

    def cleanup(self):
        for router in self._open:
            try:
                router.close()
            except Exception:
                pass
        self._open.clear()


@register_tunable("fleet.roles")
class FleetRolesTunable(Tunable):
    """Prefill/decode role mix for a disaggregated fleet. Measured as
    end-to-end drain time of a bursty mixed stream (long shared-prefix
    prompts + short follow-ups) through an in-process sim fleet whose
    cost model charges per-token prefill time, multiplied when prefill
    interleaves with in-flight decode (the mixed-batch interference that
    motivates disaggregation). More prefill replicas absorb prompt
    bursts; more decode replicas carry the token streams — the right
    split depends on the host, so it is measured. Bucketed by host CPU
    count, like ``fleet.router``."""

    kernel = "fleet.roles"

    def __init__(self):
        self._open: list = []

    def default_shapes(self):
        import os as _os

        return [dict(cpus=_os.cpu_count() or 1, slots=4, step_ms=0.5,
                     prefill_ms_per_token=0.2, interference=3.0,
                     page_size=16, n_requests=24, prompt_len=48,
                     max_new=8)]

    def bucket(self, shape):
        return _table.bucket_slots(shape["cpus"])

    def candidates(self, shape):
        return [{"prefill": p, "decode": d}
                for p, d in ((1, 1), (1, 2), (1, 3), (2, 2))]

    def default_config(self, shape):
        return {"prefill": 1, "decode": 1}

    def build(self, shape, config):
        from ..fleet import FleetConfig, Router, SimConfig, SimEngine

        ps = int(shape["page_size"])
        router = Router(FleetConfig(
            roles={"prefill": int(config["prefill"]),
                   "decode": int(config["decode"])},
            mode="inprocess", affinity="round_robin", page_size=ps,
            engine_factory=lambda i: SimEngine(SimConfig(
                slots=shape["slots"], step_ms=shape["step_ms"],
                page_size=ps,
                prefill_ms_per_token=shape["prefill_ms_per_token"],
                interference=shape["interference"]))))
        self._open.append(router)
        n_requests = int(shape["n_requests"])
        prompt_len = int(shape["prompt_len"])
        max_new = int(shape["max_new"])

        def drive():
            frs = []
            for i in range(n_requests):
                # a burst of distinct long prompts (prefill-heavy) mixed
                # with short follow-ups (decode-heavy)
                if i % 3:
                    prompt = [i * 131 + t for t in range(prompt_len)]
                else:
                    prompt = [7, 11, i % 5]
                frs.append(router.submit(prompt, max_new))
            ok = router.wait_all(120.0)
            assert ok and all(f.terminal for f in frs)
            return len(frs)

        return drive, ()

    def cleanup(self):
        for router in self._open:
            try:
                router.close()
            except Exception:
                pass
        self._open.clear()
