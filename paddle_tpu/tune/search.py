"""Measured candidate search — the driver half of ``paddle_tpu.tune``.

TVM's loop (PAPERS.md) applied to this stack's knobs: enumerate a candidate
space, prune candidates the cost model says are obviously memory-blown,
time the survivors on the real device (warmup first, median of k timed
reps, compile time excluded because the first call traces+compiles before
the clock starts), pick the winner, persist it to the config table keyed
(kernel, shape-bucket, device_kind).

The driver is deliberately backend-agnostic: on TPU it times compiled
kernels; on CPU the same code path times Pallas interpret-mode or XLA:CPU
executions, which is how CI exercises the whole mechanism end-to-end
(ISSUE: the table produced from a fixed candidate list must be
deterministic — ties break toward the earlier candidate, and tests inject
a deterministic ``measure`` function).

Pruning uses the tunable's analytic cost features (estimated VMEM working
set per candidate — the same arithmetic the kernels' own docstrings derive)
against a per-device budget, defaulting to 3/4 of the ~16 MB/core TPU VMEM;
XLA ``cost_analysis`` gauges from a compiled probe can refine the budget
but are never required (no-TPU CI must still prune the 2048x2048 tile that
would blow VMEM on any current chip).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, List, Optional, Sequence

from ..monitor import metrics as _mx
from . import table as _table

__all__ = ["SearchResult", "median_time_ms", "search", "vmem_budget_bytes"]

_m_sweeps = _mx.counter(
    "autotune/sweeps", help="candidate sweeps run (one per kernel x shape)")
_m_timed = _mx.counter(
    "autotune/candidates_timed", help="candidates actually timed on device")
_m_pruned = _mx.counter(
    "autotune/candidates_pruned",
    help="candidates dropped by the analytic cost model before timing "
         "(VMEM working set over budget)")
_m_failed = _mx.counter(
    "autotune/candidates_failed",
    help="candidates whose build/measure raised (recorded, sweep continues)")
_m_measure = _mx.histogram(
    "autotune/measure_ms",
    help="median candidate times observed by the search driver")

# ~16 MB/core of VMEM on current TPUs (pallas_guide.md); leave headroom for
# the compiler's own scratch. Overridable for other parts/experiments.
_DEFAULT_VMEM_BUDGET = 12 * 2 ** 20


def vmem_budget_bytes() -> int:
    raw = os.environ.get("PADDLE_TPU_TUNE_VMEM_BUDGET", "").strip()
    try:
        return int(raw) if raw else _DEFAULT_VMEM_BUDGET
    except ValueError:
        return _DEFAULT_VMEM_BUDGET


def _block(x: Any) -> None:
    """block_until_ready over an arbitrary result pytree (numpy results —
    e.g. an Executor fetch — are already synchronous)."""
    import jax

    try:
        jax.block_until_ready(x)
    except Exception:
        pass


def median_time_ms(fn: Callable, args: Sequence, *, warmup: int = 1,
                   reps: int = 5, **_ignored) -> float:
    """Median wall time of ``fn(*args)`` over ``reps`` timed calls.

    The FIRST call runs before the clock starts — that is where trace +
    compile happen, and tuned tables must rank steady-state step time, not
    compile latency (the persistent compile cache absorbs that separately).
    """
    for _ in range(max(1, int(warmup))):
        _block(fn(*args))
    times = []
    for _ in range(max(1, int(reps))):
        t0 = time.perf_counter()
        _block(fn(*args))
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    n = len(times)
    return times[n // 2] if n % 2 else 0.5 * (times[n // 2 - 1] + times[n // 2])


class SearchResult:
    """Outcome of one sweep: every candidate row (timed, pruned or failed),
    the winner, the default config's measured time for the before/after
    story, and where (if anywhere) the winner was persisted."""

    def __init__(self, kernel: str, bucket: str, device: str, shape: dict,
                 rows: List[dict], best: Optional[dict],
                 best_ms: Optional[float], default_ms: Optional[float],
                 written_path: Optional[str]):
        self.kernel = kernel
        self.bucket = bucket
        self.device = device
        self.shape = shape
        self.rows = rows
        self.best = best
        self.best_ms = best_ms
        self.default_ms = default_ms
        self.written_path = written_path

    @property
    def speedup_vs_default(self) -> Optional[float]:
        if self.best_ms and self.default_ms:
            return self.default_ms / self.best_ms
        return None

    def to_dict(self) -> dict:
        return {
            "kernel": self.kernel, "bucket": self.bucket,
            "device": self.device, "shape": self.shape,
            "best": self.best, "best_ms": self.best_ms,
            "default_ms": self.default_ms,
            "speedup_vs_default": (round(self.speedup_vs_default, 4)
                                   if self.speedup_vs_default else None),
            "written": self.written_path,
            "candidates": self.rows,
        }


def _same_config(a: Optional[dict], b: Optional[dict]) -> bool:
    return a is not None and b is not None and dict(a) == dict(b)


def search(tunable, shape: Optional[dict] = None, *,
           candidates: Optional[Sequence[dict]] = None,
           reps: int = 5, warmup: int = 1, persist: bool = True,
           measure: Optional[Callable] = None,
           budget_bytes: Optional[int] = None,
           table_file: Optional[str] = None) -> SearchResult:
    """Run one measured sweep for ``tunable`` at ``shape`` and (optionally)
    persist the winner into the runtime config table.

    ``candidates`` overrides the tunable's own space (fixed candidate lists
    are how determinism is asserted); ``measure(fn, args, warmup=, reps=,
    config=, shape=)`` overrides the timer (tests inject deterministic cost
    functions). The default config is always appended to the space when
    missing, so ``default_ms`` exists and the sweep can only match-or-beat
    the hardcoded fallback.
    """
    shape = dict(shape if shape is not None else tunable.default_shapes()[0])
    space = [dict(c) for c in (candidates if candidates is not None
                               else tunable.candidates(shape))]
    if not space:
        raise ValueError("%s: empty candidate space for shape %r"
                         % (tunable.kernel, shape))
    default_cfg = dict(tunable.default_config(shape))
    if not any(_same_config(c, default_cfg) for c in space):
        space.append(default_cfg)
    budget = budget_bytes if budget_bytes is not None else vmem_budget_bytes()
    timer = measure or median_time_ms

    rows: List[dict] = []
    best = best_ms = default_ms = None
    for cfg in space:
        row: dict = {"config": cfg}
        feats = {}
        try:
            feats = tunable.cost(shape, cfg) or {}
        except Exception:
            pass
        vmem = feats.get("vmem_bytes")
        if vmem is not None:
            row["vmem_bytes"] = int(vmem)
        if vmem is not None and vmem > budget:
            row["pruned"] = "vmem %d > budget %d" % (vmem, budget)
            if _mx._enabled:
                _m_pruned.inc()
            rows.append(row)
            continue
        try:
            fn, args = tunable.build(shape, cfg)
            ms = float(timer(fn, args, warmup=warmup, reps=reps,
                             config=cfg, shape=shape))
        except Exception as e:
            row["error"] = "%s: %s" % (type(e).__name__, str(e)[:160])
            if _mx._enabled:
                _m_failed.inc()
            rows.append(row)
            continue
        row["median_ms"] = round(ms, 6)
        if _mx._enabled:
            _m_timed.inc()
            _m_measure.observe(ms)
        rows.append(row)
        if _same_config(cfg, default_cfg):
            default_ms = ms
        # strict < keeps ties on the EARLIER candidate — determinism of the
        # produced table under a fixed candidate list is a tested contract
        if best_ms is None or ms < best_ms:
            best, best_ms = cfg, ms
    if _mx._enabled:
        _m_sweeps.inc()
    if best is None:
        raise RuntimeError(
            "%s: no candidate survived the sweep at shape %r (all pruned "
            "or failed): %r" % (tunable.kernel, shape, rows))

    bucket = tunable.bucket(shape)
    device = _table.device_kind()
    written = None
    if persist:
        written = _table.record(
            tunable.kernel, bucket, best, device=device, median_ms=best_ms,
            note="autotune %s reps=%d" % (shape, reps),
            path=table_file)
    return SearchResult(tunable.kernel, bucket, device, shape, rows,
                        best, best_ms, default_ms, written)
