"""Persistent autotuned-config table — the storage half of ``paddle_tpu.tune``.

Tuned configs are keyed ``(kernel, shape-bucket, device_kind)`` — the Tensor
Processing Primitives argument (PAPERS.md): optimal blocking is shape- AND
microarchitecture-specific, so a v5e-tuned 512x512 flash tile must never be
served to a v4 chip or to a 384-long sequence as if it were universal. Three
layers answer every lookup, best first:

1. **tuned** — the runtime JSON table written by ``tools/autotune.py`` /
   :func:`paddle_tpu.tune.search`. Lives next to the persistent XLA compile
   cache (``<PADDLE_TPU_COMPILE_CACHE>/autotune_table.json``) so tuned
   configs survive restarts exactly like compiled executables do;
   ``PADDLE_TPU_TUNE_TABLE=<file>`` overrides the location.
2. **shipped** — ``paddle_tpu/tune/shipped.json``, checked into the repo and
   seeded with today's hand-tuned entries (the v5e 512x512 flash BlockSizes
   from the round-4 sweep, the sparse-adam 128-id blocks) as the cold-start
   lookup for known device kinds.
3. **default** — ``(None, "default")``: the caller keeps its hardcoded
   fallback. This is the answer on unknown devices, unknown shapes, a
   missing table, and — critically — a CORRUPT or partially-written table
   file, which logs once per file and never raises: a broken table must
   never crash a training run that was healthy without it.

Buckets are coarse on purpose (power-of-two floors): a tuned config for
s=8192 serves s=9000 too, and callers clamp tile sizes to the divisibility
constraints of the actual shape. A ``*`` bucket is the kernel-wide wildcard
(shipped entries use it so one hand-tuned row covers every shape the sweep
validated the trend for).

Every lookup ticks ``autotune/lookups`` plus a per-source counter and
records per-kernel provenance (:func:`provenance_snapshot`) so bench tails
can report whether the hot kernels ran ``tuned``, ``shipped`` or
``default`` configs.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Optional, Tuple

from ..monitor import metrics as _mx

__all__ = [
    "FORMAT", "WILDCARD_BUCKET",
    "device_kind", "normalize_device_kind",
    "pow2_floor", "bucket_seq", "bucket_rows", "bucket_nv", "bucket_slots",
    "bucket_ctx",
    "table_path", "shipped_path", "entry_key",
    "lookup", "record", "read_entries", "write_entries",
    "resolve_decode_fuse", "resolve_fleet_roles", "resolve_fleet_router",
    "resolve_speculation_k",
    "provenance_snapshot", "reset_provenance",
]

FORMAT = "paddle_tpu.tune/1"
WILDCARD_BUCKET = "*"

_log = logging.getLogger("paddle_tpu")

# Registered at import so the counters exist (value 0) before the first
# lookup — tools/dump_metrics --selftest asserts their presence.
_m_lookups = _mx.counter(
    "autotune/lookups",
    help="tuned-config table lookups (any source)")
_m_by_src = {
    src: _mx.counter("autotune/lookup_" + src,
                     help="lookups answered by the %s layer" % src)
    for src in ("tuned", "shipped", "default")
}
_m_writes = _mx.counter(
    "autotune/table_writes",
    help="atomic runtime-table writes (tools/autotune.py / tune.search)")
_m_errors = _mx.counter(
    "autotune/table_errors",
    help="corrupt/unreadable table files tolerated (logged once, fell "
         "back to shipped/default configs)")

_lock = threading.RLock()
# path -> (stat signature, entries dict | None-when-corrupt); re-read only
# when the file changes, so trace-time lookups cost one os.stat
_file_cache: Dict[str, Tuple[Tuple[int, int], Optional[Dict[str, dict]]]] = {}
_warned_paths: set = set()
# kernel -> {"source", "bucket", "device", "config"} of the LAST lookup —
# the bench tail's provenance evidence
_provenance: Dict[str, dict] = {}


# -- device identity ----------------------------------------------------------

_KIND_ALIASES = {
    "tpu v2": "tpu-v2",
    "tpu v3": "tpu-v3",
    "tpu v4": "tpu-v4",
    "tpu v4 lite": "tpu-v4i",
    "tpu v5": "tpu-v5p",
    "tpu v5p": "tpu-v5p",
    "tpu v5 lite": "tpu-v5e",
    "tpu v5e": "tpu-v5e",
    "tpu v5litepod": "tpu-v5e",
    "tpu v6 lite": "tpu-v6e",
    "tpu v6e": "tpu-v6e",
}


def normalize_device_kind(raw: str) -> str:
    """Canonical table key for a raw ``jax.Device.device_kind`` string
    (``"TPU v5 lite"`` -> ``"tpu-v5e"``); unknown kinds lowercase with
    spaces dashed so they still key consistently."""
    k = str(raw or "unknown").strip().lower()
    return _KIND_ALIASES.get(k, k.replace(" ", "-"))


def device_kind() -> str:
    """Normalized device kind of the current default backend."""
    from ..monitor.device import raw_device_kind

    return normalize_device_kind(raw_device_kind())


# -- shape buckets ------------------------------------------------------------


def pow2_floor(x: int) -> int:
    """Largest power of two <= x (min 1) — the bucket edge."""
    x = int(x)
    return 1 if x <= 1 else 1 << (x.bit_length() - 1)


def bucket_seq(sq: int, sk: int) -> str:
    """Flash-attention bucket over (q_len, kv_len)."""
    return "s%dx%d" % (pow2_floor(sq), pow2_floor(sk))


def bucket_rows(n_ids: int, dim: int) -> str:
    """Sparse row-update bucket over (merged id count, row width)."""
    return "n%dxd%d" % (pow2_floor(n_ids), pow2_floor(dim))


def bucket_nv(n: int, v: int) -> str:
    """Softmax-xent bucket over (batch rows, vocab)."""
    return "n%dxv%d" % (pow2_floor(n), pow2_floor(v))


def bucket_slots(slots: int) -> str:
    """Serving-knob bucket over the decode batch width."""
    return "slots%d" % pow2_floor(slots)


def bucket_ctx(max_ctx: int, hd: int) -> str:
    """Paged-attention bucket over (slot context capacity, H*D row width) —
    the two shapes that size the kernel's per-wave VMEM scratch."""
    return "c%dxhd%d" % (pow2_floor(max_ctx), pow2_floor(hd))


# -- file locations -----------------------------------------------------------


def table_path() -> Optional[str]:
    """Where the runtime (tuned) table lives: ``PADDLE_TPU_TUNE_TABLE``
    wins; else ``autotune_table.json`` next to the persistent compile cache
    (``PADDLE_TPU_COMPILE_CACHE``); None when neither is configured —
    lookups then see only shipped + default."""
    p = os.environ.get("PADDLE_TPU_TUNE_TABLE", "").strip()
    if p:
        return p
    from ..compile_cache import compile_cache_dir

    d = compile_cache_dir()
    return os.path.join(d, "autotune_table.json") if d else None


def shipped_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "shipped.json")


def entry_key(kernel: str, bucket: str, device: str) -> str:
    return "%s|%s|%s" % (kernel, bucket, device)


# -- load / store -------------------------------------------------------------


def _valid_entries(doc: Any, path: str, fmt: str = FORMAT) -> Dict[str, dict]:
    """Schema-check a parsed table document; raises ValueError on anything
    a partially-written or foreign file could look like. ``fmt`` lets other
    subsystems (monitor.numerics calibration tables) reuse the whole
    read/validate/publish discipline under their own format tag."""
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), dict):
        raise ValueError("%s: not a tune-table document" % path)
    got = doc.get("format")
    if got != fmt:
        raise ValueError("%s: unknown format %r (want %r)" % (path, got, fmt))
    out = {}
    for key, ent in doc["entries"].items():
        if not (isinstance(key, str) and key.count("|") == 2
                and isinstance(ent, dict)
                and isinstance(ent.get("config"), dict)):
            raise ValueError("%s: malformed entry %r" % (path, key))
        out[key] = ent
    return out


def read_entries(path: Optional[str],
                 fmt: str = FORMAT) -> Optional[Dict[str, dict]]:
    """Entries of the table file at ``path`` (mtime-cached), or None when
    the file is absent OR corrupt — corruption is logged ONCE per file and
    counted, never raised (lookups fall through to the next layer)."""
    if not path:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    sig = (st.st_mtime_ns, st.st_size, fmt)
    with _lock:
        cached = _file_cache.get(path)
        if cached is not None and cached[0] == sig:
            return cached[1]
    entries: Optional[Dict[str, dict]]
    try:
        with open(path) as f:
            entries = _valid_entries(json.load(f), path, fmt)
    except Exception as e:
        entries = None
        if _mx._enabled:
            _m_errors.inc()
        with _lock:
            if path not in _warned_paths:
                _warned_paths.add(path)
                _log.warning(
                    "paddle_tpu.tune: ignoring unreadable/corrupt config "
                    "table %s (%s: %s) — falling back to shipped/default "
                    "configs. Re-run tools/autotune.py to rebuild it.",
                    path, type(e).__name__, e)
    with _lock:
        _file_cache[path] = (sig, entries)
    return entries


def write_entries(path: str, entries: Dict[str, dict],
                  fmt: str = FORMAT) -> str:
    """Atomically publish ``entries`` as the table at ``path`` (tmp file +
    ``os.replace`` in the same directory, so readers only ever see a
    complete document)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    doc = {"format": fmt, "entries": entries}
    tmp = os.path.join(d, ".%s.tmp.%d" % (os.path.basename(path), os.getpid()))
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    if _mx._enabled:
        _m_writes.inc()
    with _lock:
        # a rebuilt table supersedes any remembered corruption
        _warned_paths.discard(path)
        _file_cache.pop(path, None)
    return path


def record(kernel: str, bucket: str, config: dict, *,
           device: Optional[str] = None, median_ms: Optional[float] = None,
           note: Optional[str] = None,
           path: Optional[str] = None) -> Optional[str]:
    """Merge one tuned entry into the runtime table (read-modify-write,
    atomic publish). Returns the table path, or None when no table location
    is configured (no env var, no compile cache — nothing to persist to)."""
    path = path or table_path()
    if not path:
        return None
    dev = device or device_kind()
    ent: Dict[str, Any] = {"config": dict(config), "source": "tuned"}
    if median_ms is not None:
        ent["median_ms"] = round(float(median_ms), 6)
    if note:
        ent["note"] = str(note)
    with _lock:
        entries = dict(read_entries(path) or {})
        entries[entry_key(kernel, bucket, dev)] = ent
        return write_entries(path, entries)


# -- lookup -------------------------------------------------------------------


def _note(kernel: str, source: str, bucket: str, device: str,
          config: Optional[dict]) -> None:
    with _lock:
        _provenance[kernel] = {"source": source, "bucket": bucket,
                               "device": device,
                               "config": dict(config) if config else None}


def lookup(kernel: str, bucket: str, device: Optional[str] = None,
           table_file: Optional[str] = None) -> Tuple[Optional[dict], str]:
    """``(config, source)`` for ``(kernel, bucket, device)``.

    Precedence: runtime table exact bucket, runtime wildcard, shipped
    exact, shipped wildcard, then ``(None, "default")``. NEVER raises —
    any failure (corrupt file, bad env, no backend) degrades to the
    default answer, because this is called from trace-time kernel-config
    hooks inside training runs.
    """
    try:
        dev = device or device_kind()
        if _mx._enabled:
            _m_lookups.inc()
        layers = (("tuned", read_entries(table_file or table_path())),
                  ("shipped", read_entries(shipped_path())))
        for source, entries in layers:
            if not entries:
                continue
            for b in (bucket, WILDCARD_BUCKET):
                ent = entries.get(entry_key(kernel, b, dev))
                if ent is not None:
                    cfg = dict(ent["config"])
                    _note(kernel, source, b, dev, cfg)
                    if _mx._enabled:
                        _m_by_src[source].inc()
                    return cfg, source
        _note(kernel, "default", bucket, dev, None)
        if _mx._enabled:
            _m_by_src["default"].inc()
        return None, "default"
    except Exception as e:  # pragma: no cover - belt and braces
        _log.warning("paddle_tpu.tune: lookup(%s,%s) failed (%s: %s); "
                     "using default config", kernel, bucket,
                     type(e).__name__, e)
        return None, "default"


def resolve_decode_fuse(slots: int) -> Tuple[int, str]:
    """(decode_fuse, source) for a serving engine with ``slots`` batch
    slots — THE shared resolution ``ServingConfig(decode_fuse="auto")``
    and ``tools/serve_bench`` both use, so the value the bench reports is
    by construction the value the engine runs. (1, "default") on no entry
    or any table failure: serving must come up even with a corrupt table."""
    try:
        cfg, src = lookup("serving.decode_fuse", bucket_slots(slots))
        if cfg and int(cfg.get("decode_fuse", 0)) > 0:
            return int(cfg["decode_fuse"]), src
    except Exception:
        pass
    return 1, "default"


def resolve_speculation_k(slots: int) -> Tuple[int, str]:
    """(draft k, source) for speculative decoding on a serving engine with
    ``slots`` batch slots — THE shared resolution
    ``ServingConfig(speculation="auto")`` and ``tools/serve_bench`` both
    use, mirroring :func:`resolve_decode_fuse`. The useful k trades
    verify-window compute against acceptance decay, so it is measured per
    (slot bucket, device kind) by ``tools/autotune.py --kernel
    speculation_k``. (4, "default") on no entry or any table failure:
    serving must come up even with a corrupt table."""
    try:
        cfg, src = lookup("serving.speculation_k", bucket_slots(slots))
        if cfg and int(cfg.get("k", 0)) > 0:
            return int(cfg["k"]), src
    except Exception:
        pass
    return 4, "default"


def resolve_fleet_router(cpus: Optional[int] = None
                         ) -> Tuple[Dict[str, object], str]:
    """(router config, source) for the fleet router — THE shared
    resolution ``fleet.FleetConfig(replicas="auto")`` and
    ``tools/fleet_bench`` both use. The config dict carries ``replicas``
    (int) and ``affinity`` (``"prefix"``/``"round_robin"``), bucketed by
    host CPU count (replica workers are processes — the useful count
    tracks cores, not devices). ``({"replicas": 2, "affinity": "prefix"},
    "default")`` on no entry or any table failure: the fleet must come up
    with no table on disk."""
    default = {"replicas": 2, "affinity": "prefix"}
    try:
        if cpus is None:
            cpus = os.cpu_count() or 1
        cfg, src = lookup("fleet.router", bucket_slots(int(cpus)))
        if cfg and int(cfg.get("replicas", 0)) > 0:
            out = {"replicas": int(cfg["replicas"]),
                   "affinity": cfg.get("affinity", "prefix")}
            if out["affinity"] in ("prefix", "round_robin"):
                return out, src
    except Exception:
        pass
    return default, "default"


def resolve_fleet_roles(cpus: Optional[int] = None
                        ) -> Tuple[Dict[str, int], str]:
    """(role mix, source) for a disaggregated fleet — THE shared
    resolution ``fleet.FleetConfig(roles="auto")`` and
    ``tools/fleet_bench`` both use. The config dict carries ``prefill``
    and ``decode`` (replica counts per role), bucketed by host CPU count
    like ``fleet.router``. ``({"prefill": 1, "decode": 1}, "default")``
    on no entry or any table failure: a role-split fleet must come up
    with no table on disk."""
    default = {"prefill": 1, "decode": 1}
    try:
        if cpus is None:
            cpus = os.cpu_count() or 1
        cfg, src = lookup("fleet.roles", bucket_slots(int(cpus)))
        if cfg and int(cfg.get("prefill", 0)) > 0 \
                and int(cfg.get("decode", 0)) > 0:
            return ({"prefill": int(cfg["prefill"]),
                     "decode": int(cfg["decode"])}, src)
    except Exception:
        pass
    return default, "default"


def provenance_snapshot() -> Dict[str, dict]:
    """Per-kernel record of the most recent lookup's answer — the bench
    summary tail's ``autotune`` section evidence."""
    with _lock:
        return {k: dict(v) for k, v in _provenance.items()}


def reset_provenance() -> None:
    with _lock:
        _provenance.clear()
