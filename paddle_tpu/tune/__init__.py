"""paddle_tpu.tune — measured autotuning over the knobs we used to hand-tune.

The repo's two largest single wins were found by hand: the v5e
flash-attention BlockSizes sweep (3.57x over composed at S=8192) and the
trace-time pass-gate pipeline. This subsystem turns that manual loop into
infrastructure (TVM's measured schedule search, PAPERS.md):

* :mod:`~paddle_tpu.tune.table` — persistent config table keyed
  ``(kernel, shape-bucket, device_kind)``: runtime JSON next to the
  persistent compile cache, a checked-in ``shipped.json`` seeded with the
  hand-tuned v5e entries, hardcoded defaults as the final fallback. Corrupt
  tables log once and fall back — never crash a run.
* :mod:`~paddle_tpu.tune.search` — the measured search driver: analytic
  VMEM pruning, warmup + median-of-k timing with compile excluded,
  ``autotune/*`` counters, atomic table writes.
* :mod:`~paddle_tpu.tune.tunables` — the registered knobs: flash
  BlockSizes, sparse-adam row blocks, softmax-xent tiles, per-program
  pass gates (end-to-end measured), serving ``decode_fuse``.

Entry points: ``tools/autotune.py`` (sweep + write + before/after table);
``ops/attention_ops._tuned_block_sizes``, ``sparse_adam._block_size`` and
the softmax-xent tile choice consult :func:`lookup` at trace time;
``ServingConfig(decode_fuse="auto")`` does the same for serving.
"""

from .table import (  # noqa: F401
    bucket_ctx,
    bucket_nv,
    bucket_rows,
    bucket_seq,
    bucket_slots,
    device_kind,
    lookup,
    normalize_device_kind,
    pow2_floor,
    provenance_snapshot,
    record,
    reset_provenance,
    resolve_decode_fuse,
    resolve_fleet_roles,
    resolve_fleet_router,
    resolve_speculation_k,
    shipped_path,
    table_path,
)
from .search import SearchResult, median_time_ms, search  # noqa: F401

__all__ = [
    "bucket_ctx", "bucket_nv", "bucket_rows", "bucket_seq", "bucket_slots",
    "device_kind", "normalize_device_kind", "pow2_floor",
    "lookup", "record", "table_path", "shipped_path",
    "resolve_decode_fuse", "resolve_fleet_roles", "resolve_fleet_router",
    "resolve_speculation_k",
    "provenance_snapshot", "reset_provenance",
    "SearchResult", "median_time_ms", "search",
    "Tunable", "register_tunable", "get_tunable", "registered_tunables",
]


def __getattr__(name):
    # tunables pull in ops/serving/passes machinery — load them only when
    # someone actually asks for the registry (the CLI, tests), keeping
    # `import paddle_tpu.tune` cheap for the trace-time lookup path
    if name in ("Tunable", "register_tunable", "get_tunable",
                "registered_tunables"):
        from . import tunables as _t

        return getattr(_t, name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
