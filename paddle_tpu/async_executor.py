"""AsyncExecutor — multi-threaded file-fed training (reference:
python/paddle/fluid/async_executor.py:33 + framework/async_executor.h:60,
data_feed.h:49 MultiSlotDataFeed, executor_thread_worker.h).

TPU-native redesign: the reference spawns N CPU trainer threads each with
its own scope, racing optimizer updates Hogwild-style. A TPU chip is one
fast SIMD core — racing updates buy nothing. So the N threads here do what
actually parallelizes on the host: file reading + MultiSlot text parsing +
batch assembly, feeding a bounded queue; the single XLA stream consumes
batches in order. Same API (run(program, data_feed, filelist, thread_num,
fetch, debug)), same MultiSlot on-disk format, deterministic updates
instead of racy ones.

Sparse (variable-length) slots batch to the framework's padded+Length
convention: ``<name>`` [B, Lmax] int64 padded with 0 + ``<name>_length``
[B] — the LoD replacement used across the framework (ops/sequence_ops.py).
Dense slots batch to [B, dim] float32.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional, Sequence

import numpy as np

from .core.framework import Program, default_main_program
from .core.place import Place
from .core.scope import global_scope
from .data_feed_desc import DataFeedDesc
from .executor import Executor

__all__ = ["AsyncExecutor"]


def _parse_multislot_line(line: str, slots):
    """One MultiSlot text line: per slot, <count> then <count> values
    (reference: data_feed.cc MultiSlotDataFeed::ParseOneInstance)."""
    toks = line.split()
    pos = 0
    inst = []
    for s in slots:
        if pos >= len(toks):
            raise ValueError("MultiSlot line ended early at slot %r" % s.name)
        n = int(toks[pos])
        pos += 1
        vals = toks[pos:pos + n]
        if len(vals) != n:
            raise ValueError("slot %r declares %d values, found %d"
                             % (s.name, n, len(vals)))
        pos += n
        if s.type.startswith("uint") or s.type.startswith("int"):
            inst.append(np.asarray([int(v) for v in vals], np.int64))
        else:
            inst.append(np.asarray([float(v) for v in vals], np.float32))
    return inst


def _batch_to_feed(batch, slots):
    feed = {}
    for i, s in enumerate(slots):
        if not s.is_used:
            continue
        col = [inst[i] for inst in batch]
        if s.is_dense:
            feed[s.name] = np.stack(col).astype(
                np.float32 if s.type.startswith("float") else np.int64)
        else:
            lens = np.asarray([len(c) for c in col], np.int64)
            lmax = max(1, int(lens.max()))
            padded = np.zeros((len(col), lmax), col[0].dtype)
            for r, c in enumerate(col):
                padded[r, :len(c)] = c
            feed[s.name] = padded
            feed[s.name + "_length"] = lens
    return feed


class AsyncExecutor:
    """reference: async_executor.py:33."""

    def __init__(self, place: Optional[Place] = None, run_mode: str = ""):
        self.place = place
        self._exe = Executor(place)

    def run(self, program: Optional[Program], data_feed: DataFeedDesc,
            filelist: Sequence[str], thread_num: int, fetch, mode: str = "",
            debug: bool = False):
        """Train over every file in ``filelist`` with ``thread_num`` parser
        threads. Returns the list of fetched values per batch (the reference
        prints them with debug=True; we both print and return)."""
        if program is None:
            program = default_main_program()
        if isinstance(fetch, str):
            fetch = [fetch]
        if isinstance(filelist, str):
            with open(filelist) as f:
                filelist = [l.strip() for l in f if l.strip()]
        thread_num = max(1, int(thread_num))
        slots = data_feed.slots
        bs = data_feed.batch_size

        files_q: queue.Queue = queue.Queue()
        for fn in filelist:
            files_q.put(fn)
        batches_q: queue.Queue = queue.Queue(maxsize=thread_num * 4)
        errors: List[BaseException] = []
        _END = object()

        def worker():
            try:
                while True:
                    try:
                        fn = files_q.get_nowait()
                    except queue.Empty:
                        return
                    batch = []
                    with open(fn) as f:
                        for line in f:
                            line = line.strip()
                            if not line:
                                continue
                            batch.append(_parse_multislot_line(line, slots))
                            if len(batch) == bs:
                                batches_q.put(_batch_to_feed(batch, slots))
                                batch = []
                    if batch:
                        batches_q.put(_batch_to_feed(batch, slots))
            except BaseException as e:  # surfaced to the caller
                errors.append(e)
            finally:
                batches_q.put(_END)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(thread_num)]
        for t in threads:
            t.start()

        results = []
        done = 0
        while done < thread_num:
            item = batches_q.get()
            if item is _END:
                done += 1
                continue
            vals = self._exe.run(program, feed=item, fetch_list=list(fetch))
            results.append(vals)
            if debug:
                print("AsyncExecutor:", {n: np.asarray(v).ravel()[:4]
                                         for n, v in zip(fetch, vals)})
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return results
