"""Static-graph autodiff API (reference: python/paddle/fluid/backward.py:394).

Fluid's ``append_backward`` walks the forward ops in reverse and appends
per-op grad ops built by C++ GradOpMakers. The TPU-native equivalent keeps
the same API shape — it declares gradient variables (``p@GRAD``) and marks
the program — but the actual differentiation is done by ``jax.grad`` over the
traced forward function at compile time inside the Executor. That yields
XLA-fused backward code instead of an interpreted grad-op list, while user
code (optimizers reading ``param_to_grad``) is unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .core.framework import Parameter, Program, Variable, grad_var_name

__all__ = ["append_backward", "gradients", "calc_gradient"]


def _find_trainable_params(program: Program, parameter_list, no_grad_set) -> List[Parameter]:
    if parameter_list:
        names = set(p.name if isinstance(p, Variable) else p for p in parameter_list)
        params = [p for p in program.all_parameters() if p.name in names]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    if no_grad_set:
        no_grad = set(v.name if isinstance(v, Variable) else v for v in no_grad_set)
        params = [p for p in params if p.name not in no_grad]
    return params


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[set] = None,
    callbacks=None,
) -> List[Tuple[Parameter, Variable]]:
    """Mark the program for differentiation; returns [(param, grad_var), ...].

    The returned grad vars are bound at execution: the Executor computes
    ``jax.grad`` of the loss wrt each param and materializes the results
    under the ``p@GRAD`` names, so downstream ops (optimizers, grad clip,
    regularizers — which the Optimizer layer appends *after* the marker) see
    exactly what Fluid's appended grad ops would have produced.
    """
    program = loss.block.program
    block = program.global_block
    if program._backward_info is not None:
        raise RuntimeError("append_backward called twice on the same program")

    params = _find_trainable_params(program, parameter_list, no_grad_set)
    param_to_grad: Dict[str, str] = {}
    param_grads: List[Tuple[Parameter, Variable]] = []
    for p in params:
        gname = grad_var_name(p.name)
        gvar = block.create_var(name=gname, shape=p.shape, dtype=p.dtype, stop_gradient=True)
        param_to_grad[p.name] = gname
        param_grads.append((p, gvar))

    loss_grad = block.create_var(
        name=grad_var_name(loss.name), shape=loss.shape, dtype=loss.dtype, stop_gradient=True
    )
    block.append_op(
        "backward_marker",
        inputs={"Loss": loss},
        outputs={"ParamGrads": [g for _, g in param_grads]},
        attrs={"loss": loss.name, "param_to_grad": dict(param_to_grad)},
    )
    program._backward_info = {"loss": loss.name, "param_to_grad": param_to_grad}
    return param_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """jax.grad-backed replacement for fluid.gradients (backward.py:613)."""
    raise NotImplementedError(
        "gradients() for arbitrary targets is provided via Executor fetch of "
        "@GRAD vars after append_backward; arbitrary-var grads land with the "
        "inference/export milestone."
    )


calc_gradient = gradients
