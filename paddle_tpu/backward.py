"""Static-graph autodiff API (reference: python/paddle/fluid/backward.py:394).

Fluid's ``append_backward`` walks the forward ops in reverse and appends
per-op grad ops built by C++ GradOpMakers. The TPU-native equivalent keeps
the same API shape — it declares gradient variables (``p@GRAD``) and marks
the program — but the actual differentiation is done by ``jax.grad`` over the
traced forward function at compile time inside the Executor. That yields
XLA-fused backward code instead of an interpreted grad-op list, while user
code (optimizers reading ``param_to_grad``) is unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .core.framework import Parameter, Program, Variable, grad_var_name

__all__ = ["append_backward", "gradients", "calc_gradient"]


def _find_trainable_params(program: Program, parameter_list, no_grad_set) -> List[Parameter]:
    if parameter_list:
        names = set(p.name if isinstance(p, Variable) else p for p in parameter_list)
        params = [p for p in program.all_parameters() if p.name in names]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    if no_grad_set:
        no_grad = set(v.name if isinstance(v, Variable) else v for v in no_grad_set)
        params = [p for p in params if p.name not in no_grad]
    return params


def _unique_grad_name(block, base: str) -> str:
    """A grad var name not yet taken in ``block`` (an earlier gradients()
    call may already have claimed ``x@GRAD``; silently aliasing the two would
    make one overwrite the other at execution)."""
    name, k = base, 0
    while block.has_var(name):
        k += 1
        name = "%s@RENAME@%d" % (base, k)
    return name


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[set] = None,
    callbacks=None,
) -> List[Tuple[Parameter, Variable]]:
    """Mark the program for differentiation; returns [(param, grad_var), ...].

    The returned grad vars are bound at execution: the Executor computes
    ``jax.grad`` of the loss wrt each param and materializes the results
    under the ``p@GRAD`` names, so downstream ops (optimizers, grad clip,
    regularizers — which the Optimizer layer appends *after* the marker) see
    exactly what Fluid's appended grad ops would have produced.
    """
    program = loss.block.program
    block = program.global_block
    if program._backward_info is not None:
        raise RuntimeError("append_backward called twice on the same program")

    params = _find_trainable_params(program, parameter_list, no_grad_set)
    param_to_grad: Dict[str, str] = {}
    param_grads: List[Tuple[Parameter, Variable]] = []
    for p in params:
        gname = _unique_grad_name(block, grad_var_name(p.name))
        gvar = block.create_var(name=gname, shape=p.shape, dtype=p.dtype, stop_gradient=True)
        param_to_grad[p.name] = gname
        param_grads.append((p, gvar))

    loss_grad_name = _unique_grad_name(block, grad_var_name(loss.name))
    loss_grad = block.create_var(
        name=loss_grad_name, shape=loss.shape, dtype=loss.dtype, stop_gradient=True
    )
    block.append_op(
        "backward_marker",
        inputs={"Loss": loss},
        outputs={"ParamGrads": [g for _, g in param_grads]},
        attrs={"loss": loss.name, "param_to_grad": dict(param_to_grad)},
    )
    program._backward_info = {
        "loss": loss.name,
        "param_to_grad": param_to_grad,
        "loss_grad": loss_grad_name,
    }
    return param_grads


def _as_var_list(x) -> List[Variable]:
    if isinstance(x, Variable):
        return [x]
    return list(x)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None) -> List[Variable]:
    """Compute d(targets)/d(inputs) (reference: backward.py:613 calc_gradient).

    Appends a ``calc_gradient`` op (ops/gradient_ops.py) that jax.vjp's the
    traced forward prefix at execution time. Returns one grad Variable per
    input; fetch them (or feed them onward) like any other var. May be called
    multiple times per program — each call differentiates the ops appended so
    far, so GAN-style per-loss gradients and double-grad (a later call whose
    prefix contains an earlier marker) both work.

    ``target_gradients`` seeds the vjp per target (default: ones, like the
    reference). ``no_grad_set`` names are treated as stop_gradient.
    Inputs with no path to any target yield zeros (the reference returns
    None; a traced program cannot know reachability per-element at trace
    time, so zeros are the functional equivalent).
    """
    targets = _as_var_list(targets)
    inputs = _as_var_list(inputs)
    if not targets or not inputs:
        raise ValueError("gradients() needs at least one target and one input")
    program = targets[0].block.program
    block = program.global_block

    if target_gradients is None:
        tg_list: List[Optional[Variable]] = [None] * len(targets)
    else:
        tg_list = _as_var_list(target_gradients)
        if len(tg_list) != len(targets):
            raise ValueError(
                "target_gradients must match targets: got %d vs %d"
                % (len(tg_list), len(targets)))
    no_grad_names = sorted(
        v.name if isinstance(v, Variable) else str(v) for v in (no_grad_set or ()))

    # Dedup repeated inputs: the env is keyed by name, so each name is one
    # leaf; duplicates share the grad var (the reference returns the same
    # gradient for each occurrence too).
    grad_by_name: Dict[str, Variable] = {}
    unique_inputs: List[Variable] = []
    for v in inputs:
        if v.name in grad_by_name:
            continue
        if v.dtype is not None and not str(v.dtype).startswith(("float", "bfloat")):
            raise TypeError(
                "gradients() input %r has non-differentiable dtype %s"
                % (v.name, v.dtype))
        gname = _unique_grad_name(block, grad_var_name(v.name))
        grad_by_name[v.name] = block.create_var(
            name=gname, shape=v.shape, dtype=v.dtype, stop_gradient=True)
        unique_inputs.append(v)
    grad_vars = [grad_by_name[v.name] for v in unique_inputs]

    op_inputs = {"Targets": targets, "Inputs": unique_inputs}
    tg_vars = [g for g in tg_list if g is not None]
    if tg_vars:
        op_inputs["TargetGradients"] = tg_vars
    block.append_op(
        "calc_gradient",
        inputs=op_inputs,
        outputs={"InputGrads": grad_vars},
        attrs={
            "targets": [t.name for t in targets],
            "inputs": [v.name for v in unique_inputs],
            "target_gradients": [g.name if g is not None else None for g in tg_list],
            "no_grad_set": no_grad_names,
        },
    )
    return [grad_by_name[v.name] for v in inputs]


calc_gradient = gradients
