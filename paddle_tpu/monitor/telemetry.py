"""Continuous metrics export: registry snapshots → bounded JSONL ring.

PR 1/5 made telemetry *readable* (``monitor.snapshot()``); a long-running
serving engine or supervised training job needs it *streamed*: a time
series an operator can tail, scrape, and alert on while the process is
still alive. :class:`TelemetryExporter` is that streamer — a background
thread that every ``interval_s``:

1. snapshots the registry and computes INTERVAL DELTAS vs the previous
   tick (counter increments, histogram bucket/count/sum deltas — the
   inputs rate and percentile alerting need),
2. appends one JSON line to a bounded on-disk ring
   (``PADDLE_TPU_TELEMETRY_DIR``; ``telemetry_<pid>_<k>.jsonl`` files
   rotated every ``PADDLE_TPU_TELEMETRY_ROTATE`` samples, oldest deleted
   past ``PADDLE_TPU_TELEMETRY_KEEP`` files; each append is flushed+fsynced
   so a crash loses at most the in-flight line),
3. hands the sample to registered listeners — the
   :mod:`~paddle_tpu.monitor.slo` monitor evaluates its specs here.

Lifecycle: the exporter is a REFCOUNTED process singleton.
``ServingEngine`` and ``run_supervised`` call :func:`acquire` on entry and
:func:`release` on exit; the first acquire starts the thread, the last
release stops it and flushes the final PARTIAL interval (so short drills
still produce a series). With ``PADDLE_TPU_TELEMETRY_DIR`` unset the whole
subsystem costs one env read — :func:`acquire` returns ``None``.

Failure policy mirrors the flight recorder: an unwritable telemetry dir
logs ONE error and disables the on-disk export — it never masks the run,
and in-memory listeners (SLO evaluation) keep working.

Prometheus: the same registry renders scrapeable text via
``monitor.to_prometheus()``; :meth:`TelemetryExporter.write_prometheus`
drops ``metrics.prom`` next to the ring on every tick for a file-based
scrape (node-exporter textfile-collector style).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import metrics as _mx

__all__ = [
    "TelemetryExporter", "TelemetrySample", "acquire", "release",
    "active_exporter", "force_tick", "read_series", "SAMPLE_SCHEMA",
]

SAMPLE_SCHEMA = "paddle_tpu.telemetry/v1"

_log = logging.getLogger("paddle_tpu")

_c_samples = _mx.counter(
    "telemetry/samples", help="telemetry ring samples written (or handed to "
                              "listeners when the dir is unwritable)")
_c_rotations = _mx.counter(
    "telemetry/rotations", help="telemetry ring file rotations")
_c_write_errors = _mx.counter(
    "telemetry/write_errors", help="telemetry ring write failures (first "
                                   "one disables the on-disk export)")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class TelemetrySample:
    """One export tick: the full snapshot plus interval deltas.

    ``deltas["counters"]`` maps counter name → increment since the
    previous tick (non-zero entries only); ``deltas["histograms"]`` maps
    histogram name → ``{"count", "sum", "buckets": {le_*: delta}}`` for
    histograms that saw observations this interval; ``deltas["gauges"]``
    maps gauge name → current value for gauges that CHANGED this interval
    (the snapshot value is the time-series point — the delta entry just
    flags movement for change-driven consumers like ``--watch``).
    """

    __slots__ = ("seq", "t", "dt_s", "metrics", "deltas")

    def __init__(self, seq: int, t: float, dt_s: float,
                 metrics: Dict[str, dict], deltas: Dict[str, dict]):
        self.seq = seq
        self.t = t
        self.dt_s = dt_s
        self.metrics = metrics
        self.deltas = deltas

    def to_doc(self) -> dict:
        # pid rides along so a consumer of a multi-process ring dir can
        # keep one monotone seq cursor per writer
        return {"schema": SAMPLE_SCHEMA, "seq": self.seq, "t": self.t,
                "dt_s": self.dt_s, "pid": os.getpid(),
                "deltas": self.deltas, "metrics": self.metrics}

    def counter_delta(self, name: str) -> float:
        return self.deltas.get("counters", {}).get(name, 0.0)

    def counter_rate(self, name: str) -> float:
        """Interval rate (delta / dt) — the QPS-style readout."""
        if self.dt_s <= 0:
            return 0.0
        return self.counter_delta(name) / self.dt_s

    def gauge_value(self, name: str) -> Optional[float]:
        """Current value of a GAUGE instrument — None for anything else
        (handing back a counter's lifetime total here would let a
        mis-typed ceiling SLO compare against cumulative history)."""
        snap = self.metrics.get(name)
        if snap is None or snap.get("type") != "gauge":
            return None
        return float(snap.get("value", 0.0))

    def histogram_delta(self, name: str) -> Optional[dict]:
        return self.deltas.get("histograms", {}).get(name)

    def histogram_interval_percentile(self, name: str, p: float
                                      ) -> Optional[float]:
        """Estimated p-th percentile of THIS interval's observations,
        interpolated over the bucket-count deltas (None when the
        histogram saw nothing this interval). The full bucket grid comes
        from the snapshot — delta dicts drop zero entries, and losing the
        empty buckets must not shrink the interpolation range."""
        d = self.histogram_delta(name)
        if not d or not d.get("count"):
            return None
        full = (self.metrics.get(name) or {}).get("buckets") or d["buckets"]
        bounds = sorted(_parse_le(k) for k in full)
        counts = {_parse_le(k): v for k, v in d["buckets"].items()}
        return _bucket_percentile(bounds, counts, p)

    def histogram_interval_mean(self, name: str) -> Optional[float]:
        """Mean of THIS interval's observations (delta sum / delta count;
        None when the histogram saw nothing this interval) — exact, no
        bucket interpolation, so the breach autopsy can rank replicas by
        the interval a breach actually fired in."""
        d = self.histogram_delta(name)
        if not d or not d.get("count"):
            return None
        return float(d.get("sum", 0.0)) / float(d["count"])


def _parse_le(key: str) -> float:
    if key == "le_inf":
        return float("inf")
    return float(key[3:])


def _bucket_percentile(bounds, counts: Dict[float, float],
                       p: float) -> float:
    """Linear-interpolated percentile over per-bucket interval counts on
    the histogram's FULL bound grid — the interval-windowed twin of
    ``Histogram.percentile``. A rank landing in the +Inf overflow bucket
    reports the largest finite bound of the grid (the interval kept no
    max; anything smaller would understate — and an SLO ceiling below
    that bound must breach, exactly the slow-death case)."""
    total = sum(counts.values())
    if total <= 0:
        return 0.0
    rank = max(1.0, total * min(max(p, 0.0), 100.0) / 100.0)
    largest_finite = max((b for b in bounds if b != float("inf")),
                         default=0.0)
    cum = 0.0
    prev_bound = 0.0
    for bound in bounds:
        c = counts.get(bound, 0)
        if c:
            if rank <= cum + c:
                if bound == float("inf"):
                    return largest_finite
                frac = (rank - cum) / c
                return prev_bound + (bound - prev_bound) * frac
            cum += c
        if bound != float("inf"):
            prev_bound = bound
    return largest_finite


def _counter_values(snap: Dict[str, dict]) -> Dict[str, float]:
    return {n: float(s.get("value", 0.0)) for n, s in snap.items()
            if s.get("type") == "counter"}


def _hist_state(snap: Dict[str, dict]) -> Dict[str, dict]:
    return {n: {"count": s.get("count", 0), "sum": s.get("sum", 0.0),
                "buckets": dict(s.get("buckets", {}))}
            for n, s in snap.items() if s.get("type") == "histogram"}


class TelemetryExporter:
    """The background snapshot→JSONL-ring thread (module docstring).

    Construct directly for tests/tools (``interval_s`` etc. override the
    env defaults); production surfaces go through :func:`acquire` /
    :func:`release` so one process shares one exporter.
    """

    def __init__(self, dirpath: str, interval_s: Optional[float] = None,
                 rotate_samples: Optional[int] = None,
                 keep_files: Optional[int] = None,
                 prometheus_file: Optional[bool] = None):
        self.dir = dirpath
        self.interval_s = (interval_s if interval_s is not None else
                           _env_float("PADDLE_TPU_TELEMETRY_INTERVAL_S", 1.0))
        self.rotate_samples = max(1, rotate_samples if rotate_samples
                                  is not None else
                                  _env_int("PADDLE_TPU_TELEMETRY_ROTATE", 512))
        self.keep_files = max(1, keep_files if keep_files is not None else
                              _env_int("PADDLE_TPU_TELEMETRY_KEEP", 4))
        self.prometheus_file = (
            prometheus_file if prometheus_file is not None
            else _env_int("PADDLE_TPU_TELEMETRY_PROM", 1) != 0)
        self.disabled = False        # disk export off after a write error
        self.closed = False
        self._refs = 0               # managed by module acquire()/release()
        self._listeners: List[Callable[[TelemetrySample], None]] = []
        self._lock = threading.Lock()       # tick serialization
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._file_idx = 0
        self._samples_in_file = 0
        # most recent tick, for consumers that need the latest interval
        # delta without re-ticking (flight-recorder dumps join on it)
        self.last_sample: Optional[TelemetrySample] = None
        snap = _mx.snapshot()
        self._prev_counters = _counter_values(snap)
        self._prev_hists = _hist_state(snap)
        self._prev_gauges = {n: float(d.get("value", 0.0))
                             for n, d in snap.items()
                             if d.get("type") == "gauge"}
        self._last_t = time.time()

    # -- listeners ------------------------------------------------------------
    def add_listener(self, fn: Callable[[TelemetrySample], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[TelemetrySample], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- the tick -------------------------------------------------------------
    def tick(self) -> TelemetrySample:
        """One export cycle: delta, append, rotate, notify. Public so
        tests and ``--watch`` tooling can drive ticks deterministically;
        the background thread calls exactly this."""
        with self._lock:
            now = time.time()
            snap = _mx.snapshot()
            counters = _counter_values(snap)
            hists = _hist_state(snap)
            gauges = {n: float(s.get("value", 0.0))
                      for n, s in snap.items() if s.get("type") == "gauge"}
            deltas: Dict[str, Any] = {"counters": {}, "histograms": {},
                                      "gauges": {}}
            for name, v in gauges.items():
                if v != self._prev_gauges.get(name):
                    deltas["gauges"][name] = v
            for name, v in counters.items():
                d = v - self._prev_counters.get(name, 0.0)
                if d < 0:
                    # counter went backwards = a mid-run metrics.reset():
                    # Prometheus rate() semantics — treat the current value
                    # as the whole interval's increment, never emit a
                    # negative delta (which would fake SLO breaches)
                    d = v
                if d:
                    deltas["counters"][name] = d
            for name, h in hists.items():
                prev = self._prev_hists.get(
                    name, {"count": 0, "sum": 0.0, "buckets": {}})
                if h["count"] < prev["count"] or any(
                        h["buckets"].get(k, 0) < c
                        for k, c in prev["buckets"].items()):
                    # a shrinking total OR any shrinking bucket = a mid-run
                    # metrics.reset(): restart the window from zero
                    prev = {"count": 0, "sum": 0.0, "buckets": {}}
                dc = h["count"] - prev["count"]
                if dc:
                    deltas["histograms"][name] = {
                        "count": dc,
                        "sum": h["sum"] - prev["sum"],
                        "buckets": {
                            k: v - prev["buckets"].get(k, 0)
                            for k, v in h["buckets"].items()
                            if v - prev["buckets"].get(k, 0)},
                    }
            self._seq += 1
            sample = TelemetrySample(self._seq, now,
                                     max(0.0, now - self._last_t),
                                     snap, deltas)
            self._prev_counters = counters
            self._prev_hists = hists
            self._prev_gauges = gauges
            self._last_t = now
            self.last_sample = sample
            self._write(sample)
            listeners = list(self._listeners)
        _c_samples.inc()
        for fn in listeners:
            try:
                fn(sample)
            except Exception:
                _log.exception("telemetry listener failed (ignored)")
        return sample

    # -- ring file management -------------------------------------------------
    def _path(self, idx: int) -> str:
        return os.path.join(self.dir,
                            "telemetry_%d_%06d.jsonl" % (os.getpid(), idx))

    def _write(self, sample: TelemetrySample) -> None:
        if self.disabled:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            if self._samples_in_file >= self.rotate_samples:
                self._file_idx += 1
                self._samples_in_file = 0
                _c_rotations.inc()
                self._prune()
            path = self._path(self._file_idx)
            with open(path, "a") as f:
                f.write(json.dumps(sample.to_doc(), default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._samples_in_file += 1
            if self.prometheus_file:
                # per-pid temp so concurrent multi-process exporters can't
                # interleave writes; each atomic replace publishes a
                # complete, self-consistent exposition (last writer wins)
                tmp = os.path.join(self.dir,
                                   ".metrics.prom.%d.tmp" % os.getpid())
                with open(tmp, "w") as f:
                    f.write(_mx.to_prometheus())
                os.replace(tmp, os.path.join(self.dir, "metrics.prom"))
        except OSError as e:
            # the flight-recorder rule: a broken telemetry dir must never
            # mask the run it observes — log once, keep listeners alive
            self.disabled = True
            _c_write_errors.inc()
            _log.error(
                "telemetry: cannot write to PADDLE_TPU_TELEMETRY_DIR=%r "
                "(%s) — on-disk export disabled for this exporter; SLO "
                "evaluation continues in-process", self.dir, e)

    def _prune(self) -> None:
        mine = sorted(glob.glob(
            os.path.join(self.dir, "telemetry_%d_*.jsonl" % os.getpid())))
        excess = len(mine) + 1 - self.keep_files  # +1: the file about to open
        for path in mine[:max(0, excess)]:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "TelemetryExporter":
        if self._thread is not None or self.closed:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    _log.exception("telemetry tick failed (ignored)")

        self._thread = threading.Thread(target=loop, name="tpu-telemetry",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, flush: bool = True) -> None:
        """Stop the thread; ``flush`` writes the final PARTIAL interval so
        activity since the last periodic tick is never lost."""
        if self.closed:
            return
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, 2 * self.interval_s))
            self._thread = None
        if flush:
            try:
                self.tick()
            except Exception:
                _log.exception("telemetry final flush failed (ignored)")
        self.closed = True

    # convenience: context manager for tests/tools
    def __enter__(self) -> "TelemetryExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- refcounted process singleton ---------------------------------------------

_singleton_lock = threading.Lock()
_exporter: Optional[TelemetryExporter] = None


def acquire() -> Optional[TelemetryExporter]:
    """Refcounted handle on the process exporter; ``None`` (one env read)
    when ``PADDLE_TPU_TELEMETRY_DIR`` is unset. The first acquire starts
    the thread; a second engine/supervisor in the same process shares it
    instead of double-starting. The refcount lives ON the exporter, so a
    mid-run dir change starts a fresh exporter for new acquirers while
    existing holders keep theirs alive until their own release."""
    d = os.environ.get("PADDLE_TPU_TELEMETRY_DIR", "").strip()
    if not d:
        return None
    global _exporter
    with _singleton_lock:
        if _exporter is None or _exporter.closed or _exporter.dir != d:
            _exporter = TelemetryExporter(d).start()
        _exporter._refs += 1
        return _exporter


def release(handle: Optional[TelemetryExporter]) -> None:
    """Drop one reference on ``handle``; the LAST release of an exporter
    stops its thread and flushes the final partial interval — even for an
    exporter superseded by a dir change, whose remaining holders keep
    receiving ticks until they release. ``release(None)`` is a no-op so
    callers can pair it unconditionally with :func:`acquire`."""
    if handle is None:
        return
    global _exporter
    with _singleton_lock:
        handle._refs -= 1
        if handle._refs > 0:
            return
        if handle is _exporter:
            _exporter = None
    handle.stop()


def active_exporter() -> Optional[TelemetryExporter]:
    return _exporter


def force_tick() -> Optional[TelemetrySample]:
    """Synchronously run one export tick on the live exporter (None when
    no exporter is active) — the deterministic hook tests and the SLO
    drills use instead of sleeping for the interval."""
    exp = _exporter
    return exp.tick() if exp is not None and not exp.closed else None


# -- read-back ----------------------------------------------------------------

def read_series(dirpath: str, pid: Optional[int] = None) -> List[dict]:
    """Load the JSONL ring back as a list of sample docs ordered by
    (file index, line order). ``pid=None`` reads every process's files
    (multi-process jobs write disjoint names). Torn trailing lines (a
    crash mid-append) are skipped, not fatal — the ring is a post-mortem
    artifact first."""
    pat = ("telemetry_%d_*.jsonl" % pid) if pid is not None \
        else "telemetry_*_*.jsonl"

    def _key(path):
        m = re.search(r"telemetry_(\d+)_(\d+)\.jsonl$", path)
        return (int(m.group(1)), int(m.group(2))) if m else (0, 0)

    out: List[dict] = []
    for path in sorted(glob.glob(os.path.join(dirpath, pat)), key=_key):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue  # torn tail line
                    if doc.get("schema") == SAMPLE_SCHEMA:
                        out.append(doc)
        except OSError:
            continue
    return out
