"""paddle_tpu.monitor.device — device-side profiling, attribution & post-mortem.

PR 1 made the *host* observable (metrics registry, span tracer); everything
past ``jax.jit`` stayed a black box: one opaque step span, a NaN report that
could only name a fetch after a full-model host copy, and collectives nobody
counted. This module is the device-side layer, four pieces:

1. **Per-op attribution** — the block interpreter wraps every op impl in
   ``jax.named_scope("<slot>:<type>")`` (``PADDLE_TPU_OP_SCOPES=0``
   disables), so lowered HLO, xprof device traces and
   ``compiled.cost_analysis()`` carry Program-op identity. ``<slot>`` is the
   op's position in the SOURCE program, frozen by
   ``passes.analysis.stamp_op_slots`` before the trace-time optimizer
   mutates the clone — DCE/CSE renumbering never shifts reported identities.
   The Executor's ``prepare``/AOT path (and ``PADDLE_TPU_DEVICE_PROFILE=1``
   on a compile miss) publishes ``cost_analysis()`` + ``memory_analysis()``
   of the compiled step as the ``device_profile/*`` gauges;
   ``tools/profile_report.py`` renders the per-op roofline table.

2. **In-graph numerics watchdog** — ``PADDLE_TPU_CHECK_NUMERICS``:
   ``0`` off; ``1`` the post-step check is ONE fused device-side
   ``isfinite`` reduction (a single scalar sync — replaces the legacy
   every-tensor-to-numpy scan, same error message); ``2`` compiles a
   guarded step variant where each op's floating outputs feed a per-op
   ``isfinite`` bit into one packed device-side mask fetched once per step,
   so a NaN/Inf is attributed to the ORIGINATING Program op by
   ``<slot>:<type>`` without per-tensor host syncs — including under the
   fused ``run_steps`` driver, where the mask comes back per fused step.
   ``FLAGS_check_nan_inf`` implies level >= 1.

3. **Collective traffic accounting** — the explicit collective emission
   sites (``parallel/pipeline.py`` / ``parallel/ring_attention.py``
   ppermutes, ``core/sparse.py`` all_to_alls) call
   :func:`record_collective` at TRACE time, so the
   ``collectives/<op>/bytes`` counters hold the per-device bytes ONE step
   moves through each compiled program (reset before measuring; a
   recompile records again). GSPMD-inserted collectives (dp grad
   all-reduce etc.) are not visible here — they show up in xprof and the
   ``device_profile`` totals instead.

4. **Flight recorder** — with ``PADDLE_TPU_FLIGHT_DIR`` set, the Executor
   records a ring buffer of the last N steps (feed shapes/dtypes, program
   fingerprint, opt-pass gate set, metrics snapshot) and dumps it as JSON
   on any step/tracing failure (EnforceNotMet included) for post-mortem
   debugging. Off (the default) it costs one attribute load per run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import metrics as _mx

__all__ = [
    "op_scopes_enabled", "numerics_level", "profile_enabled",
    "compiled_analysis", "publish_compiled_analysis", "memory_report_from",
    "program_op_costs", "step_report", "op_scope_coverage",
    "lowered_scope_text",
    "check_numerics_mask",
    "record_collective", "collectives_snapshot",
    "FlightRecorder", "flight_recorder", "program_fingerprint",
]

def _env_on(name: str, default: str) -> bool:
    return os.environ.get(name, default).strip().lower() not in (
        "0", "false", "no", "off", "")


def op_scopes_enabled() -> bool:
    """``PADDLE_TPU_OP_SCOPES`` (default ON): wrap each op impl in
    ``jax.named_scope`` at trace time. Pure HLO metadata — zero per-step
    cost — so it is on by default; turn off only if scope names disturb
    an HLO-text-diffing workflow."""
    return _env_on("PADDLE_TPU_OP_SCOPES", "1")


def numerics_level() -> int:
    """``PADDLE_TPU_CHECK_NUMERICS`` clamped to 0..2 (module docstring);
    read per call so tests/REPLs can flip it without restarting."""
    raw = os.environ.get("PADDLE_TPU_CHECK_NUMERICS", "0").strip()
    try:
        lvl = int(raw)
    except ValueError:
        lvl = 1 if raw.lower() in ("true", "yes", "on") else 0
    return max(0, min(2, lvl))


def profile_enabled() -> bool:
    """``PADDLE_TPU_DEVICE_PROFILE=1``: publish cost/memory analysis gauges
    on every Executor compile miss (pays an extra lower+compile per
    specialization — debug opt-in). ``Executor.prepare`` publishes them
    unconditionally: it compiled AOT anyway."""
    return _env_on("PADDLE_TPU_DEVICE_PROFILE", "0")


def raw_device_kind() -> str:
    """``device_kind`` of the default backend's first device (e.g.
    ``"TPU v5 lite"``, ``"cpu"``) — the microarchitecture identity that
    keys tuned kernel configs (paddle_tpu.tune normalizes it)."""
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


# -- 1. compiled-step cost/memory attribution ---------------------------------

_g_flops = _mx.gauge("device_profile/flops",
                     help="XLA cost_analysis flops of the last analyzed "
                          "compiled step")
_g_bytes = _mx.gauge("device_profile/bytes_accessed",
                     help="XLA cost_analysis bytes accessed (HBM traffic "
                          "estimate) of the last analyzed compiled step")
_g_arg_b = _mx.gauge("device_profile/argument_bytes",
                     help="memory_analysis argument buffer bytes")
_g_out_b = _mx.gauge("device_profile/output_bytes",
                     help="memory_analysis output buffer bytes")
_g_tmp_b = _mx.gauge("device_profile/temp_bytes",
                     help="memory_analysis temp (scratch) buffer bytes")
_g_peak = _mx.gauge("device_profile/peak_hbm_bytes",
                    help="argument+output+temp-alias bytes: the compiled "
                         "step's peak device-memory footprint")
_c_analyses = _mx.counter("device_profile/analyses",
                          help="compiled-step cost/memory analyses published")


def _cost_dict(executable) -> Dict[str, float]:
    try:
        ca = executable.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        v = ca.get(key)
        if v is not None:
            out[name] = float(v)
    return out


def _memory_dict(executable) -> Dict[str, float]:
    try:
        ma = executable.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    g = lambda k: float(getattr(ma, k, 0) or 0)
    out = {
        "argument_bytes": g("argument_size_in_bytes"),
        "output_bytes": g("output_size_in_bytes"),
        "temp_bytes": g("temp_size_in_bytes"),
        "alias_bytes": g("alias_size_in_bytes"),
        "generated_code_bytes": g("generated_code_size_in_bytes"),
    }
    out["peak_hbm_bytes"] = max(
        0.0, out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        - out["alias_bytes"])
    return out


def compiled_analysis(executable) -> Dict[str, Any]:
    """``{"cost": {...}, "memory": {...}}`` from a jax AOT-compiled
    executable (``lowered.compile()`` result). Backend gaps (a runtime
    without one of the analyses) yield empty sub-dicts, never a raise."""
    return {"cost": _cost_dict(executable), "memory": _memory_dict(executable)}


def publish_compiled_analysis(executable) -> Dict[str, Any]:
    """Mirror :func:`compiled_analysis` into the ``device_profile/*``
    gauges (last-analyzed-step semantics, like the pass-pipeline gauges)."""
    rep = compiled_analysis(executable)
    if _mx._enabled:
        cost, mem = rep["cost"], rep["memory"]
        if "flops" in cost:
            _g_flops.set(cost["flops"])
        if "bytes_accessed" in cost:
            _g_bytes.set(cost["bytes_accessed"])
        if mem:
            _g_arg_b.set(mem["argument_bytes"])
            _g_out_b.set(mem["output_bytes"])
            _g_tmp_b.set(mem["temp_bytes"])
            _g_peak.set(mem["peak_hbm_bytes"])
        if cost or mem:
            _c_analyses.inc()
    return rep


def memory_report_from(executable) -> Dict[str, float]:
    """The authoritative pre-run memory figure for a compiled step —
    what ``contrib.utils.memory_usage``'s docstring defers to."""
    return _memory_dict(executable) if executable is not None else {}


# -- analytic per-op cost table (the roofline rows) ---------------------------

# fwd flop-per-output-element factors for ops that aren't a plain map;
# everything absent costs 1 flop/element (elementwise) — these are
# first-order attribution weights, not a simulator.
_FLOPS_PER_ELEM = {
    "softmax": 5.0, "log_softmax": 5.0, "layer_norm": 8.0,
    "softmax_with_cross_entropy": 6.0, "cross_entropy": 2.0,
    "batch_norm": 4.0, "gelu": 8.0, "tanh": 4.0, "sigmoid": 4.0,
    "exp": 2.0, "log": 2.0, "sqrt": 2.0, "rsqrt": 2.0, "pow": 2.0,
    "dropout": 2.0,
}
_ZERO_FLOP_OPS = frozenset({
    "reshape", "reshape2", "transpose", "transpose2", "concat", "stack",
    "split", "slice", "assign", "cast", "fill_constant", "shape",
    "lookup_table", "gather", "one_hot", "squeeze", "squeeze2",
    "unsqueeze", "unsqueeze2", "flatten", "flatten2", "expand",
})


def _numel(shape, batch_size) -> int:
    n = 1
    for d in shape or ():
        if d is None:
            continue
        n *= batch_size * (-d) if d < 0 else d
    return n


def _var_bytes(block, name, batch_size) -> int:
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return 0
    from ..core.dtypes import to_jnp_dtype

    try:
        import numpy as np

        itemsize = np.dtype(to_jnp_dtype(v.dtype)).itemsize
    except Exception:
        itemsize = 4
    return _numel(v.shape, batch_size) * itemsize


def _op_flops(op, block, batch_size) -> float:
    """First-order forward flops for one symbolic op from static shapes."""
    t = op.type
    if t in _ZERO_FLOP_OPS:
        return 0.0
    outs = op.output_arg_names
    out_elems = 0
    for n in outs:
        v = block._find_var_recursive(n)
        if v is not None and v.shape is not None:
            out_elems = max(out_elems, _numel(v.shape, batch_size))
    if t in ("mul", "matmul", "matmul_v2"):
        # 2*M*K*N: out elems (M*N) times 2K from the contracted dim
        xn = op.inputs.get("X") or []
        k = 0
        if xn:
            xv = block._find_var_recursive(xn[0])
            if xv is not None and xv.shape:
                k = abs(xv.shape[-1] or 0)
        return 2.0 * out_elems * max(k, 1)
    if t in ("conv2d", "depthwise_conv2d"):
        wn = op.inputs.get("Filter") or []
        per_out = 1
        if wn:
            wv = block._find_var_recursive(wn[0])
            if wv is not None and wv.shape and len(wv.shape) == 4:
                _, cin, kh, kw = wv.shape
                per_out = 2 * abs(cin or 1) * abs(kh or 1) * abs(kw or 1)
        return float(out_elems * per_out)
    if t == "scaled_dot_product_attention":
        # 4*B*H*S^2*D ≈ 4 * out_elems * S (out is [B, S, H*D])
        xn = op.inputs.get("Q") or op.inputs.get("X") or []
        s = 1
        if xn:
            xv = block._find_var_recursive(xn[0])
            if xv is not None and xv.shape and len(xv.shape) >= 2:
                s = abs(xv.shape[-2] or 1) or 1
        return 4.0 * out_elems * s
    if t.startswith("reduce_") or t in ("mean", "sum"):
        ins = op.input_arg_names
        in_elems = max((_numel(getattr(block._find_var_recursive(n), "shape",
                                       None), batch_size)
                        for n in ins), default=out_elems)
        return float(in_elems)
    return _FLOPS_PER_ELEM.get(t, 1.0) * out_elems


def program_op_costs(program, batch_size: int = 1) -> List[Dict[str, Any]]:
    """Analytic per-op flops/bytes rows for block 0 from static var shapes
    (``-1`` batch dims substituted with ``batch_size``).

    These are ATTRIBUTION WEIGHTS — the measured truth is the compiled
    step's aggregate ``cost_analysis`` (XLA fuses across ops); the rows
    apportion that total over Program ops, and ``intensity`` (flops/byte)
    says which side of the roofline each op lives on. Rows carry the
    stable ``slot`` identity (``__op_slot__`` when stamped, position
    otherwise) matching named scopes and watchdog reports."""
    from ..core.interpreter import SKIP_OPS

    block = program.global_block
    rows: List[Dict[str, Any]] = []
    for i, op in enumerate(block.ops):
        if op.type in SKIP_OPS:
            continue
        flops = _op_flops(op, block, batch_size)
        nbytes = sum(_var_bytes(block, n, batch_size)
                     for n in op.input_arg_names)
        nbytes += sum(_var_bytes(block, n, batch_size)
                      for n in op.output_arg_names)
        rows.append({
            "slot": int(op.attrs.get("__op_slot__", i)),
            "type": op.type,
            "out": (op.output_arg_names or [""])[0],
            "flops": float(flops),
            "bytes": float(nbytes),
            "intensity": float(flops) / nbytes if nbytes else 0.0,
        })
    return rows


def step_report(program, executable=None, batch_size: int = 1,
                top: int = 0) -> Dict[str, Any]:
    """The JSON ``device_profile`` section: measured compiled totals
    (when ``executable`` is a jax AOT executable) + analytic per-op rows
    sorted by flops. ``top`` truncates the row list (0 = all)."""
    rows = sorted(program_op_costs(program, batch_size),
                  key=lambda r: -r["flops"])
    total_f = sum(r["flops"] for r in rows) or 1.0
    for r in rows:
        r["flops_frac"] = round(r["flops"] / total_f, 4)
    out: Dict[str, Any] = {
        "n_ops": len(rows),
        "analytic_total_flops": total_f,
        "op_costs": rows[:top] if top else rows,
    }
    if executable is not None:
        out.update(compiled_analysis(executable))
    return out


def lowered_scope_text(lowered) -> str:
    """Pre-optimization HLO/StableHLO text WITH scope metadata for a jax
    ``Lowered``. ``lowered.as_text()`` strips debug locations (and XLA's
    backend passes fuse most per-instruction metadata away from the
    compiled text), so the full-coverage artifact is the MLIR asm with
    debug info — every instruction's ``loc("...<slot>:<type>...")``."""
    try:
        return lowered.compiler_ir().operation.get_asm(
            enable_debug_info=True)
    except Exception:
        return lowered.as_text()


def op_scope_coverage(hlo_text: str) -> Dict[str, int]:
    """Parse HLO/MLIR text metadata for the ``<slot>:<type>`` named
    scopes: {scope label: instruction count}. Accepts compiled-HLO text
    (``executable.as_text()``, ``op_name="..."`` metadata — post-fusion,
    partial coverage) and :func:`lowered_scope_text` output
    (``loc("...")`` debug locations — full pre-optimization coverage).
    The presence/coverage check behind tests and ``profile_report``.
    Autodiff re-derives forward ops under ``jvp(<scope>)`` /
    ``transpose(jvp(<scope>))`` path segments — those count toward the
    same ``<slot>:<type>`` scope (it IS the same Program op's work)."""
    import re

    cov: Dict[str, int] = {}
    for m in re.finditer(r'(?:op_name="([^"]*)"|loc\("([^"]*)")', hlo_text):
        for seg in (m.group(1) or m.group(2)).split("/"):
            s = re.search(r"(?:^|\()(\d+:[A-Za-z0-9_.]+)\)*$", seg)
            if s:
                cov[s.group(1)] = cov.get(s.group(1), 0) + 1
    return cov


# -- 2. numerics watchdog (host side) -----------------------------------------

def check_numerics_mask(mask, layout: Sequence[Tuple[str, tuple]],
                        driver: str = "run") -> None:
    """Validate the packed per-op isfinite mask a guarded step fetched.

    ``mask``: bool [K] (one step) or [steps, K] (a fused run_steps chunk).
    ``layout``: the compiled step's trace-time record — entry k is
    ``(label, output names)`` for mask bit k. All-finite is one tiny
    device->host transfer and no further work; a failure walks the mask on
    host and raises EnforceNotMet naming the originating Program op."""
    import numpy as np

    arr = np.asarray(mask)  # the once-per-step sync (a few bytes)
    if arr.all():
        return
    from ..core.enforce import EnforceNotMet

    arr2 = arr.reshape(1, -1) if arr.ndim == 1 else arr
    bad = []
    for s in range(arr2.shape[0]):
        for k in np.flatnonzero(~arr2[s]):
            label, outs = (layout[k] if k < len(layout)
                           else ("?%d:?" % k, ()))
            bad.append((s, label, outs))
    first_step, first_label, first_outs = bad[0]
    step_part = (" (step %d of the fused chunk)" % first_step
                 if arr2.shape[0] > 1 else "")
    also = ""
    if len(bad) > 1:
        others = sorted({label for _, label, _ in bad[1:]})
        also = "\n  downstream non-finite ops (propagation): %s" % (
            ", ".join(others[:8]) + ("..." if len(others) > 8 else ""))
    raise EnforceNotMet(
        "PADDLE_TPU_CHECK_NUMERICS=2: non-finite values first produced by "
        "op %s (outputs %s)%s during %s%s\n"
        "(op identity is <source-op-index>:<type>; inspect it with "
        "tools/dump_program.py)"
        % (first_label, list(first_outs), step_part, driver, also))


# -- 3. collective traffic accounting -----------------------------------------

def record_collective(op: str, axis: Optional[str], array,
                      per_step_calls: int = 1) -> None:
    """Account one traced collective emission site.

    Called at TRACE time (``array`` is usually a tracer — only
    shape/dtype are read), so each compile records the bytes ONE step
    moves per device through this site; ``per_step_calls`` multiplies for
    sites inside a ``lax.scan`` body that executes N times per step.
    Counters: ``collectives/<op>/bytes``, ``collectives/<op>/calls`` and,
    with ``axis``, ``collectives/<op>/<axis>/bytes``."""
    if not _mx._enabled:
        return
    shape = getattr(array, "shape", None)
    dtype = getattr(array, "dtype", None)
    if shape is None or dtype is None:
        return
    import numpy as np

    n = 1
    for d in shape:
        n *= int(d)
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = 4
    total = n * itemsize * max(1, int(per_step_calls))
    _mx.counter("collectives/%s/bytes" % op,
                help="per-device bytes one step moves through traced "
                     "%s sites (recorded at trace time)" % op).inc(total)
    _mx.counter("collectives/%s/calls" % op).inc(max(1, int(per_step_calls)))
    if axis:
        _mx.counter("collectives/%s/%s/bytes" % (op, axis)).inc(total)


def collectives_snapshot() -> Dict[str, int]:
    """{counter name: value} of every non-zero ``collectives/*`` counter —
    the MULTICHIP-JSON / dryrun reporting surface."""
    out = {}
    for name, snap in _mx.snapshot().items():
        if name.startswith("collectives/") and snap.get("value"):
            out[name] = int(snap["value"])
    return out


# -- 4. flight recorder -------------------------------------------------------

def program_fingerprint(program) -> str:
    """Stable short hash of a Program's structure (op types + wiring),
    memoized per (program, version)."""
    cached = getattr(program, "_fp_cache", None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    import hashlib

    h = hashlib.sha1()
    for blk in program.blocks:
        for op in blk.ops:
            h.update(op.type.encode())
            for slot in sorted(op.inputs):
                h.update(("|i:%s=%s" % (slot, op.inputs[slot])).encode())
            for slot in sorted(op.outputs):
                h.update(("|o:%s=%s" % (slot, op.outputs[slot])).encode())
    fp = h.hexdigest()[:16]
    program._fp_cache = (program._version, fp)
    return fp


class FlightRecorder:
    """Ring buffer of the last N step records, dumped to JSON on crash.

    One recorder per ``PADDLE_TPU_FLIGHT_DIR`` value per process; thread
    safe (reader threads may be mid-step when the main loop crashes)."""

    def __init__(self, dirpath: str, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("PADDLE_TPU_FLIGHT_STEPS", "16"))
            except ValueError:
                capacity = 16
        self.dir = dirpath
        self.capacity = max(1, capacity)
        self._entries: List[dict] = []
        self._lock = threading.Lock()
        self._seq = 0
        self._dumped = 0

    def record_step(self, driver: str, program, feed_specs, fetch_names,
                    extra: Optional[dict] = None) -> None:
        """Append one pre-dispatch step record (the crash will have it)."""
        from ..passes.pipeline import DEFAULT_PASS_NAMES, opt_level, pass_enabled

        entry = {
            "t": time.time(),
            "seq": self._seq,
            "driver": driver,
            "program": program_fingerprint(program),
            "program_version": program._version,
            "n_ops": len(program.global_block.ops),
            "feed": [(n, str(d), list(s)) for n, d, s in feed_specs],
            "fetch": list(fetch_names),
            "opt_level": opt_level(),
            "pass_gates_off": [n for n in DEFAULT_PASS_NAMES
                               if not pass_enabled(n)],
            "metrics": _mx.snapshot(),
        }
        if extra:
            entry.update(extra)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._entries.append(entry)
            if len(self._entries) > self.capacity:
                del self._entries[:len(self._entries) - self.capacity]

    def record_event(self, kind: str, **payload) -> None:
        with self._lock:
            self._entries.append({"t": time.time(), "event": kind, **payload})
            if len(self._entries) > self.capacity:
                del self._entries[:len(self._entries) - self.capacity]

    def dump(self, reason: str, exc: Optional[BaseException] = None) -> str:
        """Write the ring + final metrics snapshot; returns the path.
        The dump embeds the process ``run_id`` and the most recent
        telemetry interval delta so it is self-contained AND joinable
        against the run ledger and the telemetry JSONL ring."""
        os.makedirs(self.dir, exist_ok=True)
        with self._lock:
            self._dumped += 1
            path = os.path.join(
                self.dir, "flight_%d_%d.json" % (os.getpid(), self._dumped))
            doc = {
                "reason": reason,
                "t": time.time(),
                "pid": os.getpid(),
                "run_id": None,
                "exception": (None if exc is None
                              else "%s: %s" % (type(exc).__name__, exc)),
                "env": {k: v for k, v in os.environ.items()
                        if k.startswith(("PADDLE_TPU_", "FLAGS_"))},
                "entries": list(self._entries),
                "metrics_final": _mx.snapshot(),
            }
        try:
            from .runlog import run_id

            doc["run_id"] = run_id()
        except Exception:
            pass
        try:
            from .telemetry import active_exporter

            exp = active_exporter()
            last = exp.last_sample if exp is not None else None
            if last is not None:
                doc["telemetry_last"] = {
                    "seq": last.seq, "t": last.t, "dt_s": last.dt_s,
                    "deltas": last.deltas}
        except Exception:
            pass
        try:
            # with PADDLE_TPU_NUMERICS armed, embed the per-op range
            # history — a NaN dump then shows the offending op's absmax
            # trajectory, not just the trip bit
            from . import numerics as _numerics

            if _numerics.stats_level() >= 1:
                snap = _numerics.snapshot()
                if snap:
                    doc["numerics_last"] = snap
        except Exception:
            pass
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        return path


_recorder: Optional[FlightRecorder] = None
_recorder_dir: Optional[str] = None


def flight_recorder() -> Optional[FlightRecorder]:
    """The process recorder, or None when ``PADDLE_TPU_FLIGHT_DIR`` is
    unset (the hot-path cost of the whole subsystem is then this env read
    + branch). A changed dir mid-process starts a fresh ring."""
    global _recorder, _recorder_dir
    d = os.environ.get("PADDLE_TPU_FLIGHT_DIR")
    if not d:
        return None
    if _recorder is None or _recorder_dir != d:
        _recorder = FlightRecorder(d)
        _recorder_dir = d
    return _recorder
