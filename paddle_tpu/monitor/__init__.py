"""paddle_tpu.monitor — unified metrics + host-span tracing.

The observability layer the Fluid reference spreads over RecordEvent,
the CUPTI DeviceTracer, ``tools/timeline.py`` and ad-hoc VLOGs, rebuilt
TPU-native in three pieces:

* :mod:`~paddle_tpu.monitor.metrics` — process-global registry of
  counters / gauges / fixed-bucket histograms. ``PADDLE_TPU_METRICS=0``
  disables it (hot paths then pay a single branch). The Executor, readers
  and optimizer are pre-instrumented; ``monitor.snapshot()`` returns
  everything as a dict, ``monitor.to_text()`` as a table.
* :mod:`~paddle_tpu.monitor.tracer` — nested host wall-clock spans with
  Chrome-trace/Perfetto export. ``PADDLE_TPU_TRACE_FILE=/tmp/t.json``
  records for the whole process and writes the trace at exit; it composes
  with the ``jax.profiler`` device trace via ``profiler.record_event`` /
  ``span(..., device=True)``.
* :mod:`~paddle_tpu.monitor.step_logger` — ``StepLogger``, the periodic
  throughput/step-time/loss line emitter used by ``bench.py`` and
  ``train/``; its ``summary()`` is the ``metrics`` section of bench JSON.
* :mod:`~paddle_tpu.monitor.device` — the DEVICE-side layer: per-op
  named-scope attribution in HLO/xprof + ``device_profile/*``
  cost/memory gauges, the in-graph numerics watchdog
  (``PADDLE_TPU_CHECK_NUMERICS``), explicit-collective byte accounting
  (``collectives/*``), and the crash flight recorder
  (``PADDLE_TPU_FLIGHT_DIR``).
* :mod:`~paddle_tpu.monitor.telemetry` — CONTINUOUS export: a background
  thread snapshots the registry on an interval into a bounded JSONL
  time-series ring (``PADDLE_TPU_TELEMETRY_DIR``), renders Prometheus
  text (``monitor.to_prometheus()``), and drives the per-tick SLO
  evaluation of the next module.
* :mod:`~paddle_tpu.monitor.slo` — declarative SLOs
  (``SLO("serving/request_latency_ms", p=99, max_ms=250)``) evaluated per
  export tick against interval deltas; breaches count, hit the flight
  recorder, and (opt-in) degrade ``ServingEngine.health()``.
* :mod:`~paddle_tpu.monitor.budgets` — checked-in closed-form
  collective-traffic budgets asserted against the measured
  ``collectives/*`` counters (``tools/check_budgets.py``).
* :mod:`~paddle_tpu.monitor.runlog` / :mod:`~paddle_tpu.monitor.regress`
  / :mod:`~paddle_tpu.monitor.stepstats` — the ACROSS-run layer: a
  provenance-stamped run ledger (``PADDLE_TPU_RUN_LEDGER``), noise-aware
  regression verdicts over its trailing baselines, and step-time
  bottleneck attribution (``tools/perf_gate.py`` is the CLI).

Quick tour::

    from paddle_tpu import monitor

    monitor.tracer.start_tracing()
    for batch in data:
        exe.run(main, feed=batch, fetch_list=[loss])
    print(monitor.to_text())                       # cache hits, step times…
    monitor.tracer.stop_tracing("/tmp/trace.json")  # open in chrome://tracing
"""

from __future__ import annotations

import os

from . import (  # noqa: F401
    budgets, device, metrics, numerics, regress, runlog, slo, stepstats,
    telemetry, tracer,
)
from .metrics import (  # noqa: F401
    counter, gauge, histogram, enabled, enable, disable,
    snapshot, to_json, to_text, to_prometheus, reset,
)
from .slo import SLO, SLOMonitor  # noqa: F401
from .step_logger import StepLogger  # noqa: F401
from .telemetry import TelemetryExporter  # noqa: F401

__all__ = [
    "budgets", "device", "metrics", "numerics", "regress", "runlog", "slo",
    "stepstats", "telemetry", "tracer",
    "StepLogger", "SLO", "SLOMonitor", "TelemetryExporter",
    "counter", "gauge", "histogram", "enabled", "enable", "disable",
    "snapshot", "to_json", "to_text", "to_prometheus", "reset",
    "GRAD_NORM_VAR", "grad_norm_enabled",
]

# Name of the (non-persistable — never checkpointed) program var the
# optimizer writes the pre-clip global gradient norm into when grad-norm
# monitoring is on; the Executor fetches it as a hidden extra and mirrors
# it into the "optimizer/grad_global_norm" gauge post-step.
GRAD_NORM_VAR = "@grad_global_norm@"


def grad_norm_enabled() -> bool:
    """Opt-in (env ``PADDLE_TPU_GRAD_NORM=1``): reading the norm gauge
    forces one scalar device sync per step, so it is off by default."""
    return os.environ.get("PADDLE_TPU_GRAD_NORM", "").strip().lower() in (
        "1", "true", "yes", "on")
