"""Run ledger: one provenance-stamped JSONL record per bench/selftest run.

PR 8 made a single process observable while it runs (telemetry ring, SLO
monitor); nothing connected runs to each other — the measured trajectory
lived in log tails a human had to reread. This module is the ACROSS-run
layer: with ``PADDLE_TPU_RUN_LEDGER=/path/ledger.jsonl`` armed, every
``bench.py`` / ``tools/serve_bench.py`` / ``tools/autotune.py`` /
``tools/perf_gate.py`` invocation appends one record carrying

* ``run_id`` — one id per process (also printed in the summary tail and
  embedded in flight-recorder dumps, so ledger <-> telemetry <-> crash
  artifacts join on a single key),
* provenance — git sha + dirty flag, device kind, backend, JAX version,
  opt level + disabled pass gates, tune-table path + per-kernel config
  provenance, and the ``PADDLE_TPU_*``/``FLAGS_*`` env knob snapshot,
* ``configs`` — the {config: {metric: value}} map the run already prints
  in its truncation-proof tail.

Write discipline mirrors the telemetry ring (telemetry.py ``_write``):
every append is flushed + fsynced so a crash loses at most the in-flight
line; the file rotates to ``<path>.<k>`` every
``PADDLE_TPU_RUN_LEDGER_ROTATE`` records keeping
``PADDLE_TPU_RUN_LEDGER_KEEP`` rotated files; the first write error logs
once and disables the on-disk ledger — it never masks the run it records.
Read-back (:func:`read_ledger`) tolerates torn trailing lines and skips
foreign schemas, so a ledger shared across versions stays loadable.

:mod:`paddle_tpu.monitor.regress` consumes the ledger as the baseline
window for noise-aware regression verdicts; ``tools/perf_gate.py`` is the
CLI over both.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

from . import metrics as _mx

__all__ = [
    "RUN_SCHEMA", "RunLedger", "run_id", "provenance", "ledger_path",
    "record_run", "read_ledger", "tail_info",
]

RUN_SCHEMA = "paddle_tpu.runlog/v1"

_log = logging.getLogger("paddle_tpu")

_c_records = _mx.counter(
    "runlog/records", help="run-ledger records appended (or handed back "
                           "unwritten when no ledger is armed)")
_c_rotations = _mx.counter(
    "runlog/rotations", help="run-ledger file rotations")
_c_write_errors = _mx.counter(
    "runlog/write_errors", help="run-ledger write failures (first one "
                                "disables the on-disk ledger)")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# -- run identity -------------------------------------------------------------

_run_id: Optional[str] = None


def run_id() -> str:
    """One id per process, generated on first use:
    ``r<utc-stamp>-<pid>-<4 hex>``. Every artifact a run leaves (ledger
    record, summary tail, flight dump) carries the same value."""
    global _run_id
    if _run_id is None:
        _run_id = "r%s-%d-%s" % (
            time.strftime("%Y%m%dT%H%M%S", time.gmtime()),
            os.getpid(), uuid.uuid4().hex[:4])
    return _run_id


# -- provenance ---------------------------------------------------------------

_git_cache: Optional[Dict[str, Any]] = None


def _git_state() -> Dict[str, Any]:
    """HEAD sha + dirty flag of the repo containing this package; every
    failure mode (no git binary, not a checkout, timeout) degrades to
    ``{"sha": None}`` — provenance must never sink a bench. Cached per
    process (two subprocess spawns once, not per record)."""
    global _git_cache
    if _git_cache is not None:
        return dict(_git_cache)
    _git_cache = _read_git_state()
    return dict(_git_cache)


def _read_git_state() -> Dict[str, Any]:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, timeout=5,
            capture_output=True, text=True)
        if sha.returncode != 0:
            return {"sha": None}
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=root, timeout=5,
            capture_output=True, text=True)
        return {"sha": sha.stdout.strip(),
                "dirty": bool(dirty.stdout.strip())
                if dirty.returncode == 0 else None}
    except Exception:
        return {"sha": None}


def provenance() -> Dict[str, Any]:
    """The full context stamp: everything needed to ask "what produced
    this number" of a ledger record months later. Each section degrades
    independently (a broken tune table must not cost the git sha)."""
    out: Dict[str, Any] = {"git": _git_state(),
                           "python": sys.version.split()[0],
                           "pid": os.getpid()}
    try:
        import jax

        out["jax"] = jax.__version__
        out["backend"] = jax.default_backend()
    except Exception:
        out["jax"] = None
    try:
        from .device import raw_device_kind

        out["device_kind"] = raw_device_kind()
    except Exception:
        out["device_kind"] = "unknown"
    try:
        from ..passes.pipeline import (DEFAULT_PASS_NAMES, opt_level,
                                       pass_enabled)

        out["opt_level"] = opt_level()
        out["pass_gates_off"] = [n for n in DEFAULT_PASS_NAMES
                                 if not pass_enabled(n)]
    except Exception:
        out["opt_level"] = None
    try:
        from .. import tune

        out["tune_table"] = tune.table_path()
        out["tune_provenance"] = {
            k: p.get("source") for k, p in
            sorted(tune.provenance_snapshot().items())}
    except Exception:
        out["tune_table"] = None
    # same knob families the flight recorder snapshots (device.py dump())
    out["env"] = {k: v for k, v in sorted(os.environ.items())
                  if k.startswith(("PADDLE_TPU_", "FLAGS_"))}
    return out


# -- the ledger ---------------------------------------------------------------

def ledger_path() -> Optional[str]:
    p = os.environ.get("PADDLE_TPU_RUN_LEDGER", "").strip()
    return p or None


class RunLedger:
    """Append-only JSONL ledger at ``path`` (telemetry-ring discipline:
    fsync per append, bounded rotation, disable-on-write-error)."""

    def __init__(self, path: str, rotate_records: Optional[int] = None,
                 keep_files: Optional[int] = None):
        self.path = path
        self.rotate_records = max(1, rotate_records if rotate_records
                                  is not None else
                                  _env_int("PADDLE_TPU_RUN_LEDGER_ROTATE",
                                           4096))
        self.keep_files = max(1, keep_files if keep_files is not None else
                              _env_int("PADDLE_TPU_RUN_LEDGER_KEEP", 4))
        self.disabled = False
        self._records_in_file: Optional[int] = None  # counted lazily

    def _count_records(self) -> int:
        try:
            with open(self.path) as f:
                return sum(1 for line in f if line.strip())
        except OSError:
            return 0

    def _rotate(self) -> None:
        """Shift the live file to ``<path>.<k>`` (k monotonically
        increasing) and prune rotated files past ``keep_files``."""
        idx = 1
        existing = _rotated_paths(self.path)
        if existing:
            idx = existing[-1][0] + 1
        os.replace(self.path, "%s.%d" % (self.path, idx))
        _c_rotations.inc()
        keep = _rotated_paths(self.path)
        excess = len(keep) - (self.keep_files - 1)
        for _, p in keep[:max(0, excess)]:
            try:
                os.remove(p)
            except OSError:
                pass

    def append(self, record: dict) -> Optional[str]:
        """Write one record; returns the ledger path, or ``None`` once
        the ledger disabled itself after a write error."""
        if self.disabled:
            return None
        try:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            if self._records_in_file is None:
                self._records_in_file = self._count_records()
            if self._records_in_file >= self.rotate_records:
                self._rotate()
                self._records_in_file = 0
            with open(self.path, "a") as f:
                f.write(json.dumps(record, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._records_in_file += 1
            return self.path
        except OSError as e:
            # the telemetry-ring rule: a broken ledger path must never
            # mask the run it records — log once, keep returning records
            self.disabled = True
            _c_write_errors.inc()
            _log.error(
                "runlog: cannot write PADDLE_TPU_RUN_LEDGER=%r (%s) — "
                "on-disk ledger disabled for this process", self.path, e)
            return None


_ledger: Optional[RunLedger] = None


def _active_ledger() -> Optional[RunLedger]:
    """Process ledger for the current ``PADDLE_TPU_RUN_LEDGER`` value
    (None when unarmed); a changed path mid-process opens a fresh one."""
    global _ledger
    p = ledger_path()
    if p is None:
        return None
    if _ledger is None or _ledger.path != p:
        _ledger = RunLedger(p)
    return _ledger


def record_run(kind: str, configs: Dict[str, dict],
               extra: Optional[dict] = None) -> dict:
    """Build (and, when the ledger is armed, append) one run record.

    ``configs`` is the {config: {metric: value}} map the caller's summary
    tail prints; ``kind`` names the producing surface ("bench",
    "serve_bench", "autotune", "perf_gate"). Returns the record either
    way — callers embed ``run_id`` in their tails unconditionally, and
    ``record["ledger_path"]`` says whether it also landed on disk."""
    record = {
        "schema": RUN_SCHEMA,
        "run_id": run_id(),
        "t": time.time(),
        "kind": kind,
        "provenance": provenance(),
        "configs": configs,
    }
    if extra:
        record["extra"] = extra
    try:
        # with PADDLE_TPU_NUMERICS armed, the record carries the run's
        # final per-op range stats — joins the perf trajectory to the
        # numerics trajectory on the same run_id (each section of a record
        # degrades independently, same rule as provenance())
        from . import numerics as _numerics

        if _numerics.stats_level() >= 1:
            snap = _numerics.snapshot()
            if snap:
                record["numerics_last"] = snap
    except Exception:
        pass
    led = _active_ledger()
    record["ledger_path"] = led.append(record) if led is not None else None
    _c_records.inc()
    return record


def tail_info() -> Dict[str, Any]:
    """The cross-linking keys every summary tail carries: the process
    ``run_id``, plus the ledger path when one is armed."""
    out: Dict[str, Any] = {"run_id": run_id()}
    p = ledger_path()
    if p:
        out["run_ledger"] = p
    return out


# -- read-back ----------------------------------------------------------------

def _rotated_paths(path: str) -> List[tuple]:
    """[(idx, path)] of rotated shards, oldest first."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if name.startswith(base + "."):
            suffix = name[len(base) + 1:]
            if suffix.isdigit():
                out.append((int(suffix), os.path.join(d, name)))
    return sorted(out)


def read_ledger(path: Optional[str] = None) -> List[dict]:
    """Load the ledger back, rotated shards first, in append order.
    Torn trailing lines (a crash mid-append) and foreign-schema lines
    are skipped, not fatal — the ledger is a baseline source first."""
    path = path or ledger_path()
    if not path:
        return []
    out: List[dict] = []
    files = [p for _, p in _rotated_paths(path)] + [path]
    for p in files:
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue  # torn tail line
                    if doc.get("schema") == RUN_SCHEMA:
                        out.append(doc)
        except OSError:
            continue
    return out
