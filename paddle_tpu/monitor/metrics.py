"""Process-global metrics registry: counters, gauges, fixed-bucket histograms.

The role Fluid scatters across ``platform/profiler.cc`` event counters,
``memory_usage_calc.py`` and ad-hoc VLOG lines, unified the way modern
serving stacks do it (Prometheus-style instruments). Three design rules:

1. **Near-zero overhead when disabled** — every instrument method starts
   with a single ``if not _enabled: return`` branch (no lock, no
   allocation). ``PADDLE_TPU_METRICS=0`` turns the whole subsystem into
   that branch; the default is ON because enabled-path cost is a lock +
   a float add, invisible next to a device step.
2. **Thread-safe when enabled** — reader/prefetcher worker threads and
   the main step loop write concurrently; each instrument carries its own
   lock so there is no global hot lock.
3. **Names are stable strings** (``"executor/cache_hit"``) — the registry
   is get-or-create, so instrumented modules can be imported in any order
   and tests can look instruments up by name.

Export surfaces: ``snapshot()`` (plain dict), ``to_json()``, ``to_text()``
(one line per instrument), ``to_prometheus()`` (text exposition format a
promtool-style validator parses: sanitized names, cumulative ``_bucket``
counts + ``_sum``/``_count`` per histogram), ``reset()`` (zero values, keep
registrations).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Dict, List, Optional, Sequence

from ..log import vlog

__all__ = [
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "enabled", "enable", "disable",
    "snapshot", "to_json", "to_text", "to_prometheus", "prometheus_name",
    "reset",
    "DEFAULT_TIME_BUCKETS_MS", "log_buckets", "sorted_percentile",
]


def sorted_percentile(xs: Sequence[float], p: float) -> float:
    """p-th percentile (p in [0, 100]) of an already-sorted sample list —
    the one convention shared by StepLogger and StepProfiler readouts
    (floor-index; exact sample values, no interpolation)."""
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, int(len(xs) * p / 100.0))]


def _env_enabled() -> bool:
    raw = os.environ.get("PADDLE_TPU_METRICS", "1").strip().lower()
    return raw not in ("0", "false", "no", "off", "")


_enabled: bool = _env_enabled()
_registry: Dict[str, "_Instrument"] = {}
_registry_lock = threading.Lock()

# Buckets for wall-time histograms, in milliseconds: sub-ms host overhead up
# through multi-second compiles, roughly 2.5x steps.
DEFAULT_TIME_BUCKETS_MS: Sequence[float] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


def log_buckets(lo: float, hi: float,
                per_decade: int = 3) -> Sequence[float]:
    """Geometric histogram bounds covering ``[lo, hi]`` with
    ``per_decade`` buckets per factor of 10 — the bucket scheme for
    quantities spanning many orders of magnitude (tensor absmax ranges run
    1e-8..1e4; no linear ladder holds that). Bounds are plain floats, so
    the existing Histogram/snapshot/to_prometheus machinery needs no
    special casing. ``hi`` is always included as the last bound."""
    if lo <= 0.0:
        raise ValueError("log_buckets: lo must be > 0, got %r" % (lo,))
    if hi <= lo:
        raise ValueError("log_buckets: need hi > lo, got %r <= %r"
                         % (hi, lo))
    if per_decade < 1:
        raise ValueError("log_buckets: per_decade must be >= 1, got %r"
                         % (per_decade,))
    import math

    steps = int(math.ceil(math.log10(hi / lo) * per_decade)) + 1
    out: List[float] = [float(lo)]
    for i in range(1, steps):
        # each bound from lo directly (no cumulative drift), snapped to 10
        # significant digits so ``le_%g`` labels stay clean
        nxt = float("%.10g" % (lo * 10.0 ** (i / float(per_decade))))
        if nxt >= hi:
            break
        if nxt > out[-1]:
            out.append(nxt)
    if out[-1] < hi:
        out.append(float(hi))
    return tuple(out)


def enabled() -> bool:
    return _enabled


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = bool(flag)


def disable() -> None:
    enable(False)


class _Instrument:
    kind = "instrument"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def snapshot(self) -> dict:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonically increasing count (resets only via registry reset)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._value}

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge(_Instrument):
    """Last-written value (queue depth, HBM bytes, grad norm, ...)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0
        self._written = False

    def set(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = float(v)
            self._written = True

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n
            self._written = True

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value, "set": self._written}

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0
            self._written = False


class Histogram(_Instrument):
    """Fixed-bucket histogram with sum/count/min/max and estimated quantiles.

    Buckets are upper bounds (le); observations past the last bound land in
    the +Inf overflow bucket. Quantile estimation interpolates linearly
    inside the containing bucket — the standard Prometheus
    ``histogram_quantile`` behaviour, good enough for p50/p95 step-time
    readouts without retaining raw samples.
    """

    kind = "histogram"

    def __init__(self, name: str, buckets: Optional[Sequence[float]] = None,
                 help: str = ""):
        super().__init__(name, help)
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_TIME_BUCKETS_MS)))
        if not bounds:
            raise ValueError("histogram %r needs at least one bucket bound" % name)
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1 = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._overflow_warned = False

    def observe(self, v: float) -> None:
        if not _enabled:
            return
        v = float(v)
        # bisect without importing: bucket count is small and fixed
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> float:
        """Estimated p-th percentile (p in [0, 100]) by linear interpolation
        within the containing bucket; exact-ish at the observed min/max.

        A rank landing in the +Inf overflow bucket CLAMPS to the top
        finite bucket edge (one-time vlog) instead of interpolating
        toward the observed max: the overflow bucket has no upper edge,
        so interpolation there manufactures spuriously precise values a
        single outlier drags arbitrarily high — the same honest-lower-
        bound convention telemetry's interval percentiles use."""
        with self._lock:
            total = self._count
            if total == 0:
                return 0.0
            rank = max(1.0, math.ceil(total * min(max(p, 0.0), 100.0) / 100.0))
            cum = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                if i == len(self.bounds):
                    if not self._overflow_warned:
                        self._overflow_warned = True
                        vlog(1, "histogram %s: p%g rank in +Inf overflow "
                                "bucket — clamping to top edge %g (max "
                                "observed %g); widen the buckets",
                             self.name, p, self.bounds[-1], self._max)
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else max(0.0, min(self._min, self.bounds[0]))
                hi = self.bounds[i]
                if rank <= cum + c:
                    frac = (rank - cum) / c
                    est = lo + (hi - lo) * frac
                    return min(max(est, self._min), self._max)
                cum += c
            return self._max

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
            mn = self._min if n else 0.0
            mx = self._max if n else 0.0
        out = {
            "type": "histogram",
            "count": n,
            "sum": s,
            "min": mn,
            "max": mx,
            "mean": (s / n) if n else 0.0,
            "buckets": {("le_%g" % b): c for b, c in zip(self.bounds, counts)},
        }
        out["buckets"]["le_inf"] = counts[-1]
        if n:
            out["p50"] = self.percentile(50)
            out["p95"] = self.percentile(95)
            out["p99"] = self.percentile(99)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._overflow_warned = False
            self._min = math.inf
            self._max = -math.inf


def _get_or_create(name: str, cls, **kwargs) -> _Instrument:
    inst = _registry.get(name)
    if inst is not None:
        if not isinstance(inst, cls):
            raise TypeError("metric %r already registered as %s, requested %s"
                            % (name, inst.kind, cls.kind))
        return inst
    with _registry_lock:
        inst = _registry.get(name)
        if inst is None:
            inst = cls(name, **kwargs)
            _registry[name] = inst
        elif not isinstance(inst, cls):
            raise TypeError("metric %r already registered as %s, requested %s"
                            % (name, inst.kind, cls.kind))
        return inst


def counter(name: str, help: str = "") -> Counter:
    return _get_or_create(name, Counter, help=help)


def gauge(name: str, help: str = "") -> Gauge:
    return _get_or_create(name, Gauge, help=help)


def histogram(name: str, buckets: Optional[Sequence[float]] = None,
              help: str = "") -> Histogram:
    inst = _get_or_create(name, Histogram, buckets=buckets, help=help)
    if buckets is not None:
        want = tuple(sorted(float(b) for b in buckets))
        if want != inst.bounds:
            # silently handing back different bounds would skew every
            # percentile the caller computes against its requested buckets
            raise ValueError(
                "histogram %r already registered with buckets %s; "
                "requested %s" % (name, list(inst.bounds), list(want)))
    return inst


def snapshot() -> Dict[str, dict]:
    """Point-in-time view of every registered instrument, as a plain dict
    (JSON-serializable; the ``metrics`` section of bench JSON)."""
    with _registry_lock:
        items = list(_registry.items())
    return {name: inst.snapshot() for name, inst in sorted(items)}


def to_json(indent: Optional[int] = None) -> str:
    return json.dumps(snapshot(), indent=indent, sort_keys=True)


def to_text() -> str:
    """One line per instrument — the quick ``print`` surface."""
    lines: List[str] = []
    for name, snap in snapshot().items():
        t = snap["type"]
        if t == "histogram":
            lines.append(
                "%-40s hist  count=%d mean=%.3f p50=%.3f p95=%.3f p99=%.3f "
                "min=%.3f max=%.3f"
                % (name, snap["count"], snap["mean"], snap.get("p50", 0.0),
                   snap.get("p95", 0.0), snap.get("p99", 0.0),
                   snap["min"], snap["max"]))
        else:
            lines.append("%-40s %-5s value=%g" % (name, t, snap["value"]))
    return "\n".join(lines)


def prometheus_name(name: str) -> str:
    """Instrument name → a valid Prometheus metric name: ``/`` and ``:``
    (and any other illegal character) become ``_``; a leading digit gains a
    ``_`` prefix. Distinct registry names stay distinct in practice because
    every instrument here uses ``/``-separated word segments."""
    out = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_num(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return "%d" % int(v)
    return repr(float(v))


def to_prometheus() -> str:
    """Render the registry in the Prometheus text exposition format (0.0.4).

    Counters/gauges are one sample each; histograms emit the standard
    triplet — CUMULATIVE ``<name>_bucket{le="..."}`` counts ending in
    ``le="+Inf"``, plus ``<name>_sum`` and ``<name>_count`` — so the output
    parses under promtool-style validators and ``histogram_quantile``
    works on a scrape of it. Names are sanitized via
    :func:`prometheus_name` (``serving/ttft_ms`` → ``serving_ttft_ms``).
    """
    with _registry_lock:
        items = sorted(_registry.items())
    lines: List[str] = []
    for name, inst in items:
        pname = prometheus_name(name)
        if inst.help:
            lines.append("# HELP %s %s" % (pname, _prom_escape_help(inst.help)))
        if isinstance(inst, Histogram):
            lines.append("# TYPE %s histogram" % pname)
            snap = inst.snapshot()
            cum = 0
            bounds = list(inst.bounds)
            counts = [snap["buckets"]["le_%g" % b] for b in bounds]
            counts.append(snap["buckets"]["le_inf"])
            for b, c in zip(bounds + [math.inf], counts):
                cum += c
                le = "+Inf" if b == math.inf else _prom_num(b)
                lines.append('%s_bucket{le="%s"} %d' % (pname, le, cum))
            lines.append("%s_sum %s" % (pname, _prom_num(snap["sum"])))
            lines.append("%s_count %d" % (pname, snap["count"]))
        elif isinstance(inst, Counter):
            lines.append("# TYPE %s counter" % pname)
            lines.append("%s %s" % (pname, _prom_num(inst.value)))
        else:
            lines.append("# TYPE %s gauge" % pname)
            lines.append("%s %s" % (pname, _prom_num(inst.value)))
    return "\n".join(lines) + ("\n" if lines else "")


def reset() -> None:
    """Zero all values; registrations (and module-held instrument handles)
    stay valid."""
    with _registry_lock:
        items = list(_registry.values())
    for inst in items:
        inst.reset()
