"""Step-time decomposition: WHY a step costs what it costs.

The regression detector (monitor.regress) says a run got slower; this
module says where the time went, fusing what the repo already measures
into per-term millisecond estimates for one step:

* ``compute_ms`` — ``device_profile/flops`` / peak FLOP/s (the roofline
  numerator ``tools/profile_report`` renders per op);
* ``memory_ms`` — ``device_profile/bytes_accessed`` / HBM bandwidth;
* ``comms_ms``  — the closed-form ``collectives/*/bytes`` counters /
  ICI bandwidth (per-device bytes one step moves, trace-time accounting);
* ``host_ms``   — the bench's measured host dispatch gap per step;
* ``input_ms``  — mean feed wait per observation across the prefetch-
  instrumented readers (``data/prefetch_wait_ms``,
  ``reader/wait_time_ms``, ``prefetcher/wait_time_ms``).

On hardware where no peak table entry exists (CPU dry runs), the device
terms fall back to the measured residual ``step_ms - host_ms - input_ms``
so attribution still ranks measured terms instead of going silent.

:func:`attribute` labels the step **compute- / comms- / host- /
input-bound** by the dominant term (the device roofline pair compute +
memory both map to "compute" — they are the same knob family) and
attaches an actionable hint. Rendered in bench summary tails and by
``tools/perf_gate.py --explain``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from . import metrics as _mx

__all__ = ["collect_terms", "attribute", "decompose", "render", "PEAKS"]

# per-chip peaks by device-kind fragment: bf16 FLOP/s (bench.py's
# _PEAK_BF16 table), HBM GB/s and ICI GB/s per direction (public specs)
PEAKS: Dict[str, Dict[str, float]] = {
    "TPU v3": {"flops": 123e12, "hbm_gbps": 900.0, "ici_gbps": 70.0},
    "TPU v4": {"flops": 275e12, "hbm_gbps": 1200.0, "ici_gbps": 100.0},
    "TPU v5e": {"flops": 197e12, "hbm_gbps": 819.0, "ici_gbps": 50.0},
    "TPU v5 lite": {"flops": 197e12, "hbm_gbps": 819.0, "ici_gbps": 50.0},
    "TPU v5p": {"flops": 459e12, "hbm_gbps": 2765.0, "ici_gbps": 100.0},
    "TPU v6e": {"flops": 918e12, "hbm_gbps": 1640.0, "ici_gbps": 100.0},
}

# which Program-level knob each bound label points at
HINTS = {
    "compute": "device-bound: check MFU vs roofline per op "
               "(tools/profile_report), precision, and fusion rewrites",
    "comms": "comms-bound: check collectives/* vs the closed-form budgets "
             "(tools/check_budgets) and overlap/sharding layout",
    "host": "host-bound: use the fused run_steps driver / AOT prepare so "
            "dispatch overlaps device work",
    "input": "input-bound: feed wait dominates — raise prefetch depth / "
             "reader workers (paddle_tpu.data), or move parsing off the "
             "step loop",
}

_WAIT_HISTS = ("data/prefetch_wait_ms", "reader/wait_time_ms",
               "prefetcher/wait_time_ms")

# dominant-term name -> bound label
_TERM_BOUND = {"compute_ms": "compute", "memory_ms": "compute",
               "comms_ms": "comms", "host_ms": "host", "input_ms": "input"}


def device_peaks(device_kind: Optional[str] = None) -> Dict[str, float]:
    """Peak table entry matched by device-kind fragment ({} when unknown
    — CPU dry runs have no meaningful peak)."""
    if device_kind is None:
        from .device import raw_device_kind

        device_kind = raw_device_kind()
    for frag, peaks in PEAKS.items():
        if frag.lower() in (device_kind or "").lower():
            return dict(peaks)
    return {}


def _hist_mean(snap: Dict[str, dict], name: str) -> Optional[float]:
    h = snap.get(name)
    if not h or h.get("type") != "histogram" or not h.get("count"):
        return None
    return float(h["sum"]) / float(h["count"])


def collect_terms(snapshot: Optional[Dict[str, dict]] = None, *,
                  host_ms: Optional[float] = None,
                  device_kind: Optional[str] = None,
                  peaks: Optional[Dict[str, float]] = None
                  ) -> Dict[str, Optional[float]]:
    """Per-step term estimates (ms) from a metrics snapshot (default: the
    live registry). Terms the snapshot cannot support come back None —
    :func:`attribute` ranks only what is known."""
    snap = _mx.snapshot() if snapshot is None else snapshot
    if peaks is None:
        peaks = device_peaks(device_kind)

    def gauge(name):
        s = snap.get(name)
        return float(s["value"]) if s and s.get("value") else None

    terms: Dict[str, Optional[float]] = {
        "compute_ms": None, "memory_ms": None, "comms_ms": None,
        "host_ms": host_ms, "input_ms": None,
    }
    flops = gauge("device_profile/flops")
    if flops and peaks.get("flops"):
        terms["compute_ms"] = 1e3 * flops / peaks["flops"]
    hbm_bytes = gauge("device_profile/bytes_accessed")
    if hbm_bytes and peaks.get("hbm_gbps"):
        terms["memory_ms"] = 1e3 * hbm_bytes / (peaks["hbm_gbps"] * 1e9)
    coll_bytes = sum(
        float(s.get("value", 0.0)) for name, s in snap.items()
        if name.startswith("collectives/") and name.endswith("/bytes")
        and name.count("/") == 2 and s.get("value"))
    if coll_bytes and peaks.get("ici_gbps"):
        terms["comms_ms"] = 1e3 * coll_bytes / (peaks["ici_gbps"] * 1e9)
    waits = [m for m in (_hist_mean(snap, n) for n in _WAIT_HISTS)
             if m is not None]
    if waits:
        terms["input_ms"] = sum(waits)
    return terms


def attribute(terms: Dict[str, Optional[float]],
              step_ms: Optional[float] = None) -> Dict[str, Any]:
    """Label a step by its dominant term.

    ``terms`` is the (possibly partial) dict :func:`collect_terms`
    builds; ``step_ms`` the measured wall step time when known. With no
    device-side estimate but a measured ``step_ms``, the residual after
    host + input is attributed to compute — measured terms keep ranking
    on peak-less hardware."""
    known = {k: float(v) for k, v in terms.items() if v is not None}
    out: Dict[str, Any] = {"terms": {k: round(v, 4)
                                     for k, v in known.items()}}
    if step_ms is not None:
        out["step_ms"] = round(float(step_ms), 4)
    device_known = any(k in known for k in
                       ("compute_ms", "memory_ms", "comms_ms"))
    if not device_known and step_ms is not None:
        residual = float(step_ms) - known.get("host_ms", 0.0) \
            - known.get("input_ms", 0.0)
        known["compute_ms"] = max(0.0, residual)
        out["terms"]["compute_ms"] = round(known["compute_ms"], 4)
        out["compute_is_residual"] = True
    if not known:
        out.update(bound="unknown", dominant=None,
                   hint="no terms measured — run with metrics enabled")
        return out
    dominant = max(known, key=lambda k: known[k])
    bound = _TERM_BOUND.get(dominant, "unknown")
    out["dominant"] = dominant
    out["bound"] = bound
    out["hint"] = HINTS.get(bound, "")
    if step_ms:
        covered = sum(known.values())
        out["attributed_frac"] = round(
            min(1.0, covered / float(step_ms)), 4)
    return out


def decompose(snapshot: Optional[Dict[str, dict]] = None, *,
              step_ms: Optional[float] = None,
              host_ms: Optional[float] = None,
              device_kind: Optional[str] = None,
              peaks: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
    """collect_terms + attribute in one call — the bench-tail surface."""
    return attribute(
        collect_terms(snapshot, host_ms=host_ms, device_kind=device_kind,
                      peaks=peaks),
        step_ms=step_ms)


def render(breakdown: Dict[str, Any], config: str = "step") -> str:
    """One short human block for ``perf_gate --explain``."""
    lines = ["%s: %s-bound (dominant: %s)"
             % (config, breakdown.get("bound", "unknown"),
                breakdown.get("dominant"))]
    terms = breakdown.get("terms", {})
    for name in ("compute_ms", "memory_ms", "comms_ms", "host_ms",
                 "input_ms"):
        if name in terms:
            note = (" (residual)" if name == "compute_ms"
                    and breakdown.get("compute_is_residual") else "")
            lines.append("  %-12s %10.3f ms%s" % (name, terms[name], note))
    if "step_ms" in breakdown:
        lines.append("  %-12s %10.3f ms" % ("step_ms", breakdown["step_ms"]))
    if breakdown.get("hint"):
        lines.append("  hint: %s" % breakdown["hint"])
    return "\n".join(lines)
