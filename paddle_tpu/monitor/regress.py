"""Noise-aware perf regression detection over the run ledger.

Benchmarks are noisy; a naive ``current < previous`` gate either cries
wolf on every CPU-jitter wobble or needs thresholds so loose a real 10%
regression slides through. This module compares a run against a TRAILING
BASELINE WINDOW per (config, metric) using robust statistics:

* baseline center = **median**, spread = **MAD** (median absolute
  deviation) — one outlier run cannot move either;
* the noise band is ``max(rel_threshold, mad_mult * 1.4826 * MAD /
  |median|)`` — at least the configured relative tolerance, widened when
  the baseline itself is noisy (1.4826 scales MAD to a normal sigma);
* **direction-aware**: throughput metrics (eps/QPS/MFU/tokens-per-sec)
  regress DOWNWARD, latency/step-time metrics (p99/ms) regress UPWARD —
  inferred from the metric name, overridable per call;
* **min-sample gating**: a deviation beyond the band is only called
  REGRESSED/IMPROVED with ``min_samples`` baseline runs to stand on;
  fewer yields INSUFFICIENT_DATA (a verdict, not a guess). Within-band
  runs are NEUTRAL against any non-empty baseline; under ``min_samples``
  the band floor widens by ``sqrt(min_samples/n)`` since the MAD has
  nothing to say yet.

Verdicts are typed (:class:`Verdict`); :func:`report` gives each a
one-line human rendering. :func:`check_verdicts` is the enforcement arm
(SLO-monitor pattern): every REGRESSED verdict ticks ``perf/regressions``,
records a flight-recorder event when ``PADDLE_TPU_FLIGHT_DIR`` is armed,
and invokes an optional degrade hook. ``tools/perf_gate.py --check``
turns the result into a CI exit code.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from . import metrics as _mx

__all__ = [
    "REGRESSED", "IMPROVED", "NEUTRAL", "INSUFFICIENT_DATA",
    "Verdict", "metric_direction", "compare_point", "compare_run",
    "baseline_series", "check_verdicts", "report",
]

REGRESSED = "REGRESSED"
IMPROVED = "IMPROVED"
NEUTRAL = "NEUTRAL"
INSUFFICIENT_DATA = "INSUFFICIENT_DATA"

_c_regressions = _mx.counter(
    "perf/regressions", help="REGRESSED verdicts raised by the regression "
                             "detector (monitor.regress)")
_c_comparisons = _mx.counter(
    "perf/comparisons", help="(config, metric) comparisons evaluated")

# MAD -> sigma for normally distributed noise
_MAD_SIGMA = 1.4826

# name fragments decide which way "worse" points; checked lower-better
# first so "latency_p50_ms" never reads as throughput
_LOWER_BETTER = ("latency", "_ms", "ms_", "p99", "p95", "p50", "step_time",
                 "wall", "overhead", "wait", "stall", "ttft",
                 "migrated_pages")
_HIGHER_BETTER = ("eps", "examples_per_sec", "steps_per_sec", "qps", "mfu",
                  "tokens_per_sec", "throughput", "efficiency", "speedup",
                  "ratio", "acceptance_rate", "accept_", "hit_rate",
                  "remote_hit", "per_dispatch")


def metric_direction(name: str) -> int:
    """+1 = higher is better, -1 = lower is better, 0 = unknown (the
    detector skips metrics it cannot orient rather than guessing)."""
    low = name.lower()
    if low.endswith("ms") or any(t in low for t in _LOWER_BETTER):
        return -1
    if any(t in low for t in _HIGHER_BETTER):
        return 1
    return 0


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(xs: Sequence[float], center: float) -> float:
    return _median([abs(x - center) for x in xs]) if xs else 0.0


class Verdict:
    """One (config, metric) comparison outcome."""

    __slots__ = ("config", "metric", "verdict", "current", "baseline_median",
                 "baseline_mad", "n_baseline", "direction", "delta_frac",
                 "band_frac")

    def __init__(self, config: str, metric: str, verdict: str,
                 current: Optional[float] = None,
                 baseline_median: Optional[float] = None,
                 baseline_mad: float = 0.0, n_baseline: int = 0,
                 direction: int = 0, delta_frac: Optional[float] = None,
                 band_frac: Optional[float] = None):
        self.config = config
        self.metric = metric
        self.verdict = verdict
        self.current = current
        self.baseline_median = baseline_median
        self.baseline_mad = baseline_mad
        self.n_baseline = n_baseline
        self.direction = direction
        self.delta_frac = delta_frac
        self.band_frac = band_frac

    def to_doc(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}

    def describe(self) -> str:
        if self.baseline_median is None:
            return "%-12s %s/%s: no baseline" % (
                self.verdict, self.config, self.metric)
        return ("%-12s %s/%s: %.4g vs median %.4g (n=%d, %+.1f%%, "
                "band ±%.1f%%, %s better)" % (
                    self.verdict, self.config, self.metric,
                    self.current, self.baseline_median, self.n_baseline,
                    100.0 * (self.delta_frac or 0.0),
                    100.0 * (self.band_frac or 0.0),
                    "higher" if self.direction > 0 else "lower"))


def compare_point(config: str, metric: str, current: float,
                  baseline: Sequence[float], *, direction: Optional[int] = None,
                  rel_threshold: float = 0.10, mad_mult: float = 4.0,
                  min_samples: int = 4) -> Optional[Verdict]:
    """Verdict for one value against its trailing baseline series; None
    when the metric's direction is unknown (nothing to enforce)."""
    d = metric_direction(metric) if direction is None else direction
    if d == 0:
        return None
    _c_comparisons.inc()
    vals = [float(v) for v in baseline]
    if not vals:
        return Verdict(config, metric, INSUFFICIENT_DATA, current=current,
                       direction=d)
    med = _median(vals)
    mad = _mad(vals, med)
    if med == 0.0:
        # a zero-centered baseline has no meaningful relative band
        return Verdict(config, metric, INSUFFICIENT_DATA, current=current,
                       baseline_median=med, baseline_mad=mad,
                       n_baseline=len(vals), direction=d)
    band = max(rel_threshold, mad_mult * _MAD_SIGMA * mad / abs(med))
    if len(vals) < min_samples:
        # under min_samples the MAD is untrustworthy (n=1 gives MAD=0),
        # so the floor widens by sqrt(min_samples/n): less baseline,
        # less certainty, wider NEUTRAL zone. Beyond it the verdict is
        # INSUFFICIENT_DATA anyway, never REGRESSED.
        band = max(band, rel_threshold * (min_samples / len(vals)) ** 0.5)
    delta = (current - med) / abs(med)
    # positive badness = movement in the "worse" direction
    badness = -delta if d > 0 else delta
    if abs(delta) <= band:
        v = NEUTRAL
    elif len(vals) < min_samples:
        v = INSUFFICIENT_DATA  # out of band, but too few runs to call it
    elif badness > 0:
        v = REGRESSED
    else:
        v = IMPROVED
    return Verdict(config, metric, v, current=current, baseline_median=med,
                   baseline_mad=mad, n_baseline=len(vals), direction=d,
                   delta_frac=delta, band_frac=band)


def baseline_series(history: Sequence[dict], config: str, metric: str,
                    window: int = 20) -> List[float]:
    """Trailing numeric values of (config, metric) across ledger records,
    oldest->newest, capped at ``window``."""
    out: List[float] = []
    for rec in history:
        v = (rec.get("configs") or {}).get(config, {}).get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append(float(v))
    return out[-window:]


def compare_run(record: dict, history: Sequence[dict], *,
                rel_threshold: float = 0.10, mad_mult: float = 4.0,
                min_samples: int = 4, window: int = 20,
                directions: Optional[Dict[str, int]] = None
                ) -> List[Verdict]:
    """Compare every numeric (config, metric) of ``record`` against its
    trailing window in ``history`` (earlier ledger records, any order —
    ledger order is append order). ``directions`` overrides the
    name-inferred orientation per metric name."""
    verdicts: List[Verdict] = []
    for config, metrics in sorted((record.get("configs") or {}).items()):
        if not isinstance(metrics, dict):
            continue
        for metric, value in sorted(metrics.items()):
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                continue
            base = baseline_series(history, config, metric, window=window)
            v = compare_point(
                config, metric, float(value), base,
                direction=(directions or {}).get(metric),
                rel_threshold=rel_threshold, mad_mult=mad_mult,
                min_samples=min_samples)
            if v is not None:
                verdicts.append(v)
    return verdicts


def check_verdicts(verdicts: Sequence[Verdict],
                   on_regression: Optional[Callable[[Verdict], None]] = None
                   ) -> List[Verdict]:
    """Enforcement: tick ``perf/regressions`` per REGRESSED verdict,
    record a flight-recorder event (when armed), fire the degrade hook.
    Returns the regressed subset (empty = gate passes)."""
    regressed = [v for v in verdicts if v.verdict == REGRESSED]
    for v in regressed:
        _c_regressions.inc()
        try:
            from .device import flight_recorder

            fr = flight_recorder()
            if fr is not None:
                fr.record_event("perf_regression", **v.to_doc())
        except Exception:
            pass
        if on_regression is not None:
            try:
                on_regression(v)
            except Exception:
                pass
    return regressed


def report(verdicts: Sequence[Verdict]) -> str:
    """Human rendering, worst first."""
    order = {REGRESSED: 0, INSUFFICIENT_DATA: 1, IMPROVED: 2, NEUTRAL: 3}
    return "\n".join(v.describe() for v in
                     sorted(verdicts, key=lambda v: (order.get(v.verdict, 9),
                                                     v.config, v.metric)))
