"""StepLogger: periodic throughput/step-time/loss lines + a summary dict.

The step-callback layer bench.py and train/ scripts share: call ``step()``
once per training step and every ``every_n`` steps one line goes to the
``paddle_tpu`` logger (stderr by default):

    [train] step 200 | 31.9 steps/s | 2041 ex/s | step 31.3ms p50 31.1 p95 34.8 | loss 2.3127

``summary()`` returns the same numbers as a dict — the ``metrics`` section
benchmark JSON embeds. Step times also feed the registry histogram
``step_logger/step_time_ms`` so ``monitor.snapshot()`` sees them without
holding a StepLogger reference.
"""

from __future__ import annotations

import time
from typing import Optional

from ..log import get_logger
from . import metrics as _metrics

__all__ = ["StepLogger"]


class StepLogger:
    def __init__(self, every_n: int = 10, name: str = "train", logger=None,
                 keep_last: int = 4096):
        self.every_n = max(1, int(every_n))
        self.name = name
        self._log = logger or get_logger("monitor")
        self._keep_last = max(16, int(keep_last))
        self._hist = _metrics.histogram(
            "step_logger/step_time_ms", help="wall time between step() calls")
        self.reset()

    def reset(self) -> None:
        self._steps = 0
        self._examples = 0.0
        self._last_loss: Optional[float] = None
        self._pending_loss = None
        self._t_start: Optional[float] = None
        self._t_last: Optional[float] = None
        self._times_ms = []  # recent step times, bounded by keep_last
        self._win_t0: Optional[float] = None  # current reporting window
        self._win_steps = 0
        self._win_examples = 0.0

    # -- the per-step callback ------------------------------------------------
    def step(self, loss=None, examples: float = 0.0) -> None:
        """Record one finished step. ``loss`` may be a float, numpy scalar,
        or device array (converted only when a log line is due, to avoid a
        per-step device sync)."""
        now = time.perf_counter()
        if self._t_start is None:
            self._t_start = self._win_t0 = now
        else:
            dt_ms = (now - self._t_last) * 1e3
            self._times_ms.append(dt_ms)
            if len(self._times_ms) > self._keep_last:
                del self._times_ms[: -self._keep_last]
            self._hist.observe(dt_ms)
        self._t_last = now
        self._steps += 1
        self._examples += examples
        self._win_steps += 1
        self._win_examples += examples
        if loss is not None:
            self._pending_loss = loss
        if self._steps % self.every_n == 0:
            self._emit(now)

    def _emit(self, now: float) -> None:
        win_dt = max(now - (self._win_t0 or now), 1e-9)
        sps = self._win_steps / win_dt
        parts = ["[%s] step %d" % (self.name, self._steps),
                 "%.1f steps/s" % sps]
        if self._win_examples:
            parts.append("%.0f ex/s" % (self._win_examples / win_dt))
        if self._times_ms:
            recent = sorted(self._times_ms[-self._keep_last:])
            parts.append("step %.1fms p50 %.1f p95 %.1f"
                         % (self._times_ms[-1],
                            _metrics.sorted_percentile(recent, 50),
                            _metrics.sorted_percentile(recent, 95)))
        loss = self._pending_loss
        if loss is not None:
            try:
                self._last_loss = float(loss)
                parts.append("loss %.4f" % self._last_loss)
            except (TypeError, ValueError):
                pass
        self._log.info(" | ".join(parts))
        self._win_t0 = now
        self._win_steps = 0
        self._win_examples = 0.0

    # -- the bench surface ----------------------------------------------------
    def summary(self) -> dict:
        """Totals + step-time percentiles as a plain dict (bench JSON
        ``metrics`` section)."""
        elapsed = ((self._t_last - self._t_start)
                   if self._t_start is not None and self._t_last is not None
                   else 0.0)
        out = {
            "steps": self._steps,
            "examples": self._examples,
            "elapsed_sec": round(elapsed, 4),
        }
        if elapsed > 0:
            out["steps_per_sec"] = round((self._steps - 1) / elapsed, 3)
            if self._examples:
                per_step = self._examples / max(self._steps, 1)
                out["examples_per_sec"] = round(
                    (self._steps - 1) * per_step / elapsed, 2)
        if self._times_ms:
            ts = sorted(self._times_ms)
            out["step_time_ms"] = {
                "mean": round(sum(ts) / len(ts), 3),
                "p50": round(_metrics.sorted_percentile(ts, 50), 3),
                "p95": round(_metrics.sorted_percentile(ts, 95), 3),
                "p99": round(_metrics.sorted_percentile(ts, 99), 3),
                "max": round(ts[-1], 3),
            }
        if self._last_loss is None and self._pending_loss is not None:
            try:
                self._last_loss = float(self._pending_loss)
            except (TypeError, ValueError):
                pass
        if self._last_loss is not None:
            out["last_loss"] = self._last_loss
        return out
