"""Declarative SLOs evaluated per telemetry tick against interval deltas.

A crash is loud; *slow death* — p99 latency creeping past budget, error
rate climbing, throughput sagging, the queue backing up — is silent until
someone reads a dashboard. This module turns those conditions into typed
specs the telemetry exporter evaluates every export tick:

    SLO("serving/request_latency_ms", p=99, max_ms=250)     # latency ceiling
    SLO("serving/queue_depth", max_value=512)               # gauge ceiling
    SLO("serving/requests_retired", min_rate=10)            # QPS floor
    SLO("serving/requests_failed", max_ratio=0.01,          # error-rate cap
        over="serving/requests_retired")

Every evaluation runs on ONE interval's deltas (the
:class:`~paddle_tpu.monitor.telemetry.TelemetrySample`), not lifetime
aggregates — a latency regression shows up within one tick even after a
million healthy requests. A breach:

* increments ``slo/breaches`` (and ``slo/<spec>/breaches``),
* records an ``slo_breach`` flight-recorder event carrying the offending
  window (tick seq/t/dt, observed value, threshold),
* invokes the monitor's ``on_breach`` callback — the serving engine wires
  this (opt-in per spec via ``degrade=True``, the default) to flip
  ``engine.health()`` to ``degraded``, so the PR 7 recovery ladder and
  external health checks see slow-death, not just exceptions.

A tick with zero breaches invokes ``on_clear`` so a degraded engine
recovers once the signal is healthy again.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from . import metrics as _mx

__all__ = ["SLO", "Breach", "SLOMonitor", "parse_slos"]

_c_breaches = _mx.counter(
    "slo/breaches", help="SLO breaches across all specs and ticks")
_c_evals = _mx.counter(
    "slo/evaluations", help="per-tick SLO spec evaluations performed")


class Breach:
    """One spec violated on one telemetry tick."""

    __slots__ = ("slo", "value", "threshold", "window")

    def __init__(self, slo: "SLO", value: float, threshold: float,
                 window: dict):
        self.slo = slo
        self.value = value
        self.threshold = threshold
        self.window = window

    def to_doc(self) -> dict:
        # key is "slo_kind", not "kind": the doc doubles as the
        # flight-recorder event payload, whose own positional is "kind"
        return {"slo": self.slo.name, "slo_kind": self.slo.kind,
                "metric": self.slo.metric, "value": self.value,
                "threshold": self.threshold, "window": self.window}

    def __repr__(self):
        return ("Breach(%s: %s=%.4g vs %.4g over %.3gs)"
                % (self.slo.name, self.slo.kind, self.value,
                   self.threshold, self.window.get("dt_s", 0.0)))


class SLO:
    """One declarative objective over one instrument. Exactly one mode:

    * ``p`` + ``max_ms`` — interval percentile of a histogram must stay
      <= ``max_ms`` (any histogram unit works; the name says ms because
      every latency histogram here is ms),
    * ``max_value`` — gauge ceiling (queue depth, pool utilization),
    * ``min_rate`` — counter-rate floor per second (QPS/throughput); only
      evaluated on ticks where the counter moved at all unless
      ``min_rate_strict=True`` (an idle engine is not a breach),
    * ``max_ratio`` + ``over`` — interval error-rate cap:
      delta(metric)/delta(over) <= max_ratio (skipped while delta(over)
      is 0).

    ``degrade=False`` keeps a breach observational (counted + recorded,
    but the engine's health callback is not invoked for it).
    """

    __slots__ = ("metric", "kind", "p", "threshold", "over", "degrade",
                 "min_rate_strict", "name", "_warned_type")

    def __init__(self, metric: str, p: Optional[float] = None,
                 max_ms: Optional[float] = None,
                 max_value: Optional[float] = None,
                 min_rate: Optional[float] = None,
                 max_ratio: Optional[float] = None,
                 over: Optional[str] = None,
                 degrade: bool = True,
                 min_rate_strict: bool = False,
                 name: Optional[str] = None):
        modes = [m for m, on in (
            ("percentile", max_ms is not None),
            ("ceiling", max_value is not None),
            ("rate_floor", min_rate is not None),
            ("error_rate", max_ratio is not None)) if on]
        if len(modes) != 1:
            raise ValueError(
                "SLO(%r) needs exactly one of max_ms/max_value/min_rate/"
                "max_ratio (got %s)" % (metric, modes or "none"))
        self.kind = modes[0]
        if self.kind == "percentile":
            if p is None:
                raise ValueError("SLO(%r, max_ms=...) needs p=<percentile>"
                                 % metric)
            self.threshold = float(max_ms)
        elif self.kind == "ceiling":
            self.threshold = float(max_value)
        elif self.kind == "rate_floor":
            self.threshold = float(min_rate)
        else:
            if not over:
                raise ValueError("SLO(%r, max_ratio=...) needs over=<counter>"
                                 % metric)
            self.threshold = float(max_ratio)
        self.metric = metric
        self.p = None if p is None else float(p)
        self.over = over
        self.degrade = bool(degrade)
        self.min_rate_strict = bool(min_rate_strict)
        self._warned_type = False
        if name:
            self.name = name
        elif self.kind == "percentile":
            self.name = "%s:p%g" % (metric, self.p)
        else:
            self.name = "%s:%s" % (metric, self.kind)

    def evaluate(self, sample) -> Optional[Breach]:
        """Check this spec against one TelemetrySample; None = healthy or
        not evaluable this tick (no observations in the window)."""
        window = {"seq": sample.seq, "t": sample.t, "dt_s": sample.dt_s}
        if self.kind == "percentile":
            v = sample.histogram_interval_percentile(self.metric, self.p)
            if v is None:
                return None
            d = sample.histogram_delta(self.metric) or {}
            window["observations"] = d.get("count", 0)
            return Breach(self, v, self.threshold, window) \
                if v > self.threshold else None
        if self.kind == "ceiling":
            v = sample.gauge_value(self.metric)
            if v is None:
                snap = sample.metrics.get(self.metric)
                if snap is not None and snap.get("type") != "gauge" \
                        and not self._warned_type:
                    # a ceiling on a counter would compare against the
                    # LIFETIME total — refuse, loudly, once
                    self._warned_type = True
                    import logging

                    logging.getLogger("paddle_tpu").warning(
                        "SLO %s: max_value (gauge ceiling) on a %s "
                        "instrument — spec is inert; use min_rate/"
                        "max_ratio for counters", self.name,
                        snap.get("type"))
                return None
            return Breach(self, v, self.threshold, window) \
                if v > self.threshold else None
        if self.kind == "rate_floor":
            if sample.dt_s <= 0:
                return None
            delta = sample.counter_delta(self.metric)
            if delta == 0 and not self.min_rate_strict:
                return None  # idle, not slow
            v = delta / sample.dt_s
            return Breach(self, v, self.threshold, window) \
                if v < self.threshold else None
        # error_rate
        den = sample.counter_delta(self.over)
        if den <= 0:
            return None
        v = sample.counter_delta(self.metric) / den
        window["errors"] = sample.counter_delta(self.metric)
        window["total"] = den
        return Breach(self, v, self.threshold, window) \
            if v > self.threshold else None

    def __repr__(self):
        return "SLO(%s, %s<=%g)" % (self.name, self.kind, self.threshold) \
            if self.kind != "rate_floor" \
            else "SLO(%s, rate>=%g/s)" % (self.name, self.threshold)


def parse_slos(text: str) -> List[SLO]:
    """``PADDLE_TPU_SLO`` grammar: ``;``-separated entries,
    ``metric:p99<=250`` (percentile ms) | ``metric<=512`` (gauge ceiling)
    | ``metric>=10/s`` (rate floor) | ``metric/over<=0.01`` (error rate —
    metric and denominator joined by ``over=``:
    ``metric<=0.01 over other``)."""
    out: List[SLO] = []
    for raw in text.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        over = None
        if " over " in entry:
            entry, over = entry.split(" over ", 1)
            over = over.strip()
        if ">=" in entry:
            if over:
                raise ValueError(
                    "bad SLO entry %r: 'over' only combines with an "
                    "error-rate cap (metric<=ratio over denominator), "
                    "not a >= rate floor" % raw)
            metric, rhs = entry.split(">=", 1)
            rhs = rhs.strip()
            if rhs.endswith("/s"):
                rhs = rhs[:-2]
            out.append(SLO(metric.strip(), min_rate=float(rhs)))
            continue
        if "<=" not in entry:
            raise ValueError("bad SLO entry %r (need <= or >=)" % raw)
        lhs, rhs = entry.split("<=", 1)
        lhs = lhs.strip()
        val = float(rhs)
        if ":p" in lhs:
            metric, p = lhs.rsplit(":p", 1)
            out.append(SLO(metric, p=float(p), max_ms=val))
        elif over:
            out.append(SLO(lhs, max_ratio=val, over=over))
        else:
            out.append(SLO(lhs, max_value=val))
    return out


class SLOMonitor:
    """Evaluates a spec list on every telemetry tick (register
    :meth:`on_sample` as an exporter listener, or call it directly with a
    sample for synchronous drills)."""

    def __init__(self, specs: Sequence[SLO],
                 on_breach: Optional[Callable[[Breach], None]] = None,
                 on_clear: Optional[Callable[[], None]] = None):
        self.specs = list(specs)
        self.on_breach = on_breach
        self.on_clear = on_clear
        self._lock = threading.Lock()
        self.breaches_total = 0
        self.last_breaches: List[Breach] = []
        # bounded breach journal (wall-time-stamped docs): what the fleet
        # autopsy joins against the phase ledger after the run — keeps the
        # most recent breaches even when last_breaches was overwritten by
        # a later clean tick
        self.history: "deque" = deque(maxlen=64)
        self._spec_counters: Dict[str, _mx.Counter] = {
            s.name: _mx.counter("slo/%s/breaches" % s.name)
            for s in self.specs}

    def on_sample(self, sample) -> List[Breach]:
        breaches: List[Breach] = []
        for spec in self.specs:
            _c_evals.inc()
            b = spec.evaluate(sample)
            if b is not None:
                breaches.append(b)
        with self._lock:
            self.last_breaches = breaches
            self.breaches_total += len(breaches)
            for b in breaches:
                self.history.append(dict(b.to_doc(), t=time.time()))
        if breaches:
            _c_breaches.inc(len(breaches))
            from . import device as _dev

            fr = _dev.flight_recorder()
            for b in breaches:
                self._spec_counters[b.slo.name].inc()
                if fr is not None:
                    fr.record_event("slo_breach", **b.to_doc())
                if self.on_breach is not None and b.slo.degrade:
                    try:
                        self.on_breach(b)
                    except Exception:
                        import logging

                        logging.getLogger("paddle_tpu").exception(
                            "SLO on_breach callback failed (ignored)")
        # recovery keys on the DEGRADE-relevant specs only: a breaching
        # observational (degrade=False) spec is counted and recorded above
        # but must not pin a healthy engine in "degraded" forever
        if not any(b.slo.degrade for b in breaches) \
                and self.on_clear is not None:
            try:
                self.on_clear()
            except Exception:
                pass
        return breaches
