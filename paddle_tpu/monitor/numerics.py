"""paddle_tpu.monitor.numerics — device-side streaming tensor statistics.

The observability column so far answers *that* a step got slow
(``metrics``/``runlog``) or *that* a NaN appeared (``device``'s
CHECK_NUMERICS watchdog). This module sees tensor *values*: per-op range
statistics streamed off the device, drift detection that warns BEFORE the
watchdog cliff, and the amax/scale calibration tables low-precision paths
(the int8 KV-page write path in ``serving/kv_cache.py``) are gated behind.

Level-gated by ``PADDLE_TPU_NUMERICS`` (one env read per run):

``0``  off (default) — nothing traced, plan/compile caches unpolluted,
       losses bit-identical to a build without this module.
``1``  stats — the Executor compiles a stats variant of the step: every
       op's floating outputs fold a compact stat row (absmax, sum, sumsq,
       zero/subnormal/overflow-proximity counts, element count) into a
       packed ``[K, 7]`` auxiliary fetch riding the compiled step — ONE
       extra device→host copy per ``run``/``run_steps`` chunk, no
       per-tensor syncs. Op identity is the same ``<slot>:<type>`` stamp
       the watchdog and named scopes use. Host side: per-op ``numerics/*``
       gauges, a log-bucketed absmax range histogram, and an EMA drift
       detector — an op's absmax trending toward its dtype's max (or
       collapsing to zero) raises :class:`NumericsDriftWarning`, records a
       ``numerics_drift`` flight event and queues a typed early-warning
       the optional :class:`~paddle_tpu.reliability.sentinel
       .DivergenceSentinel` ``drift`` rule can trip on.
``2``  calibrate — level 1 plus persistent per-tensor amax/scale tables,
       written with the tune-table discipline (JSON keyed
       ``(program fingerprint, op slot, op type)``, atomic publish,
       never-raise lookups; the file machinery IS ``tune.table``'s,
       parameterized by format tag).

``tools/numerics_report.py`` is the CLI (``--selftest`` gates CI);
``benchmarks/diag_overhead.py --numerics`` measures the armed-stats
overhead against the ≤15% contract.
"""

from __future__ import annotations

import math
import os
import threading
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import metrics as _mx

__all__ = [
    "FORMAT", "NUM_STATS", "STAT_FIELDS", "OVERFLOW_FRACTION",
    "STATS_ENV_KEY",
    "stats_level",
    "fold_op_stats", "merge_stat_rows",
    "accumulate", "snapshot", "drain_drift_events", "reset",
    "NumericsDriftWarning",
    "table_path", "read_calibration", "record_calibration",
    "lookup_amax", "lookup_scale",
    "kv_fingerprint", "record_kv_calibration", "kv_scale",
]

# calibration-table format tag (tune.table validates it; see table_path)
FORMAT = "paddle_tpu.numerics/1"

# the env key stat rows accumulate under inside the traced name->array
# environment — the stats twin of interpreter.NUMERICS_ENV_KEY (same
# legal-aux-flow argument); defined here so executor + interpreter share it
STATS_ENV_KEY = "__numerics_stats__"

# packed per-op stat row layout (float32, device side):
#   0 absmax   max(|x|) over the op's floating outputs
#   1 sum      Σx       (f32 accumulation, bf16-safe)
#   2 sumsq    Σx²
#   3 zeros    count(x == 0)
#   4 subnorm  count(0 < |x| < dtype.tiny)
#   5 near_of  count(|x| > OVERFLOW_FRACTION * dtype.max)
#   6 count    element count
STAT_FIELDS = ("absmax", "sum", "sumsq", "zeros", "subnormal",
               "near_overflow", "count")
NUM_STATS = len(STAT_FIELDS)

# |x| beyond this fraction of the output dtype's finite max counts toward
# the overflow-proximity fraction (1/16 = within 4 doublings of the cliff)
OVERFLOW_FRACTION = 0.0625

_m_chunks = _mx.counter(
    "numerics/chunks",
    help="fetched stats chunks accumulated (one per run/run_steps dispatch "
         "with PADDLE_TPU_NUMERICS armed)")
_m_drift = _mx.counter(
    "numerics/drift_warnings",
    help="EMA drift early-warnings raised (absmax trending toward overflow "
         "or collapsing to zero) BEFORE the CHECK_NUMERICS watchdog trips")
_m_calib_writes = _mx.counter(
    "numerics/calibration_writes",
    help="atomic calibration-table publishes (PADDLE_TPU_NUMERICS=2)")
# absmax spans subnormals to bf16-overflow pressure — log-spaced buckets
# (metrics.log_buckets, the satellite this histogram exists to exercise)
_m_absmax = _mx.histogram(
    "numerics/absmax",
    buckets=_mx.log_buckets(1e-8, 1e4, per_decade=1),
    help="per-op per-chunk absmax samples, log-bucketed 1e-8..1e4")

_lock = threading.RLock()
# label -> last accumulated stats dict (the snapshot/flight-embed surface)
_last: Dict[str, Dict[str, Any]] = {}
# label -> EMA drift state
_ema: Dict[str, Dict[str, float]] = {}
# typed early warnings not yet drained by a sentinel
_pending: List[dict] = []
_warned: set = set()  # (label, kind) pairs already python-warned
# (fingerprint) -> {(slot, type): amax} pending calibration maxima
_calib: Dict[str, Dict[Tuple[str, str], float]] = {}
#: per-label resolved gauge tuples (accumulate() hot-path cache)
_gauges: Dict[str, tuple] = {}


class NumericsDriftWarning(UserWarning):
    """An op's activation range is drifting toward overflow (or collapsing
    to zero): the typed early warning raised ahead of the CHECK_NUMERICS
    watchdog. Carries ``label``/``kind``/``absmax``/``chunks_to_overflow``
    as attributes for programmatic consumers."""

    def __init__(self, label: str, kind: str, absmax: float,
                 chunks_to_overflow: Optional[float] = None):
        self.label = label
        self.kind = kind
        self.absmax = absmax
        self.chunks_to_overflow = chunks_to_overflow
        horizon = ("" if chunks_to_overflow is None else
                   " (~%.1f chunks to overflow)" % chunks_to_overflow)
        super().__init__(
            "numerics drift: op %s absmax %.4g %s%s — raise "
            "PADDLE_TPU_CHECK_NUMERICS tolerance work now, not after the "
            "watchdog trips" % (label, absmax, kind, horizon))


def stats_level() -> int:
    """``PADDLE_TPU_NUMERICS`` clamped to 0..2 (module docstring); read
    per call — the executor reads it once per run as part of plan-key
    construction, which is the whole level-0 cost."""
    raw = os.environ.get("PADDLE_TPU_NUMERICS", "0").strip()
    try:
        lvl = int(raw)
    except ValueError:
        lvl = 1 if raw.lower() in ("true", "yes", "on") else 0
    return max(0, min(2, lvl))


#: ``PADDLE_TPU_NUMERICS_EVERY`` — fold stats every Nth run/run_steps
#: chunk (default 4, chunk 0 always sampled). Per-op in-graph stat
#: reductions are memory-bound; sampling divides their steady-state cost
#: by N while the EMA drift detector and calibration maxima still see a
#: regular tick stream. Set to 1 to observe every chunk (the drift
#: drill and the parity tests do).
EVERY_ENV_KEY = "PADDLE_TPU_NUMERICS_EVERY"
DEFAULT_EVERY = 4


def stats_every() -> int:
    raw = os.environ.get(EVERY_ENV_KEY, "").strip()
    if not raw:
        return DEFAULT_EVERY
    try:
        return max(1, int(raw))
    except ValueError:
        return DEFAULT_EVERY


# -- device side (called at jit-trace time from the block interpreter) --------


def merge_stat_rows(a, b):
    """Merge two packed stat rows: absmax by max, everything else by sum.
    Used across an op's multiple outputs and across the gradient-
    accumulation scan's microbatches (executor ``_mb_step``)."""
    import jax.numpy as jnp

    return jnp.concatenate([jnp.maximum(a[:1], b[:1]), a[1:] + b[1:]])


_stat_reduce = None


def _build_stat_reduce():
    """Build the stat reduction lazily (keeps jax out of module import).

    All seven stats come out of ONE variadic ``lax.reduce`` — a single
    kernel per observed op.  That matters more than per-element speed:
    on XLA CPU each separate in-graph reduction kernel pays a cold-cache
    pass over the tensor plus dispatch, so seven ``jnp.sum``/``jnp.max``
    calls per op cost ~3-6x the fused form and blow the diag_overhead
    15% contract.  The reduce is wrapped in a ``custom_jvp`` with a zero
    tangent: stats are diagnostics, not part of the loss, and the
    variadic-reduce JVP rule rejects the symbolic zero tangents it would
    otherwise be handed under ``value_and_grad``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.custom_jvp
    def _reduce(vf, tiny, ovf):
        av = jnp.abs(vf)
        operands = (
            av,
            vf,
            vf * vf,
            (vf == 0).astype(jnp.float32),
            ((av < tiny) & (vf != 0)).astype(jnp.float32),
            (av > ovf).astype(jnp.float32),
        )
        inits = (jnp.float32(-jnp.inf),) + (jnp.float32(0),) * 5

        def _comp(a, b):
            return (jnp.maximum(a[0], b[0]), a[1] + b[1], a[2] + b[2],
                    a[3] + b[3], a[4] + b[4], a[5] + b[5])

        red = lax.reduce(operands, inits, _comp, (0,))
        return jnp.stack(list(red) + [jnp.float32(vf.size)])

    @_reduce.defjvp
    def _reduce_jvp(primals, tangents):
        out = _reduce(*primals)
        return out, jnp.zeros_like(out)

    return _reduce


def _stat_row(v):
    """Packed [7] stat row for one tensor: absmax, sum, sumsq, zeros,
    subnormal, near_overflow, count — all exact, one fused kernel."""
    import jax.numpy as jnp
    from jax import lax

    global _stat_reduce
    if _stat_reduce is None:
        _stat_reduce = _build_stat_reduce()
    fi = jnp.finfo(v.dtype)
    vf = lax.stop_gradient(v).astype(jnp.float32).ravel()
    return _stat_reduce(vf, jnp.float32(fi.tiny),
                        jnp.float32(OVERFLOW_FRACTION * float(fi.max)))


def fold_op_stats(op, env: Dict[str, Any], layout, pos: int) -> None:
    """Fold each floating output of ``op`` into one packed stat row
    appended to ``env[STATS_ENV_KEY]``; record ``(label, outputs,
    min-dtype-max)`` at the same index in ``layout`` (index-overwrite, the
    watchdog's retrace-stability idiom)."""
    import jax.numpy as jnp

    row = None
    outs = []
    fmax = None
    for name in op.output_arg_names:
        v = env.get(name)
        dt = getattr(v, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            continue
        if v.size == 0:
            continue
        # Only the op's FIRST floating output -- its primary result -- is
        # folded.  Secondary outputs (optimizer moment buffers, auxiliary
        # softmax tensors) would triple the folded volume on optimizer ops
        # and blow the diag_overhead 15% contract without adding signal:
        # drift in optimizer state always shows up in the param output too.
        row = _stat_row(v)
        fmax = float(jnp.finfo(dt).max)
        outs.append(name)
        break
    if row is None:
        return
    rows = env.setdefault(STATS_ENV_KEY, [])
    k = len(rows)
    slot = op.attrs.get("__op_slot__")
    entry = ("%d:%s" % (pos if slot is None else slot, op.type),
             tuple(outs), fmax)
    if k < len(layout):
        layout[k] = entry
    else:
        layout.append(entry)
    rows.append(row)


# -- host side: accumulation + drift ------------------------------------------


def _drift_params() -> Tuple[float, float, float]:
    """(ema_decay, horizon_chunks, min_trend_bits) — env-tunable but the
    defaults are the contract the selftest drill pins."""
    def _f(name, default):
        try:
            return float(os.environ.get(name, "") or default)
        except ValueError:
            return default
    return (_f("PADDLE_TPU_NUMERICS_EMA", 0.5),
            _f("PADDLE_TPU_NUMERICS_HORIZON", 8.0),
            _f("PADDLE_TPU_NUMERICS_MIN_TREND", 0.25))


def _emit_drift(label: str, kind: str, absmax: float,
                chunks_to_overflow: Optional[float]) -> None:
    _m_drift.inc()
    ev = {"op": label, "kind": kind, "absmax": float(absmax),
          "chunks_to_overflow": chunks_to_overflow}
    _pending.append(ev)
    if len(_pending) > 256:  # bounded: a sentinel-less run must not leak
        del _pending[:len(_pending) - 256]
    try:
        from .device import flight_recorder

        fr = flight_recorder()
        if fr is not None:
            # "kind" would collide with record_event's own kind parameter
            fr.record_event("numerics_drift", op=label, drift_kind=kind,
                            absmax=float(absmax),
                            chunks_to_overflow=chunks_to_overflow)
    except Exception:
        pass
    if (label, kind) not in _warned:
        _warned.add((label, kind))
        warnings.warn(NumericsDriftWarning(label, kind, absmax,
                                           chunks_to_overflow),
                      stacklevel=3)


def _drift_update(label: str, absmax: float, fmax: Optional[float]) -> None:
    """One EMA tick per fetched chunk for one op: track log2(absmax) and
    its per-chunk trend; warn when the projected chunks-to-overflow drops
    inside the horizon, or when a previously-live range collapses to 0."""
    decay, horizon, min_trend = _drift_params()
    st = _ema.get(label)
    if not math.isfinite(absmax):
        # the watchdog owns non-finite attribution; drift is about the ramp
        return
    if absmax <= 0.0:
        if st is not None and st.get("log2", -1e9) > -20.0:
            _emit_drift(label, "collapsed-to-zero", absmax, None)
            _ema[label] = {"log2": -1e9, "trend": 0.0, "chunks": 0}
        return
    l2 = math.log2(absmax)
    if st is None or st.get("log2", -1e9) <= -1e8:
        _ema[label] = {"log2": l2, "trend": 0.0, "chunks": 1}
        return
    delta = l2 - st["log2"]
    st["log2"] = st["log2"] + decay * (l2 - st["log2"])
    st["trend"] = st["trend"] + decay * (delta - st["trend"])
    st["chunks"] += 1
    if fmax is None or st["chunks"] < 3:
        return  # need history before a trend is evidence
    trend = st["trend"]
    if trend > min_trend:
        to_go = (math.log2(fmax) - l2) / trend
        if to_go <= horizon:
            _emit_drift(label, "trending-toward-overflow", absmax, to_go)


def accumulate(arr, layout: Sequence[Tuple[str, tuple, Optional[float]]],
               fingerprint: Optional[str] = None,
               driver: str = "run") -> None:
    """Fold one fetched stats tensor into the host registries.

    ``arr``: float32 ``[K, NUM_STATS]`` (one step) or ``[steps, K,
    NUM_STATS]`` (a fused run_steps chunk — reduced to per-chunk
    aggregates here, so drift sees one EMA tick per chunk either way).
    ``layout``: the compiled step's trace-time record — row k is
    ``(label, output names, min dtype max)``. Never raises into the step
    (the step already succeeded; losing a stats sample is acceptable,
    killing the run is not)."""
    import numpy as np

    try:
        a = np.asarray(arr, np.float64)  # THE one device→host stats copy
        if a.ndim == 2:
            a = a[None]
        if a.ndim != 3 or a.shape[-1] != NUM_STATS:
            return
        # tolist() once: per-element float() on numpy scalars is ~10x the
        # cost and this path runs on every run()/run_steps chunk.
        absmax = a[:, :, 0].max(axis=0).tolist()
        sums = a[:, :, 1:].sum(axis=0).tolist()
        mx_on = _mx._enabled
        calibrate = stats_level() >= 2 and fingerprint is not None
        with _lock:
            _m_chunks.inc()
            for k in range(a.shape[1]):
                if k < len(layout):
                    label, outs, fmax = layout[k]
                else:
                    label, outs, fmax = "?%d:?" % k, (), None
                am = absmax[k]
                s, ss, zeros, sub, near, n = sums[k]
                if n <= 0.0:
                    # the all-zero placeholder a stats-armed step packs
                    # when the program has no floating outputs (e.g. a
                    # startup program of int fills) — not an op
                    continue
                n = max(n, 1.0)
                stats = {
                    "absmax": am,
                    "mean": s / n,
                    "rms": math.sqrt(max(ss / n, 0.0)),
                    "zero_frac": zeros / n,
                    "subnormal_frac": sub / n,
                    "overflow_frac": near / n,
                    "count": n,
                    "outputs": list(outs),
                    "dtype_max": fmax,
                    "driver": driver,
                }
                prev = _last.get(label)
                stats["chunks"] = (prev["chunks"] + 1) if prev else 1
                _last[label] = stats
                if mx_on:
                    gs = _gauges.get(label)
                    if gs is None:
                        # registry lookups + name formatting are the hot
                        # cost at one chunk per step; resolve each label's
                        # six gauges once and keep the objects.
                        pfx = "numerics/%s/" % label
                        gs = tuple(_mx.gauge(pfx + f) for f in (
                            "absmax", "mean", "rms", "zero_frac",
                            "subnormal_frac", "overflow_frac"))
                        _gauges[label] = gs
                    gs[0].set(am if math.isfinite(am) else 0.0)
                    gs[1].set(stats["mean"])
                    gs[2].set(stats["rms"])
                    gs[3].set(stats["zero_frac"])
                    gs[4].set(stats["subnormal_frac"])
                    gs[5].set(stats["overflow_frac"])
                    if math.isfinite(am) and am > 0:
                        _m_absmax.observe(am)
                _drift_update(label, am, fmax)
                if calibrate and math.isfinite(am):
                    slot, _, typ = label.partition(":")
                    pend = _calib.setdefault(fingerprint, {})
                    key = (slot, typ)
                    pend[key] = max(pend.get(key, 0.0), am)
            if calibrate:
                _flush_calibration()
    except Exception:  # pragma: no cover - belt and braces
        from ..log import vlog

        vlog(1, "numerics: stats accumulation failed for one chunk "
                "(driver=%s); sample dropped", driver)


def snapshot() -> Dict[str, dict]:
    """{op label: latest accumulated stats} — the flight-dump /
    run-ledger embed and the ``tools/numerics_report`` surface."""
    with _lock:
        return {k: dict(v) for k, v in _last.items()}


def drain_drift_events() -> List[dict]:
    """Return-and-clear the queued typed early warnings — the
    ``DivergenceSentinel(drift=True)`` rule's feed."""
    with _lock:
        out = list(_pending)
        del _pending[:]
    return out


def reset() -> None:
    """Drop accumulated stats, EMA state and pending warnings (tests)."""
    with _lock:
        _last.clear()
        _ema.clear()
        _gauges.clear()
        del _pending[:]
        _warned.clear()
        _calib.clear()


# -- calibration tables (tune-table discipline, parameterized format) ---------


def table_path() -> Optional[str]:
    """Where the calibration table lives: ``PADDLE_TPU_NUMERICS_TABLE``
    wins; else ``numerics_calib.json`` next to the persistent compile
    cache; None when neither is configured (calibration then accumulates
    in-process only)."""
    p = os.environ.get("PADDLE_TPU_NUMERICS_TABLE", "").strip()
    if p:
        return p
    from ..compile_cache import compile_cache_dir

    d = compile_cache_dir()
    return os.path.join(d, "numerics_calib.json") if d else None


def read_calibration(path: Optional[str] = None) -> Optional[Dict[str, dict]]:
    """Entries of the calibration table (mtime-cached, corruption logged
    once and tolerated — ``tune.table.read_entries`` with this module's
    format tag), or None when absent/corrupt/unconfigured."""
    from ..tune import table as _tbl

    return _tbl.read_entries(path or table_path(), fmt=FORMAT)


def record_calibration(fingerprint: str, slot: str, typ: str, amax: float,
                       *, bits: int = 8,
                       path: Optional[str] = None) -> Optional[str]:
    """Merge one per-tensor amax into the table (running max against any
    existing entry; read-modify-write, atomic publish). The stored
    ``scale`` is the symmetric int-``bits`` quantization step
    ``amax / (2**(bits-1) - 1)``. Returns the table path or None when no
    location is configured."""
    from ..tune import table as _tbl

    path = path or table_path()
    if not path:
        return None
    qmax = float(2 ** (bits - 1) - 1)
    with _lock:
        entries = dict(read_calibration(path) or {})
        key = _tbl.entry_key(fingerprint, slot, typ)
        old = entries.get(key)
        if old is not None:
            try:
                amax = max(amax, float(old["config"].get("amax", 0.0)))
            except (TypeError, ValueError):
                pass
        entries[key] = {"config": {
            "amax": float(amax),
            "scale": float(amax) / qmax if amax > 0 else 0.0,
            "bits": int(bits),
        }}
        out = _tbl.write_entries(path, entries, fmt=FORMAT)
    if _mx._enabled:
        _m_calib_writes.inc()
    return out


def _flush_calibration() -> None:
    """Publish pending in-memory amax maxima (called under _lock from
    ``accumulate`` at level 2). Best-effort: no table location configured
    means calibration stays in-process."""
    path = table_path()
    if not path:
        return
    for fp, pend in _calib.items():
        for (slot, typ), amax in pend.items():
            record_calibration(fp, slot, typ, amax, path=path)
    _calib.clear()


def lookup_amax(fingerprint: str, slot: str, typ: str,
                path: Optional[str] = None) -> Optional[float]:
    """Calibrated amax for ``(fingerprint, slot, type)`` or None. NEVER
    raises — a corrupt/absent table degrades to None, because consumers
    (the int8 KV gate) must come up regardless."""
    try:
        from ..tune import table as _tbl

        entries = read_calibration(path)
        if not entries:
            return None
        ent = entries.get(_tbl.entry_key(fingerprint, slot, typ))
        if ent is None:
            return None
        v = float(ent["config"]["amax"])
        return v if math.isfinite(v) and v > 0 else None
    except Exception:
        return None


def lookup_scale(fingerprint: str, slot: str, typ: str, *, bits: int = 8,
                 path: Optional[str] = None) -> Optional[float]:
    """Symmetric int-``bits`` quantization scale from the calibrated amax,
    or None when uncalibrated (the caller keeps its fp path)."""
    amax = lookup_amax(fingerprint, slot, typ, path=path)
    if amax is None:
        return None
    return amax / float(2 ** (bits - 1) - 1)


# -- KV-cache calibration (the serving int8 gate) -----------------------------


def kv_fingerprint(n_layer: int, n_head: int, d_head: int, dtype) -> str:
    """Stable identity for a model's KV tensors — the calibration-table
    fingerprint the serving engine keys its int8 gate on (a Program
    fingerprint doesn't exist for the AOT serving path)."""
    import hashlib

    h = hashlib.sha1(("kv|%d|%d|%d|%s" % (
        int(n_layer), int(n_head), int(d_head), str(dtype))).encode())
    return h.hexdigest()[:16]


def record_kv_calibration(fingerprint: str, k_amax: float, v_amax: float,
                          path: Optional[str] = None) -> Optional[str]:
    """Persist a KV-cache calibration pass's amax pair under
    ``(fingerprint, "kv", "k"/"v")``."""
    out = record_calibration(fingerprint, "kv", "k", float(k_amax), path=path)
    record_calibration(fingerprint, "kv", "v", float(v_amax), path=path)
    return out


def kv_scale(fingerprint: str,
             path: Optional[str] = None) -> Optional[Tuple[float, float]]:
    """(k_scale, v_scale) int8 steps from a calibrated KV amax pair, or
    None when either half is uncalibrated — the never-raise gate
    ``ServingConfig(kv_dtype="int8")`` consults before swapping in the
    quantized page pool."""
    ks = lookup_scale(fingerprint, "kv", "k", path=path)
    vs = lookup_scale(fingerprint, "kv", "v", path=path)
    if ks is None or vs is None or ks <= 0 or vs <= 0:
        return None
    return ks, vs
