"""Host-span tracer: nested wall-clock spans → Chrome-trace/Perfetto JSON.

The role the reference splits between ``platform/profiler.cc`` RecordEvent
and ``tools/timeline.py`` (CUPTI → chrome://tracing converter): record
named, nested host spans with microsecond timestamps and export them as a
``chrome://tracing`` / Perfetto-loadable JSON — no TensorBoard required.
It composes with the existing ``jax.profiler`` device trace: spans opened
with ``device=True`` (and ``profiler.record_event``) also enter a
``jax.profiler.TraceAnnotation`` so the same name shows up in the XLA
device timeline when one is being captured.

Activation: ``start_tracing()`` explicitly, or set ``PADDLE_TPU_TRACE_FILE``
— tracing then starts at import and the Chrome trace is written to that
path at interpreter exit. Hot paths guard on ``active()`` (a single module
bool read) so an idle tracer costs one branch.

Two file formats:

* **raw spans** (``save_spans``): ``{"schema": "paddle_tpu.host_spans/v1",
  "spans": [{name, cat, ts_us, dur_us, pid, tid, args}]}`` — the stable
  interchange format ``tools/dump_metrics.py`` converts from.
* **Chrome trace** (``save_chrome_trace`` / ``to_chrome_trace``): complete
  ("ph": "X") events under ``traceEvents``, plus process/thread metadata.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "span", "start_tracing", "stop_tracing", "active", "get_spans",
    "clear_spans", "save_spans", "load_spans", "to_chrome_trace",
    "save_chrome_trace", "SPAN_SCHEMA",
    "virtual_track", "record_span", "record_instant", "now_us",
]

SPAN_SCHEMA = "paddle_tpu.host_spans/v1"

# Test-only clock skew (µs), read once at import: every timestamp this
# process records OR reports (now_us(), span()/instant(), record_span's
# explicit ts) is shifted by it — the process behaves as if its
# perf_counter epoch differed. The fleet clock-offset handshake
# (fleet.replica.ProcessReplica) measures exactly this shift, and
# tools/fleet_trace.py's selftest injects a known skew into its workers
# to assert the midpoint estimate recovers it. Never set in production.
try:
    _skew_us: int = int(
        os.environ.get("PADDLE_TPU_TRACE_CLOCK_SKEW_US", "0") or 0)
except ValueError:
    _skew_us = 0

_active: bool = False
_spans: List[Dict[str, Any]] = []
_spans_lock = threading.Lock()
_tls = threading.local()  # per-thread nesting depth
_trace_file: Optional[str] = None

# Virtual tracks: named synthetic (pid, tid) rows for spans whose natural
# grouping is NOT the emitting thread — e.g. one Chrome-trace row per
# serving batch slot, regardless of which host thread drove the engine.
# Synthetic tids count down from -1 so they can never collide with real
# thread idents (which are non-negative).
_track_ids: Dict[str, int] = {}
_track_names: Dict[int, str] = {}
_next_track = [-1]

# Whole-process tracing (PADDLE_TPU_TRACE_FILE) on a long-running job must
# not grow memory without bound: past this cap new spans are dropped (count
# kept) and a single warning is logged. Override with
# PADDLE_TPU_TRACE_MAX_SPANS.
_max_spans: int = int(os.environ.get("PADDLE_TPU_TRACE_MAX_SPANS", "1000000"))
_dropped: int = 0


def active() -> bool:
    return _active


def now_us() -> int:
    """This process's span clock, µs: ``perf_counter`` plus the injected
    test skew — the value cross-process clock handshakes must report so
    the handshake measures the same clock the spans are stamped with."""
    return time.perf_counter_ns() // 1000 + _skew_us


def start_tracing() -> None:
    """Begin recording host spans (idempotent; keeps prior spans)."""
    global _active
    _active = True


def stop_tracing(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Stop recording; optionally write the Chrome trace to ``path``.
    Returns the recorded spans (still held — ``clear_spans()`` drops them)."""
    global _active
    _active = False
    spans = get_spans()
    if path:
        save_chrome_trace(path, spans)
    return spans


def get_spans() -> List[Dict[str, Any]]:
    with _spans_lock:
        return list(_spans)


def clear_spans() -> None:
    global _dropped
    with _spans_lock:
        _spans.clear()
        _dropped = 0


def _record(name: str, cat: str, t0_us: int, dur_us: int,
            args: Optional[dict], depth: int = 0) -> None:
    rec = {
        "name": name,
        "cat": cat,
        "ts_us": t0_us,
        "dur_us": dur_us,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "depth": depth,
    }
    if args:
        rec["args"] = args
    global _dropped
    with _spans_lock:
        if len(_spans) >= _max_spans:
            _dropped += 1
            just_hit = _dropped == 1
        else:
            _spans.append(rec)
            just_hit = False
    if just_hit:
        import logging

        logging.getLogger("paddle_tpu").warning(
            "monitor.tracer: span buffer full (%d spans); further spans are "
            "dropped — raise PADDLE_TPU_TRACE_MAX_SPANS or scope tracing "
            "with start_tracing()/stop_tracing()", _max_spans)


@contextlib.contextmanager
def span(name: str, cat: str = "host", args: Optional[dict] = None,
         device: bool = False):
    """Record a nested wall-clock span.

    ``device=True`` additionally enters ``jax.profiler.TraceAnnotation`` so
    the span lands in an active XLA device trace too (the record_event
    composition). Nesting is implicit — Chrome's trace viewer stacks
    overlapping complete events per (pid, tid) by time containment.
    """
    if not _active and not device:
        yield
        return
    ann = None
    if device:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
    depth = getattr(_tls, "depth", 0)
    _tls.depth = depth + 1
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        dur = time.perf_counter_ns() - t0
        _tls.depth = depth
        if ann is not None:
            ann.__exit__(None, None, None)
        if _active:
            _record(name, cat, t0 // 1000 + _skew_us, max(1, dur // 1000),
                    args, depth)


def instant(name: str, cat: str = "host", args: Optional[dict] = None) -> None:
    """Zero-duration marker (rendered as an instant event)."""
    if not _active:
        return
    _record(name, cat, now_us(), 0, args)


__all__.append("instant")


def virtual_track(name: str) -> int:
    """Stable synthetic tid for a named trace row (``"serving slot 3"``).
    The name lands in the Chrome trace's ``thread_name`` metadata so
    Perfetto shows a labeled track instead of a thread id."""
    with _spans_lock:
        tid = _track_ids.get(name)
        if tid is None:
            tid = _next_track[0]
            _next_track[0] -= 1
            _track_ids[name] = tid
            _track_names[tid] = name
        return tid


def record_span(name: str, ts_us: int, dur_us: int, cat: str = "host",
                track: Optional[str] = None,
                args: Optional[dict] = None) -> None:
    """Record a complete span with EXPLICIT timestamps (µs on the
    ``time.perf_counter`` clock — the same clock :func:`span` uses, so
    mixed implicit/explicit spans stay on one timeline). ``track`` routes
    the span onto a named virtual row (see :func:`virtual_track`) instead
    of the calling thread. The serving request tracer reconstructs
    request lifecycles from wall-clock timestamps through this."""
    if not _active:
        return
    tid = virtual_track(track) if track is not None else None
    rec = {
        "name": name,
        "cat": cat,
        "ts_us": int(ts_us) + _skew_us,
        "dur_us": max(0, int(dur_us)),
        "pid": os.getpid(),
        "tid": tid if tid is not None else threading.get_ident(),
        "depth": 0,
    }
    if track is not None:
        # the label rides the span record itself, so a raw-span file
        # converted in ANOTHER process (tools/dump_metrics --to-chrome)
        # still renders named tracks, not synthetic tids
        rec["track"] = track
    if args:
        rec["args"] = args
    global _dropped
    with _spans_lock:
        if len(_spans) >= _max_spans:
            _dropped += 1
        else:
            _spans.append(rec)


def record_instant(name: str, ts_us: int, cat: str = "host",
                   track: Optional[str] = None,
                   args: Optional[dict] = None) -> None:
    """Explicit-timestamp zero-duration marker on an optional virtual
    track (terminal request states in the serving trace)."""
    record_span(name, ts_us, 0, cat=cat, track=track, args=args)


# -- serialization ------------------------------------------------------------

def save_spans(path: str, spans: Optional[List[dict]] = None) -> str:
    """Write the raw host-span interchange file (see module docstring)."""
    doc = {"schema": SPAN_SCHEMA, "spans": spans if spans is not None else get_spans()}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def load_spans(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and doc.get("schema") == SPAN_SCHEMA:
        return list(doc.get("spans", []))
    if isinstance(doc, dict) and "traceEvents" in doc:
        # accept a Chrome trace back (the dump_metrics round-trip): complete
        # events AND instant markers survive; metadata ("M") is regenerated
        # on the next export, with virtual-track labels re-attached from the
        # thread_name metadata so named rows survive repeated conversions
        labels = {}
        for ev in doc["traceEvents"]:
            if ev.get("ph") == "M" and ev.get("name") == "thread_name":
                name = (ev.get("args") or {}).get("name", "")
                if not name.startswith("host-thread-"):
                    labels[(ev.get("pid", 0), ev.get("tid", 0))] = name
        spans = []
        for ev in doc["traceEvents"]:
            if ev.get("ph") not in ("X", "i", "I"):
                continue
            track = labels.get((ev.get("pid", 0), ev.get("tid", 0)))
            spans.append({
                "name": ev.get("name", ""), "cat": ev.get("cat", "host"),
                "ts_us": int(ev.get("ts", 0)), "dur_us": int(ev.get("dur", 0)),
                "pid": ev.get("pid", 0), "tid": ev.get("tid", 0),
                **({"track": track} if track else {}),
                **({"args": ev["args"]} if ev.get("args") else {}),
            })
        return spans
    raise ValueError("%s: not a %s or Chrome-trace file" % (path, SPAN_SCHEMA))


def to_chrome_trace(spans: Optional[List[dict]] = None,
                    process_names: Optional[Dict[int, str]] = None) -> dict:
    """Spans → ``chrome://tracing`` JSON object (the ``tools/timeline.py``
    output format: ``traceEvents`` complete events + metadata).
    ``process_names`` labels pids individually (a merged multi-process
    fleet timeline names its router/worker rows); unlisted pids keep the
    default label."""
    spans = spans if spans is not None else get_spans()
    events: List[dict] = []
    seen_threads = set()
    for s in spans:
        pid, tid = s.get("pid", 0), s.get("tid", 0)
        if (pid, tid) not in seen_threads:
            seen_threads.add((pid, tid))
            label = s.get("track")
            if label is None:
                with _spans_lock:
                    label = _track_names.get(tid, "host-thread-%s" % tid)
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": label}})
        ev = {
            "ph": "X" if s.get("dur_us", 0) else "i",
            "name": s.get("name", ""),
            "cat": s.get("cat", "host"),
            "ts": s.get("ts_us", 0),
            "pid": pid,
            "tid": tid,
        }
        if s.get("dur_us", 0):
            ev["dur"] = s["dur_us"]
        else:
            ev["s"] = "t"  # instant scope: thread
        if s.get("args"):
            ev["args"] = s["args"]
        events.append(ev)
    for pid in {s.get("pid", 0) for s in spans}:
        label = (process_names or {}).get(pid, "paddle_tpu host")
        events.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                       "args": {"name": label}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"producer": "paddle_tpu.monitor.tracer"}}


def save_chrome_trace(path: str, spans: Optional[List[dict]] = None,
                      process_names: Optional[Dict[int, str]] = None) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans, process_names=process_names), f)
    return path


# -- env activation -----------------------------------------------------------

def _maybe_autostart() -> None:
    global _trace_file
    path = os.environ.get("PADDLE_TPU_TRACE_FILE", "").strip()
    if not path:
        return
    _trace_file = path
    start_tracing()

    @atexit.register
    def _flush():  # pragma: no cover — exercised via subprocess in tests
        if get_spans():
            try:
                save_chrome_trace(_trace_file)
            except OSError:
                pass


_maybe_autostart()
