"""Checked-in collective-traffic budgets vs the measured ``collectives/*``.

PR 5 made explicit collective volume *measured* (``record_collective`` at
the gpipe/ring-attention ppermute and CTR all_to_all emission sites);
this module makes it *enforced*: each leg has a closed-form bytes-per-step
budget derived from first principles, and :func:`check_budget` asserts a
measured counter against it. A refactor that silently doubles ICI traffic
(an extra rotation, a dtype widening, a lost donation) now fails
``tools/check_budgets.py --selftest`` and ``dryrun_multichip`` instead of
shipping.

The closed forms (per device, per traced step, matching exactly what the
emission sites record at trace time):

* **gpipe forward** (``parallel/pipeline.py``): with M microbatches over S
  stages, activation bytes A per microbatch — feed hops ship every
  microbatch not already on stage 0 (M - M/S), collect hops return every
  output not finishing on the last stage (M - M/S), and the tick rotation
  runs M+S-2 times: ``(2*(M - M/S) + M + S - 2) * A``. (The backward
  schedule is JAX AD transposing these permutes — same volume again, not
  separately recorded.)
* **ring attention** (``parallel/ring_attention.py``): K and V blocks of B
  bytes each rotate N hops per step: forward ``2*N*B``; backward re-rotates
  K/V and travels the f32 dK/dV accumulators: ``2*N*B + 2*N*B_f32``.
* **CTR row routing** (``core/sparse.route_rows_to_shards``): each shard
  exchanges fixed-capacity buckets — ids ``[n_shards, n_local]`` plus rows
  ``[n_shards, n_local, D]``: ``n_shards * n_local * (id_itemsize +
  D * row_itemsize)`` per routing call (ids AND rows legs summed).

Budgets are exact when the leg is traced once; pass ``slack`` only for
sites a caller traces a variable number of times.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

__all__ = [
    "COLLECTIVE_BUDGETS", "CollectiveBudgetExceeded",
    "gpipe_fwd_bytes", "ring_attention_fwd_bytes",
    "ring_attention_bwd_bytes", "ctr_row_routing_bytes",
    "budget_bytes", "check_budget",
]


class CollectiveBudgetExceeded(AssertionError):
    """Measured collective bytes exceed the closed-form budget — a real
    traffic regression (or a budget that must be consciously re-derived
    and updated in the same commit)."""


def gpipe_fwd_bytes(microbatches: int, stages: int,
                    activation_bytes: int) -> int:
    """Forward-trace ppermute bytes of one gpipe step (module docstring).
    ``microbatches`` is the padded count (a multiple of ``stages``)."""
    m, s = int(microbatches), int(stages)
    if m % s:
        m = -(-m // s) * s  # the ragged-M pad the builder applies
    return (2 * (m - m // s) + m + s - 2) * int(activation_bytes)


def ring_attention_fwd_bytes(n_devices: int, block_bytes: int) -> int:
    """K + V local blocks, each rotated ``n_devices`` hops."""
    return 2 * int(n_devices) * int(block_bytes)


def ring_attention_bwd_bytes(n_devices: int, block_bytes: int,
                             block_elems: int) -> int:
    """Backward ring: K/V in input dtype plus f32 dK/dV accumulators."""
    return (2 * int(n_devices) * int(block_bytes)
            + 2 * int(n_devices) * int(block_elems) * 4)


def ctr_row_routing_bytes(n_shards: int, n_local: int, dim: int,
                          id_itemsize: int = 4,
                          row_itemsize: int = 4) -> int:
    """One ``route_rows_to_shards`` call: the id bucket exchange plus the
    row bucket exchange (both fixed worst-case capacity)."""
    return int(n_shards) * int(n_local) * (
        int(id_itemsize) + int(dim) * int(row_itemsize))


COLLECTIVE_BUDGETS: Dict[str, Dict[str, Any]] = {
    "gpipe.fwd": {
        "counter": "collectives/ppermute/bytes",
        "formula": gpipe_fwd_bytes,
        "params": ("microbatches", "stages", "activation_bytes"),
        "doc": "GPipe feed/collect/rotate hops of one forward trace",
    },
    "ring_attention.fwd": {
        "counter": "collectives/ppermute/bytes",
        "formula": ring_attention_fwd_bytes,
        "params": ("n_devices", "block_bytes"),
        "doc": "ring-attention forward K/V rotation",
    },
    "ring_attention.bwd": {
        "counter": "collectives/ppermute/bytes",
        "formula": ring_attention_bwd_bytes,
        "params": ("n_devices", "block_bytes", "block_elems"),
        "doc": "ring-attention backward K/V + f32 dK/dV accumulators",
    },
    "ctr.row_routing": {
        "counter": "collectives/all_to_all/bytes",
        "formula": ctr_row_routing_bytes,
        "params": ("n_shards", "n_local", "dim", "id_itemsize",
                   "row_itemsize"),
        "doc": "PS-style sparse-row all_to_all exchange (ids + rows)",
    },
}


def budget_bytes(leg: str, **params) -> int:
    """Evaluate the checked-in closed form for ``leg`` at ``params``."""
    spec = COLLECTIVE_BUDGETS.get(leg)
    if spec is None:
        raise KeyError("unknown collective budget leg %r (have: %s)"
                       % (leg, ", ".join(sorted(COLLECTIVE_BUDGETS))))
    fn: Callable = spec["formula"]
    return int(fn(**params))


def check_budget(leg: str, measured_bytes: float, budget: int = None,
                 slack: float = 0.0, **params) -> dict:
    """Assert ``measured_bytes <= budget * (1 + slack)``.

    ``budget=None`` evaluates the leg's closed form at ``params`` (the
    normal path); an explicit ``budget`` overrides it (how the selftest
    proves a tightened budget fails loudly). Returns the comparison
    record on success; raises :class:`CollectiveBudgetExceeded` naming
    leg, measured, budget and the parameterization on failure."""
    if budget is None:
        budget = budget_bytes(leg, **params)
    limit = budget * (1.0 + max(0.0, slack))
    rec = {"leg": leg, "counter": COLLECTIVE_BUDGETS[leg]["counter"],
           "measured_bytes": int(measured_bytes), "budget_bytes": int(budget),
           "slack": slack, "params": params,
           "utilization": (measured_bytes / budget) if budget else None}
    if measured_bytes > limit:
        raise CollectiveBudgetExceeded(
            "collective budget exceeded for %s: measured %d B > budget %d B"
            "%s (params=%r) — a real traffic regression, or re-derive the "
            "closed form in monitor/budgets.py in the same commit"
            % (leg, measured_bytes, budget,
               (" (+%g%% slack)" % (100 * slack)) if slack else "", params))
    return rec
