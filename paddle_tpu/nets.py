"""Composite network helpers (reference: python/paddle/fluid/nets.py —
simple_img_conv_pool:28, img_conv_group:135, sequence_conv_pool:248,
glu:305, scaled_dot_product_attention:340). Pure compositions of layers;
the attention helper rides the framework's fused sdpa op."""

from __future__ import annotations

from . import layers
from .layers import tensor as tensor_layers

__all__ = [
    "simple_img_conv_pool",
    "img_conv_group",
    "sequence_conv_pool",
    "glu",
    "scaled_dot_product_attention",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, stride=conv_stride,
                             padding=conv_padding, dilation=conv_dilation,
                             groups=conv_groups, param_attr=param_attr,
                             bias_attr=bias_attr, act=act, use_cudnn=use_cudnn)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """VGG-style conv(+BN+dropout)* then pool (reference: nets.py:135)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(v):
        return v if isinstance(v, (list, tuple)) else [v] * len(conv_num_filter)

    padding = _expand(conv_padding)
    fsize = _expand(conv_filter_size)
    pattr = _expand(param_attr)
    with_bn = _expand(conv_with_batchnorm)
    drop = _expand(conv_batchnorm_drop_rate)

    for i, nf in enumerate(conv_num_filter):
        local_act = None if with_bn[i] else conv_act
        tmp = layers.conv2d(tmp, num_filters=nf, filter_size=fsize[i],
                            padding=padding[i], param_attr=pattr[i],
                            act=local_act, use_cudnn=use_cudnn)
        if with_bn[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if abs(drop[i]) > 1e-5:
                tmp = layers.dropout(tmp, dropout_prob=drop[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", length=None):
    """Context-window conv over time + sequence pool (reference: nets.py:248);
    takes the padded+Length convention's length vector."""
    from .layers import sequence as seq_layers
    from .layers.layer_helper import LayerHelper

    helper = LayerHelper("sequence_conv")
    d = int(input.shape[-1])
    filt = helper.create_parameter(param_attr, shape=[filter_size * d, num_filters],
                                   dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": input, "Filter": filt}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("sequence_conv", inputs=inputs, outputs={"Out": out},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2)})
    if act:
        out = getattr(layers, act)(out)
    return seq_layers.sequence_pool(out, pool_type, length=length)


def glu(input, dim=-1):
    """Gated linear unit: split in half on ``dim``, a ⊙ σ(b)
    (reference: nets.py:305)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head attention over [B, T, D] (reference: nets.py:340) — rides
    the framework's fused attention (Pallas flash path on TPU)."""
    from .layers.attention import multi_head_attention

    d_model = int(queries.shape[-1])
    if d_model % num_heads != 0:
        raise ValueError(
            "hidden size %d is not divisible by num_heads %d (reference "
            "nets.py raises here too)" % (d_model, num_heads))
    d_key = d_model // num_heads
    if keys is queries and values is queries:
        # self-attention: hand None through so the layer takes its fused
        # single-matmul QKV projection path
        keys = values = None
    return multi_head_attention(
        queries, keys, values, attn_bias=None, d_key=d_key, d_value=d_key,
        d_model=d_model, n_head=num_heads, dropout_rate=dropout_rate)
