"""CompiledProgram — the multi-device front door.

Reference: ``python/paddle/fluid/compiler.py:62`` + the C++ ParallelExecutor
(``framework/parallel_executor.cc:184``). Fluid replicates the program per
GPU, builds an SSA graph with NCCL AllReduce op handles, and schedules it
with a threaded dataflow executor. The TPU-native design needs none of that
machinery: the jitted step is compiled under a ``jax.sharding.Mesh`` with the
feed batch sharded on the ``data`` axis and state replicated; XLA's GSPMD
partitioner inserts the gradient ``psum`` over ICI automatically. Multi-host
(the reference's NCCL2 mode) is the same code over a larger mesh after
``jax.distributed.initialize``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core.framework import Program

__all__ = ["CompiledProgram", "ExecutionStrategy", "BuildStrategy"]


class _StrategyBase:
    """Compat attribute holder that refuses to lie: setting a knob that has
    no effect under XLA warns once, naming what owns the behavior instead
    (VERDICT weak #7: silently-ignored tuning is worse than an error)."""

    _INERT: dict = {}  # attr -> who handles it now
    _defaults: dict = {}

    def __setattr__(self, name, value):
        if name in self._INERT and value != self._defaults.get(name):
            import warnings

            warnings.warn(
                "%s.%s is accepted for API compatibility but has no effect: %s"
                % (type(self).__name__, name, self._INERT[name]),
                UserWarning, stacklevel=2)
        object.__setattr__(self, name, value)


class ExecutionStrategy(_StrategyBase):
    """API parity with details/execution_strategy.h:22 — knobs that map to XLA
    are honored; threading knobs warn (XLA owns scheduling)."""

    _INERT = {
        "num_threads": "XLA owns op scheduling on TPU (single fused program)",
        "num_iteration_per_drop_scope": "XLA buffer liveness replaces scope GC",
        "use_experimental_executor": "there is exactly one executor (trace+jit)",
    }

    def __init__(self):
        d = {"num_threads": 0, "num_iteration_per_drop_scope": 1,
             "use_experimental_executor": False}
        object.__setattr__(self, "_defaults", d)
        for k, v in d.items():
            object.__setattr__(self, k, v)


class BuildStrategy(_StrategyBase):
    """API parity with details/build_strategy.h:35."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    _INERT = {
        "memory_optimize": "XLA buffer assignment + donation owns reuse",
        "enable_inplace": "XLA buffer donation owns in-place updates",
        "fuse_all_reduce_ops": "XLA fuses collectives itself",
        "gradient_scale_strategy": "GSPMD computes the GLOBAL batch mean "
            "directly (loss reduces over the sharded batch), which is "
            "exactly CoeffNumDevice semantics; One/Customized would "
            "require per-device loss scaling that the single fused "
            "program has no seam for",
    }

    def __init__(self):
        d = {
            "reduce_strategy": BuildStrategy.ReduceStrategy.AllReduce,
            "gradient_scale_strategy": BuildStrategy.GradientScaleStrategy.CoeffNumDevice,
            "memory_optimize": True,
            "enable_inplace": True,
            "fuse_all_reduce_ops": True,
            "num_trainers": 1,
            "trainer_id": 0,
            # Microbatch gradient accumulation (the reference's
            # multi_batch_merge_pass); feed batch must divide by it. Honored.
            "gradient_accumulation_steps": 1,
        }
        object.__setattr__(self, "_defaults", d)
        for k, v in d.items():
            object.__setattr__(self, k, v)
        object.__setattr__(self, "_pass_builder", None)

    def pass_builder(self):
        """The Program-pass pipeline applied in CompiledProgram's build step
        (reference: BuildStrategy::CreatePassesFromStrategy + PassBuilder,
        pybind.cc:981-1003). Created empty on first call; append registered
        or custom passes."""
        from .core.pass_framework import PassBuilder

        if self._pass_builder is None:
            object.__setattr__(self, "_pass_builder", PassBuilder())
        return self._pass_builder


class CompiledProgram:
    """reference: compiler.py:62."""

    def __init__(self, program_or_graph: Program):
        self._program = program_or_graph
        self._is_data_parallel = False
        self._loss_name: Optional[str] = None
        self._places: Optional[Sequence] = None
        self._exec_strategy: Optional[ExecutionStrategy] = None
        self._build_strategy: Optional[BuildStrategy] = None
        self._share_vars_from: Optional["CompiledProgram"] = None
        self._mesh_cache: Optional[Mesh] = None

    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from: Optional["CompiledProgram"] = None,
        places: Optional[Sequence] = None,
    ) -> "CompiledProgram":
        """reference: compiler.py:116."""
        self._is_data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def with_mesh(self, axes, loss_name: Optional[str] = None,
                  build_strategy: Optional[BuildStrategy] = None) -> "CompiledProgram":
        """General N-D mesh parallelism: ``with_mesh({'data': 4, 'model': 2})``.

        Feeds shard over the ``data`` axis; params follow their
        ``Variable.sharding`` annotations (see paddle_tpu.parallel) — this is
        the TP/sharded-embedding path the reference lacks (SURVEY §2.3).
        """
        from .parallel.mesh import create_mesh

        self._is_data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._mesh_cache = axes if isinstance(axes, Mesh) else create_mesh(axes)
        return self

    # -- mesh construction ----------------------------------------------------
    def _device_count(self) -> int:
        if self._places is not None:
            return len(self._places)
        return len(jax.devices())

    def _mesh(self) -> Optional[Mesh]:
        if not self._is_data_parallel:
            return None
        if self._mesh_cache is None:
            n = self._device_count()
            devices = np.asarray(jax.devices()[:n])
            self._mesh_cache = Mesh(devices, axis_names=("data",))
        return self._mesh_cache

    # -- build-step passes ----------------------------------------------------
    def _apply_build_passes(self, scope):
        """Run the BuildStrategy's PassBuilder pipeline once, at first
        execution (the reference applies its pass pipeline when the
        ParallelExecutor graph is built, build_strategy.cc:44-150).

        The DEFAULT optimizer pipeline (paddle_tpu.passes, gated by
        ``PADDLE_TPU_OPT_LEVEL``) runs first on the same transactional
        clone, so user pipelines compose AFTER the defaults, matching the
        reference's BuildStrategy::CreatePassesFromStrategy ordering. With
        no fetch info at build time only the FETCH-SAFE defaults run
        (conv+bn folding, conservative DCE) — def-removing passes would
        break later fetches of named intermediates; the Executor runs the
        full fetch-seeded pipeline on the result (memoized; every default
        pass is idempotent)."""
        if getattr(self, "_passes_applied", False):
            return
        bs = self._build_strategy
        builder = getattr(bs, "_pass_builder", None) if bs is not None else None
        if builder is None:
            # no user pipeline: defaults are applied per fetch-set by the
            # Executor (where DCE can seed liveness from real fetch targets)
            self._passes_applied = True
            return
        from .core.scope import global_scope
        from .passes.pipeline import default_pipeline

        scope = scope if scope is not None else global_scope()
        for p in builder.all_passes():
            if not p.has_attr("scope"):
                p.set_attr("scope", scope)
        # transactional: passes may mutate the program in place, so run the
        # pipeline on a clone — a mid-pipeline failure (PassError, naming
        # the failing pass) leaves the original untouched and the retry
        # starts from scratch instead of double-applying the passes that
        # had already run
        work = self._program.clone()
        # freeze stochastic ops' positional PRNG identity before any rewrite
        # (see passes/analysis.py) — op deletion must not shift RNG streams
        from .passes.analysis import stamp_rng_slots

        work._rng_table_n = getattr(
            self._program, "_rng_table_n",
            len(self._program.global_block.ops) + 8)
        stamp_rng_slots(work)
        work = default_pipeline(scope=scope).apply_all(work)
        self._program = builder.apply_all(work)
        self._passes_applied = True

    # -- ZeRO-1 (ReduceStrategy.Reduce) ---------------------------------------
    def _apply_reduce_strategy(self, mesh):
        """``BuildStrategy.reduce_strategy == Reduce`` — the TPU-idiomatic
        reading of the reference's Reduce mode (details/build_strategy.h:35 +
        reduce_op_handle): instead of placing each param's *update* on one
        device, shard every per-param optimizer accumulator over the ``data``
        axis (ZeRO-1). GSPMD then partitions the optimizer update math and
        all_gathers the fresh params; per-device optimizer-state memory drops
        by ~the data-axis size. Applied once, before the first compile."""
        if getattr(self, "_reduce_applied", False) or mesh is None:
            return
        self._reduce_applied = True
        bs = self._build_strategy
        if bs is None or bs.reduce_strategy != BuildStrategy.ReduceStrategy.Reduce:
            return
        if "data" not in mesh.axis_names:
            return
        ndata = mesh.shape["data"]
        for v in self._program.list_vars():
            if not getattr(v, "is_optimizer_state", False):
                continue
            if getattr(v, "sharding", None) is not None:
                continue  # user/model-parallel annotation wins
            shape = tuple(v.shape or ())
            if not shape or shape[0] % ndata != 0 or shape[0] < ndata:
                continue  # scalars (beta_pow etc.) stay replicated
            v.sharding = ("data",) + (None,) * (len(shape) - 1)

    # -- execution (called from Executor.run) ---------------------------------
    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        self._apply_build_passes(scope)
        self._apply_reduce_strategy(self._mesh())
        accum = 1
        if self._build_strategy is not None:
            accum = getattr(self._build_strategy, "gradient_accumulation_steps", 1)
        return executor._run_impl(
            self._program,
            feed=feed,
            fetch_list=fetch_list,
            scope=scope,
            return_numpy=return_numpy,
            mesh=self._mesh(),
            accumulation_steps=accum,
        )
