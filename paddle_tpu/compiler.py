"""CompiledProgram — the multi-device front door.

Reference: ``python/paddle/fluid/compiler.py:62`` + the C++ ParallelExecutor
(``framework/parallel_executor.cc:184``). Fluid replicates the program per
GPU, builds an SSA graph with NCCL AllReduce op handles, and schedules it
with a threaded dataflow executor. The TPU-native design needs none of that
machinery: the jitted step is compiled under a ``jax.sharding.Mesh`` with the
feed batch sharded on the ``data`` axis and state replicated; XLA's GSPMD
partitioner inserts the gradient ``psum`` over ICI automatically. Multi-host
(the reference's NCCL2 mode) is the same code over a larger mesh after
``jax.distributed.initialize``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core.framework import Program

__all__ = ["CompiledProgram", "ExecutionStrategy", "BuildStrategy"]


class ExecutionStrategy:
    """API parity with details/execution_strategy.h:22 — knobs that map to XLA
    are honored; threading knobs are no-ops (XLA owns scheduling)."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = False


class BuildStrategy:
    """API parity with details/build_strategy.h:35."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = True
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.num_trainers = 1
        self.trainer_id = 0
        # Microbatch gradient accumulation (the reference's
        # multi_batch_merge_pass); feed batch must divide by it.
        self.gradient_accumulation_steps = 1


class CompiledProgram:
    """reference: compiler.py:62."""

    def __init__(self, program_or_graph: Program):
        self._program = program_or_graph
        self._is_data_parallel = False
        self._loss_name: Optional[str] = None
        self._places: Optional[Sequence] = None
        self._exec_strategy: Optional[ExecutionStrategy] = None
        self._build_strategy: Optional[BuildStrategy] = None
        self._share_vars_from: Optional["CompiledProgram"] = None
        self._mesh_cache: Optional[Mesh] = None

    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from: Optional["CompiledProgram"] = None,
        places: Optional[Sequence] = None,
    ) -> "CompiledProgram":
        """reference: compiler.py:116."""
        self._is_data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def with_mesh(self, axes, loss_name: Optional[str] = None,
                  build_strategy: Optional[BuildStrategy] = None) -> "CompiledProgram":
        """General N-D mesh parallelism: ``with_mesh({'data': 4, 'model': 2})``.

        Feeds shard over the ``data`` axis; params follow their
        ``Variable.sharding`` annotations (see paddle_tpu.parallel) — this is
        the TP/sharded-embedding path the reference lacks (SURVEY §2.3).
        """
        from .parallel.mesh import create_mesh

        self._is_data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._mesh_cache = axes if isinstance(axes, Mesh) else create_mesh(axes)
        return self

    # -- mesh construction ----------------------------------------------------
    def _device_count(self) -> int:
        if self._places is not None:
            return len(self._places)
        return len(jax.devices())

    def _mesh(self) -> Optional[Mesh]:
        if not self._is_data_parallel:
            return None
        if self._mesh_cache is None:
            n = self._device_count()
            devices = np.asarray(jax.devices()[:n])
            self._mesh_cache = Mesh(devices, axis_names=("data",))
        return self._mesh_cache

    # -- execution (called from Executor.run) ---------------------------------
    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        accum = 1
        if self._build_strategy is not None:
            accum = getattr(self._build_strategy, "gradient_accumulation_steps", 1)
        return executor._run_impl(
            self._program,
            feed=feed,
            fetch_list=fetch_list,
            scope=scope,
            return_numpy=return_numpy,
            mesh=self._mesh(),
            accumulation_steps=accum,
        )
