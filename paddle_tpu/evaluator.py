"""Deprecated ``fluid.evaluator`` namespace (reference:
python/paddle/fluid/evaluator.py — each class there points users at the
``fluid.metrics`` replacement). Kept for script compatibility: the names
resolve to the metrics implementations."""

from .metrics import ChunkEvaluator, DetectionMAP, EditDistance  # noqa: F401

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]
