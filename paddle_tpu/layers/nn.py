"""Neural-net layers (reference: python/paddle/fluid/layers/nn.py — 155 defs).

Each layer appends symbolic ops to the default main program via LayerHelper,
exactly Fluid's construction model (``nn.py:195`` fc et al.). The op impls are
pure JAX and the whole program compiles to one XLA computation.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..core.framework import Variable
from .layer_helper import LayerHelper, ParamAttr

__all__ = [
    "fc",
    "embedding",
    "conv2d",
    "conv3d",
    "conv2d_transpose",
    "pool2d",
    "pool3d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "instance_norm",
    "data_norm",
    "lrn",
    "dropout",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "smooth_l1",
    "huber_loss",
    "log_loss",
    "matmul",
    "mul",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "mean",
    "relu",
    "gelu",
    "tanh",
    "sigmoid",
    "leaky_relu",
    "prelu",
    "elu",
    "relu6",
    "swish",
    "maxout",
    "hard_sigmoid",
    "soft_relu",
    "brelu",
    "pow",
    "stanh",
    "l2_normalize",
    "clip",
    "clip_by_norm",
    "one_hot",
    "topk",
    "argsort",
    "argmax",
    "argmin",
    "accuracy",
    "auc",
    "pad",
    "pad2d",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "pixel_shuffle",
    "flatten",
    "unsqueeze",
    "squeeze",
    "stack",
    "unstack",
    "expand",
    "gather",
    "gather_nd",
    "scatter",
    "slice",
    "strided_slice",
    "shape",
    "where",
    "cos_sim",
    "dot",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "elementwise_mod",
    "uniform_random",
    "gaussian_random",
    "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like",
    "bilinear_tensor_product",
    "split",
    "multiplex",
    "label_smooth",
    "mean_iou",
    "space_to_depth",
    "shuffle_channel",
    "autoincreased_step_counter",
]


def _single_op_layer(helper_name, op_type, x, attrs=None, x_slot="X", out_slot="Out", name=None, dtype=None):
    helper = LayerHelper(helper_name, name=name)
    out = helper.create_variable_for_type_inference(dtype or x.dtype)
    helper.append_op(op_type, inputs={x_slot: x}, outputs={out_slot: out}, attrs=attrs or {})
    return out


def fc(
    input,
    size: int,
    num_flatten_dims: int = 1,
    param_attr=None,
    bias_attr=None,
    act: Optional[str] = None,
    is_test: bool = False,
    name: Optional[str] = None,
):
    """Fully-connected layer (reference: layers/nn.py:195).

    Multiple inputs each get their own weight; results are summed (mul ops +
    sum op, like Fluid), then bias + activation.
    """
    helper = LayerHelper("fc", input=input, param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        input_shape = inp.shape
        if input_shape is None:
            raise ValueError(
                "fc: input %r has unknown shape (shape inference failed on the "
                "producing op %r) — check the upstream layer geometry"
                % (inp.name, inp.op.type if inp.op else None)
            )
        import numpy as _np

        in_features = int(_np.prod([d for d in input_shape[num_flatten_dims:]]))
        w = helper.create_parameter(pattr, shape=[in_features, size], dtype=inp.dtype)
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(
            "mul",
            inputs={"X": inp, "Y": w},
            outputs={"Out": tmp},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op("sum", inputs={"X": mul_results}, outputs={"Out": pre_bias})
    if bias_attr is False:
        pre_act = pre_bias
    else:
        pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size: Sequence[int],
    is_sparse: bool = False,
    is_distributed: bool = False,
    padding_idx: Optional[int] = None,
    param_attr=None,
    dtype="float32",
    name=None,
):
    """Embedding lookup (reference: layers/nn.py embedding). ``is_sparse=True``
    enables the SelectedRows-equivalent (ids, rows) gradient path — the table
    gradient stays O(N·D) and row-wise optimizer updates apply lazily (see
    core/sparse.py, ops/nn_ops.py lookup_table_op)."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype)
    if is_sparse:
        w.is_sparse_param = True
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = -1 if padding_idx is None else (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(
        "lookup_table",
        inputs={"W": w, "Ids": input},
        outputs={"Out": out},
        attrs={"padding_idx": padding_idx, "is_sparse": is_sparse, "is_distributed": is_distributed},
    )
    return out


def conv2d(
    input,
    num_filters: int,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups: int = 1,
    param_attr=None,
    bias_attr=None,
    use_cudnn: bool = True,
    act: Optional[str] = None,
    name: Optional[str] = None,
    data_format: str = "NCHW",
):
    """2-D convolution, OIHW weights (reference: layers/nn.py conv2d).

    data_format NHWC runs channels-last — the TPU-native layout (channels on
    the 128-lane minor dim); weights stay OIHW so checkpoints are portable.
    """
    helper = LayerHelper("conv2d", bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[-1] if data_format == "NHWC" else input.shape[1]
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    groups = groups or 1
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    import math as _math

    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    from .. import initializer as init_mod

    w = helper.create_parameter(
        param_attr,
        shape=filter_shape,
        dtype=input.dtype,
        default_initializer=init_mod.Normal(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d",
        inputs={"Input": input, "Filter": w},
        outputs={"Output": pre_bias},
        attrs={
            "strides": list(stride),
            "paddings": list(padding),
            "dilations": list(dilation),
            "groups": groups,
            "use_cudnn": use_cudnn,
            "data_format": data_format,
        },
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        bias = helper.create_parameter(
            ParamAttr.to_attr(bias_attr), shape=[num_filters], dtype=input.dtype, is_bias=True
        )
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(
            "elementwise_add",
            inputs={"X": pre_bias, "Y": bias},
            outputs={"Out": pre_act},
            attrs={"axis": 3 if data_format == "NHWC" else 1},
        )
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1,
           param_attr=None, bias_attr=None, use_cudnn=True, act=None, name=None):
    helper = LayerHelper("conv3d", bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    fs = _triple(filter_size)
    filter_shape = [num_filters, num_channels // (groups or 1)] + list(fs)
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=input.dtype)
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d",
        inputs={"Input": input, "Filter": w},
        outputs={"Output": pre_bias},
        attrs={
            "strides": list(_triple(stride)),
            "paddings": list(_triple(padding)),
            "dilations": list(_triple(dilation)),
            "groups": groups or 1,
        },
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=[num_filters], dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": pre_bias, "Y": bias}, outputs={"Out": pre_act}, attrs={"axis": 1})
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None, padding=0,
                     stride=1, dilation=1, groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("filter_size or output_size required")
        output_size = _pair(output_size)
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h_in - 1) * stride[0] + 2 * padding[0] - 1) // dilation[0] + 1,
            (output_size[1] - (w_in - 1) * stride[1] + 2 * padding[1] - 1) // dilation[1] + 1,
        ]
    else:
        filter_size = list(_pair(filter_size))
    filter_shape = [num_channels, num_filters // (groups or 1)] + filter_size
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=input.dtype)
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": input, "Filter": w},
        outputs={"Output": pre_bias},
        attrs={
            "strides": list(stride),
            "paddings": list(padding),
            "dilations": list(dilation),
            "groups": groups or 1,
        },
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=[num_filters], dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": pre_bias, "Y": bias}, outputs={"Out": pre_act}, attrs={"axis": 1})
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, exclusive=True,
           name=None, data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d",
        inputs={"X": input},
        outputs={"Out": out},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(_pair(pool_size)),
            "strides": list(_pair(pool_stride)),
            "paddings": list(_pair(pool_padding)),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool3d",
        inputs={"X": input},
        outputs={"Out": out},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(_triple(pool_size)),
            "strides": list(_triple(pool_stride)),
            "paddings": list(_triple(pool_padding)),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def batch_norm(
    input,
    act: Optional[str] = None,
    is_test: bool = False,
    momentum: float = 0.9,
    epsilon: float = 1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout: str = "NCHW",
    name: Optional[str] = None,
    moving_mean_name: Optional[str] = None,
    moving_variance_name: Optional[str] = None,
    use_global_stats: bool = False,
):
    """Batch normalization (reference: layers/nn.py batch_norm)."""
    from ..core import unique_name
    from .. import initializer as init_mod

    helper = LayerHelper("batch_norm", act=act, name=name)
    dtype = input.dtype
    channels = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        param_attr, shape=[channels], dtype=dtype, default_initializer=init_mod.Constant(1.0)
    )
    bias = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=[channels], dtype=dtype, is_bias=True)
    mean_name = moving_mean_name or unique_name.generate(helper.name + ".mean")
    var_name = moving_variance_name or unique_name.generate(helper.name + ".var")
    mean = helper.create_or_get_global_variable([channels], dtype, mean_name, initializer=init_mod.Constant(0.0))
    variance = helper.create_or_get_global_variable([channels], dtype, var_name, initializer=init_mod.Constant(1.0))

    out = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        inputs={"X": input, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": variance},
        outputs={
            "Y": out,
            "MeanOut": mean,
            "VarianceOut": variance,
            "SavedMean": saved_mean,
            "SavedVariance": saved_var,
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale: bool = True,
    shift: bool = True,
    begin_norm_axis: int = 1,
    epsilon: float = 1e-5,
    param_attr=None,
    bias_attr=None,
    act: Optional[str] = None,
    name: Optional[str] = None,
):
    """Layer normalization (reference: layers/nn.py layer_norm)."""
    from .. import initializer as init_mod
    import numpy as _np

    helper = LayerHelper("layer_norm", act=act, name=name)
    dtype = input.dtype
    norm_shape = [int(_np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype=dtype, default_initializer=init_mod.Constant(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=norm_shape, dtype=dtype, is_bias=True)
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(dtype)
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": out, "Mean": mean_out, "Variance": var_out},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import initializer as init_mod

    helper = LayerHelper("group_norm", act=act, name=name)
    channels = input.shape[1]
    inputs = {"X": input}
    if param_attr is not False:
        inputs["Scale"] = helper.create_parameter(param_attr, shape=[channels], dtype=input.dtype,
                                                  default_initializer=init_mod.Constant(1.0))
    if bias_attr is not False:
        inputs["Bias"] = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=[channels],
                                                 dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    mean_out = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op("group_norm", inputs=inputs,
                     outputs={"Y": out, "Mean": mean_out, "Variance": var_out},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    from .. import initializer as init_mod

    helper = LayerHelper("instance_norm", name=name)
    channels = input.shape[1]
    scale = helper.create_parameter(param_attr, shape=[channels], dtype=input.dtype,
                                    default_initializer=init_mod.Constant(1.0))
    bias = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=[channels], dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("instance_norm", inputs={"X": input, "Scale": scale, "Bias": bias},
                     outputs={"Y": out}, attrs={"epsilon": epsilon})
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None):
    raise NotImplementedError("data_norm layer: use batch_norm; op exists for parity")


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op("lrn", inputs={"X": input}, outputs={"Out": out, "MidOut": mid},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "dropout",
        inputs={"X": x},
        outputs={"Out": out, "Mask": mask},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed or 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _single_op_layer("softmax", "softmax", input, {"axis": axis}, name=name)


def log_softmax(input, axis=-1, name=None):
    return _single_op_layer("log_softmax", "log_softmax", input, {"axis": axis}, name=name)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cross_entropy",
        inputs={"X": input, "Label": label},
        outputs={"Y": out},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1,
                               label_smoothing=0.0):
    """``label_smoothing`` is a TPU-native fusion extension: smoothing folds
    into the single log_softmax pass instead of a second full-vocab traversal
    (the reference composes label_smooth + softmax_with_cross_entropy ops)."""
    helper = LayerHelper("softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    # the Softmax slot is only declared when the caller asks for it — the
    # exp(log_p) pass over the [N, V] logits (2GB at the bench shapes) must
    # not ride along in every training step
    outputs = {"Loss": loss}
    if return_softmax:
        softmax_out = helper.create_variable_for_type_inference(logits.dtype)
        outputs["Softmax"] = softmax_out
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": logits, "Label": label},
        outputs=outputs,
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "label_smoothing": float(label_smoothing)},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, normalize=False, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": x, "Label": label},
        outputs={"Out": out},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    """(input-label)^2 (reference: layers/nn.py square_error_cost)."""
    helper = LayerHelper("square_error_cost")
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("elementwise_sub", inputs={"X": input, "Y": label}, outputs={"Out": diff})
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square", inputs={"X": diff}, outputs={"Out": out})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    diff = helper.create_variable_for_type_inference(x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": x, "Y": y}
    if inside_weight is not None:
        inputs["InsideWeight"] = inside_weight
    if outside_weight is not None:
        inputs["OutsideWeight"] = outside_weight
    helper.append_op("smooth_l1_loss", inputs=inputs, outputs={"Diff": diff, "Out": out},
                     attrs={"sigma": sigma or 1.0})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("huber_loss", inputs={"X": input, "Y": label},
                     outputs={"Residual": residual, "Out": out}, attrs={"delta": delta})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_loss", inputs={"Predicted": input, "Labels": label},
                     outputs={"Loss": out}, attrs={"epsilon": epsilon})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "matmul",
        inputs={"X": x, "Y": y},
        outputs={"Out": out},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": float(alpha)},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "mul",
        inputs={"X": x, "Y": y},
        outputs={"Out": out},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def _reduce_layer(op_type, input, dim, keep_dim, name):
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        dim = dim if isinstance(dim, (list, tuple)) else [dim]
        attrs = {"dim": list(dim), "keep_dim": keep_dim, "reduce_all": False}
    return _single_op_layer(op_type, op_type, input, attrs, name=name)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_prod", input, dim, keep_dim, name)


def mean(x, name=None):
    return _single_op_layer("mean", "mean", x, name=name)


# -- activations as layers ----------------------------------------------------


def _act(op_type, x, attrs=None, name=None):
    return _single_op_layer(op_type, op_type, x, attrs, name=name)


def relu(x, name=None):
    return _act("relu", x, name=name)


def gelu(x, approximate=False, name=None):
    return _act("gelu", x, {"approximate": approximate}, name=name)


def tanh(x, name=None):
    return _act("tanh", x, name=name)


def sigmoid(x, name=None):
    return _act("sigmoid", x, name=name)


def leaky_relu(x, alpha=0.02, name=None):
    return _act("leaky_relu", x, {"alpha": alpha}, name=name)


def elu(x, alpha=1.0, name=None):
    return _act("elu", x, {"alpha": alpha}, name=name)


def relu6(x, threshold=6.0, name=None):
    return _act("relu6", x, {"threshold": threshold}, name=name)


def swish(x, beta=1.0, name=None):
    return _act("swish", x, {"beta": beta}, name=name)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _act("hard_sigmoid", x, {"slope": slope, "offset": offset}, name=name)


def soft_relu(x, threshold=40.0, name=None):
    return _act("soft_relu", x, {"threshold": threshold}, name=name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _act("brelu", x, {"t_min": t_min, "t_max": t_max}, name=name)


def pow(x, factor=1.0, name=None):
    return _act("pow", x, {"factor": factor}, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _act("stanh", x, {"scale_a": scale_a, "scale_b": scale_b}, name=name)


# Auto-generated unary layers (the role of layer_function_generator.py /
# ops.py in the reference — one python wrapper per registered activation op).
_GENERATED_UNARY = [
    "square", "sqrt", "rsqrt", "exp", "log", "log1p", "abs", "ceil", "floor",
    "cos", "sin", "round", "reciprocal", "softplus", "softsign", "logsigmoid",
    "tanh_shrink", "soft_shrink", "hard_shrink", "thresholded_relu", "selu",
    "erf", "sign",
]


def _make_unary_layer(op_type):
    def _layer(x, name=None):
        return _act(op_type, x, name=name)

    _layer.__name__ = op_type
    _layer.__doc__ = "Elementwise %s (auto-generated wrapper over the %s op)." % (op_type, op_type)
    return _layer


for _op_name in _GENERATED_UNARY:
    if _op_name not in globals():
        globals()[_op_name] = _make_unary_layer(_op_name)
__all__ += [n for n in _GENERATED_UNARY if n not in __all__]


def prelu(x, mode="all", param_attr=None, name=None):
    from .. import initializer as init_mod

    helper = LayerHelper("prelu", name=name)
    alpha_shape = [1] if mode == "all" else ([x.shape[1]] if mode == "channel" else list(x.shape[1:]))
    alpha = helper.create_parameter(param_attr, shape=alpha_shape, dtype=x.dtype,
                                    default_initializer=init_mod.Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": x, "Alpha": alpha}, outputs={"Out": out}, attrs={"mode": mode})
    return out


def maxout(x, groups, name=None):
    return _act("maxout", x, {"groups": groups}, name=name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("norm", inputs={"X": x}, outputs={"Out": out, "Norm": norm},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def clip(x, min, max, name=None):
    return _single_op_layer("clip", "clip", x, {"min": min, "max": max}, name=name)


def clip_by_norm(x, max_norm, name=None):
    return _single_op_layer("clip_by_norm", "clip_by_norm", x, {"max_norm": max_norm}, name=name)


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", inputs={"X": input}, outputs={"Out": out}, attrs={"depth": depth})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op("top_k", inputs={"X": input}, outputs={"Out": values, "Indices": indices}, attrs={"k": k})
    return values, indices


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op("argsort", inputs={"X": input}, outputs={"Out": out, "Indices": ids}, attrs={"axis": axis})
    return out, ids


def argmax(x, axis=0, name=None):
    return _single_op_layer("arg_max", "arg_max", x, {"axis": axis}, dtype="int64", name=name)


def argmin(x, axis=0, name=None):
    return _single_op_layer("arg_min", "arg_min", x, {"axis": axis}, dtype="int64", name=name)


def accuracy(input, label, k=1, correct=None, total=None):
    """Reference: layers/metric_op.py accuracy — top-k then accuracy op."""
    helper = LayerHelper("accuracy")
    values, indices = topk(input, k)
    acc_out = helper.create_variable_for_type_inference("float32")
    correct = correct or helper.create_variable_for_type_inference("int32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(
        "accuracy",
        inputs={"Out": values, "Indices": indices, "Label": label},
        outputs={"Accuracy": acc_out, "Correct": correct, "Total": total},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """Streaming AUC (reference: layers/metric_op.py auc)."""
    from .. import initializer as init_mod
    from ..core import unique_name

    helper = LayerHelper("auc")
    stat_pos = helper.create_or_get_global_variable(
        [1, num_thresholds + 1], "float32", unique_name.generate("auc_stat_pos"),
        initializer=init_mod.Constant(0.0))
    stat_neg = helper.create_or_get_global_variable(
        [1, num_thresholds + 1], "float32", unique_name.generate("auc_stat_neg"),
        initializer=init_mod.Constant(0.0))
    auc_out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "auc",
        inputs={"Predict": input, "Label": label, "StatPos": stat_pos, "StatNeg": stat_neg},
        outputs={"AUC": auc_out, "StatPosOut": stat_pos, "StatNegOut": stat_neg},
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, [stat_pos, stat_neg]


def pad(x, paddings, pad_value=0.0, name=None):
    return _single_op_layer("pad", "pad", x, {"paddings": paddings, "pad_value": pad_value}, name=name)


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0, data_format="NCHW", name=None):
    return _single_op_layer("pad2d", "pad2d", input,
                            {"paddings": list(paddings), "mode": mode, "pad_value": pad_value}, name=name)


def image_resize(input, out_shape=None, scale=None, name=None, resample="BILINEAR",
                 actual_shape=None, align_corners=True, align_mode=1):
    op = "bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp"
    attrs = {"scale": scale or 0.0}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    helper = LayerHelper(op, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(op, inputs={"X": input}, outputs={"Out": out}, attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None, **kw):
    return image_resize(input, out_shape, scale, name, "BILINEAR")


def resize_nearest(input, out_shape=None, scale=None, name=None, **kw):
    return image_resize(input, out_shape, scale, name, "NEAREST")


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pixel_shuffle", inputs={"X": x}, outputs={"Out": out},
                     attrs={"upscale_factor": upscale_factor})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("flatten", inputs={"X": x}, outputs={"Out": out}, attrs={"axis": axis})
    return out


def unsqueeze(input, axes, name=None):
    return _single_op_layer("unsqueeze", "unsqueeze", input, {"axes": list(axes)}, name=name)


def squeeze(input, axes, name=None):
    return _single_op_layer("squeeze", "squeeze", input, {"axes": list(axes)}, name=name)


def stack(x, axis=0):
    helper = LayerHelper("stack")
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", inputs={"X": list(x)}, outputs={"Y": out}, attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    num = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op("unstack", inputs={"X": x}, outputs={"Y": outs}, attrs={"axis": axis, "num": num})
    return outs


def expand(x, expand_times, name=None):
    return _single_op_layer("expand", "expand", x, {"expand_times": list(expand_times)}, name=name)


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": input, "Index": index}, outputs={"Out": out})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", inputs={"X": input, "Index": index}, outputs={"Out": out})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter", inputs={"X": input, "Ids": index, "Updates": updates},
                     outputs={"Out": out}, attrs={"overwrite": overwrite})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)})
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("strided_slice", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends),
                            "strides": list(strides)})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op("shape", inputs={"Input": input}, outputs={"Out": out})
    return out


def where(condition, x=None, y=None):
    if x is None or y is None:
        # Fluid's one-arg where(condition) returns a data-dependent-length
        # index tensor — impossible under XLA's static shapes. Use
        # layers.argsort/topk over a mask, or the ternary form.
        raise NotImplementedError(
            "where(condition) with data-dependent output length is not "
            "supported under XLA static shapes; use where(cond, x, y)."
        )
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where", inputs={"Condition": condition, "X": x, "Y": y}, outputs={"Out": out})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype, stop_gradient=True)
    ynorm = helper.create_variable_for_type_inference(X.dtype, stop_gradient=True)
    helper.append_op("cos_sim", inputs={"X": X, "Y": Y},
                     outputs={"Out": out, "XNorm": xnorm, "YNorm": ynorm})
    return out


def dot(x, y, name=None):
    helper = LayerHelper("dot", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("dot", inputs={"X": x, "Y": y}, outputs={"Out": out})
    return out


def _elementwise_layer(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, inputs={"X": x, "Y": y}, outputs={"Out": out}, attrs={"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_mod", x, y, axis, act, name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype, "min": min, "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype, "mean": mean, "std": std, "seed": seed})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32", input_dim_idx=0,
                                   output_dim_idx=0, min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random_batch_size_like", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype, "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx, "min": min, "max": max, "seed": seed})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0, output_dim_idx=0,
                                    mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random_batch_size_like", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype, "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx, "mean": mean, "std": std, "seed": seed})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", act=act, name=name)
    w = helper.create_parameter(param_attr, shape=[size, x.shape[1], y.shape[1]], dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": x, "Y": y, "Weight": w}
    if bias_attr is not False:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr), shape=[1, size], dtype=x.dtype, is_bias=True)
        inputs["Bias"] = bias
    helper.append_op("bilinear_tensor_product", inputs=inputs, outputs={"Out": out})
    return helper.append_activation(out)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    n_out = num if num else len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype) for _ in range(n_out)]
    helper.append_op("split", inputs={"X": input}, outputs={"Out": outs},
                     attrs={"num": num, "sections": sections, "axis": dim})
    return outs


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op("multiplex", inputs={"X": list(inputs), "Ids": index}, outputs={"Out": out})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": label}
    if prior_dist is not None:
        inputs["PriorDist"] = prior_dist
    helper.append_op("label_smooth", inputs=inputs, outputs={"Out": out},
                     attrs={"epsilon": float(epsilon)})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("mean_iou", inputs={"Predictions": input, "Labels": label},
                     outputs={"OutMeanIou": out}, attrs={"num_classes": num_classes})
    return out


def space_to_depth(x, blocksize, name=None):
    return _single_op_layer("space_to_depth", "space_to_depth", x, {"blocksize": blocksize}, name=name)


def shuffle_channel(x, group, name=None):
    return _single_op_layer("shuffle_channel", "shuffle_channel", x, {"group": group}, name=name)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter (reference: layers/nn.py autoincreased_step_counter)."""
    from .. import initializer as init_mod

    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        [1], "int64", name, initializer=init_mod.Constant(begin - 1))
    helper.append_op("increment", inputs={"X": counter}, outputs={"Out": counter},
                     attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


def _pair(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x, x)


def _triple(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x, x, x)


# -- structured-loss tail (ops/loss_ops.py) -----------------------------------


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (reference: layers/nn.py warpctc → operators/warpctc_op.cc).
    input [B, T, C] raw logits + input_length [B]; label [B, L] +
    label_length [B] — padded+Length replacing the reference's LoD packing."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Logits": input, "Label": label}
    if input_length is not None:
        inputs["LogitsLength"] = input_length
    if label_length is not None:
        inputs["LabelLength"] = label_length
    helper.append_op("warpctc", inputs=inputs, outputs={"Loss": loss},
                     attrs={"blank": int(blank), "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode (reference: layers/nn.py ctc_greedy_decoder =
    argmax + ctc_align). input [B, T, C] probs/logits → (decoded [B, T]
    padded -1, length [B])."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    ids = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int32")
    out_len = helper.create_variable_for_type_inference("int32")
    inputs = {"Input": ids}
    if input_length is not None:
        inputs["Length"] = input_length
    helper.append_op("ctc_align", inputs=inputs,
                     outputs={"Output": out, "OutputLength": out_len},
                     attrs={"blank": int(blank)})
    return out, out_len


def linear_chain_crf(input, label, param_attr=None, length=None):
    """reference: layers/nn.py linear_chain_crf. input [B, T, D] emissions +
    length [B]; creates the [D+2, D] transition parameter."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = int(input.shape[-1])
    transition = helper.create_parameter(
        attr=helper.kwargs.get("param_attr"), shape=[size + 2, size], dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    e_exps = helper.create_variable_for_type_inference(input.dtype)
    t_exps = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": input, "Transition": transition, "Label": label}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("linear_chain_crf", inputs=inputs,
                     outputs={"LogLikelihood": ll, "Alpha": alpha,
                              "EmissionExps": e_exps, "TransitionExps": t_exps})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the trained transition param (reference:
    layers/nn.py crf_decoding)."""
    helper = LayerHelper("crf_decoding")
    transition = helper.main_program.global_block.var(param_attr.name)
    path = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": input, "Transition": transition, "Label": label}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": path})
    return path


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """reference: layers/nn.py nce → operators/nce_op.cc."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr, name=name)
    dim = int(input.shape[-1])
    w = helper.create_parameter(attr=helper.kwargs.get("param_attr"),
                                shape=[num_total_classes, dim], dtype=input.dtype)
    inputs = {"Input": input, "Weight": w, "Label": label}
    if helper.kwargs.get("bias_attr") is not False:
        b = helper.create_parameter(attr=helper.kwargs.get("bias_attr"),
                                    shape=[num_total_classes], dtype=input.dtype,
                                    is_bias=True)
        inputs["Bias"] = b
    cost = helper.create_variable_for_type_inference(input.dtype)
    s_logits = helper.create_variable_for_type_inference(input.dtype)
    s_labels = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "nce", inputs=inputs,
        outputs={"Cost": cost, "SampleLogits": s_logits, "SampleLabels": s_labels},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": int(num_neg_samples or 10), "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """reference: layers/nn.py hsigmoid → hierarchical_sigmoid_op.cc
    (default complete binary tree; custom trees via path_table unsupported —
    raise rather than silently mis-train)."""
    if is_custom or path_table is not None or path_code is not None:
        raise NotImplementedError("custom-tree hsigmoid (path_table/path_code) "
                                  "is not implemented")
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = int(input.shape[-1])
    w = helper.create_parameter(attr=helper.kwargs.get("param_attr"),
                                shape=[num_classes - 1, dim], dtype=input.dtype)
    inputs = {"X": input, "W": w, "Label": label}
    if helper.kwargs.get("bias_attr") is not False:
        b = helper.create_parameter(attr=helper.kwargs.get("bias_attr"),
                                    shape=[num_classes - 1], dtype=input.dtype,
                                    is_bias=True)
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": out, "PreOut": pre_out},
                     attrs={"num_classes": int(num_classes)})
    return out


def sample_logits(logits, label, num_samples, uniq=True,
                  remove_accidental_hits=True, use_customized_samples=False,
                  customized_samples=None, customized_probabilities=None,
                  seed=0):
    """Sampled-softmax helper (reference: operators/sample_logits_op.cc)."""
    helper = LayerHelper("sample_logits")
    samples = helper.create_variable_for_type_inference("int32")
    probs = helper.create_variable_for_type_inference(logits.dtype)
    s_logits = helper.create_variable_for_type_inference(logits.dtype)
    s_labels = helper.create_variable_for_type_inference("int64")
    inputs = {"Logits": logits, "Labels": label}
    if use_customized_samples:
        inputs["CustomizedSamples"] = customized_samples
        inputs["CustomizedProbabilities"] = customized_probabilities
    helper.append_op(
        "sample_logits", inputs=inputs,
        outputs={"Samples": samples, "Probabilities": probs,
                 "SampledLogits": s_logits, "SampledLabels": s_labels},
        attrs={"num_samples": int(num_samples), "uniq": uniq,
               "remove_accidental_hits": remove_accidental_hits, "seed": seed})
    return s_logits, s_labels


__all__ += ["warpctc", "ctc_greedy_decoder", "linear_chain_crf", "crf_decoding",
            "nce", "hsigmoid", "sample_logits"]


# -- metrics / vision tail / host ops -----------------------------------------


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance (reference: layers/nn.py edit_distance).
    input [B, Lh] int + input_length, label [B, Lr] + label_length."""
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    inputs = {"Hyps": input, "Refs": label}
    if input_length is not None:
        inputs["HypsLength"] = input_length
    if label_length is not None:
        inputs["RefsLength"] = label_length
    helper.append_op("edit_distance", inputs=inputs,
                     outputs={"Out": out, "SequenceNum": seq_num},
                     attrs={"normalized": normalized})
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk detection P/R/F1 (reference: layers/nn.py chunk_eval)."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    n_inf = helper.create_variable_for_type_inference("int64")
    n_lab = helper.create_variable_for_type_inference("int64")
    n_cor = helper.create_variable_for_type_inference("int64")
    inputs = {"Inference": input, "Label": label}
    if seq_length is not None:
        inputs["Length"] = seq_length
    helper.append_op(
        "chunk_eval", inputs=inputs,
        outputs={"Precision": precision, "Recall": recall, "F1-Score": f1,
                 "NumInferChunks": n_inf, "NumLabelChunks": n_lab,
                 "NumCorrectChunks": n_cor},
        attrs={"num_chunk_types": num_chunk_types, "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, n_inf, n_lab, n_cor


def grid_sampler(x, grid, name=None):
    """Bilinear grid sampling (reference: operators/grid_sampler_op.cc)."""
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler", inputs={"X": x, "Grid": grid},
                     outputs={"Output": out})
    return out


def spp(input, pyramid_height=1, pool_type="max", name=None):
    """Spatial pyramid pooling (reference: operators/spp_op.cc)."""
    helper = LayerHelper("spp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("spp", inputs={"X": input}, outputs={"Out": out},
                     attrs={"pyramid_height": pyramid_height,
                            "pooling_type": pool_type})
    return out


def unpool(x, indices, ksize, strides=None, unpooled_size=None, name=None):
    """Max unpooling via recorded indices (reference: operators/unpool_op.cc)."""
    helper = LayerHelper("unpool", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("unpool", inputs={"X": x, "Indices": indices},
                     outputs={"Out": out},
                     attrs={"ksize": list(ksize),
                            "strides": list(strides or ksize),
                            "unpooled_size": list(unpooled_size) if unpooled_size else None})
    return out


def max_pool2d_with_index(x, ksize, strides=None, paddings=None, name=None):
    helper = LayerHelper("max_pool2d_with_index", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    helper.append_op("max_pool2d_with_index", inputs={"X": x},
                     outputs={"Out": out, "Mask": mask},
                     attrs={"ksize": list(ksize), "strides": list(strides or ksize),
                            "paddings": list(paddings or [0, 0])})
    return out, mask


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, batch_id=None, name=None):
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("psroi_pool",
                     inputs={"X": input, "ROIs": rois, "BatchId": batch_id},
                     outputs={"Out": out},
                     attrs={"output_channels": output_channels,
                            "spatial_scale": float(spatial_scale),
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width})
    return out


def Print(input, first_n=-1, message=None, summarize=-1, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Tensor tap-out (reference: layers/control_flow.py Print → print_op.cc)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("print", inputs={"In": input}, outputs={"Out": out},
                     attrs={"message": message or "", "first_n": first_n,
                            "summarize": summarize})
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference: layers/nn.py py_func → py_func_op.cc).
    ``out`` must be pre-created variables with known shape/dtype."""
    from ..ops.misc_ops import register_py_func

    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    fwd_id = register_py_func(func)
    bwd_id = register_py_func(backward_func) if backward_func else -1
    helper.append_op("py_func", inputs={"X": list(xs)}, outputs={"Out": list(outs)},
                     attrs={"forward_callable_id": fwd_id,
                            "backward_callable_id": bwd_id})
    return out


__all__ += ["edit_distance", "chunk_eval", "grid_sampler", "spp", "unpool",
            "max_pool2d_with_index", "psroi_pool", "Print", "py_func"]


# -- round-3 layer-surface parity sweep (VERDICT item 4) ----------------------


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """Adaptive pooling to a fixed output size (reference: nn.py
    adaptive_pool2d → pool2d op with adaptive=True)."""
    if require_index:
        raise NotImplementedError(
            "adaptive_pool2d(require_index=True): argmax-index output is not "
            "implemented; use max_pool2d_with_index for indices")
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d", inputs={"X": input}, outputs={"Out": out},
        attrs={"pooling_type": pool_type, "ksize": list(_pair(pool_size)),
               "adaptive": True})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """3-D adaptive pooling (reference: nn.py adaptive_pool3d)."""
    if require_index:
        raise NotImplementedError(
            "adaptive_pool3d(require_index=True): argmax-index output is not "
            "implemented; use max_pool2d_with_index for indices")
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool3d", inputs={"X": input}, outputs={"Out": out},
        attrs={"pooling_type": pool_type, "ksize": list(_triple(pool_size)),
               "adaptive": True})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """3-D transposed convolution (reference: nn.py conv3d_transpose)."""
    helper = LayerHelper("conv3d_transpose", bias_attr=bias_attr, act=act,
                         name=name)
    num_channels = input.shape[1]
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("filter_size or output_size required")
        output_size = _triple(output_size)
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1
            for i in range(3)
        ]
    else:
        filter_size = list(_triple(filter_size))
    filter_shape = [num_channels, num_filters // (groups or 1)] + filter_size
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype)
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d_transpose",
        inputs={"Input": input, "Filter": w},
        outputs={"Output": pre_bias},
        attrs={"strides": list(stride), "paddings": list(padding),
               "dilations": list(dilation), "groups": groups or 1},
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[num_filters],
                                       dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": pre_bias, "Y": bias},
                         outputs={"Out": pre_act}, attrs={"axis": 1})
    return helper.append_activation(pre_act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectral weight normalization (reference: nn.py spectral_norm,
    operators/spectral_norm_op.cc). The power-iteration vectors U/V live as
    persistent non-trainable parameters; the op writes their updated values
    back (UOut/VOut wired onto the same vars), so the iteration state
    advances across steps like the reference's in-place buffers."""
    from .. import initializer as init_mod

    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w = 1
    for i, s in enumerate(weight.shape):
        if i != dim:
            w *= s
    u = helper.create_parameter(
        ParamAttr(trainable=False, initializer=init_mod.Normal(0.0, 1.0)),
        shape=[h], dtype=weight.dtype)
    v = helper.create_parameter(
        ParamAttr(trainable=False, initializer=init_mod.Normal(0.0, 1.0)),
        shape=[w], dtype=weight.dtype)
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op(
        "spectral_norm",
        inputs={"Weight": weight, "U": u, "V": v},
        outputs={"Out": out, "UOut": u, "VOut": v},
        attrs={"dim": dim, "power_iters": power_iters, "eps": eps},
    )
    return out


def dice_loss(input, label, epsilon=0.00001):
    """Dice loss for segmentation (reference: nn.py dice_loss — a pure
    layer composition, mirrored here)."""
    label = one_hot(label, depth=input.shape[-1])
    reduce_dim = list(range(1, len(input.shape)))
    inse = reduce_sum(elementwise_mul(input, label), dim=reduce_dim)
    denom = reduce_sum(input, dim=reduce_dim) + reduce_sum(label, dim=reduce_dim)
    dice_score = 1 - inse * 2 / (denom + epsilon)
    return reduce_mean(dice_score)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the short image edge equals ``out_short_len`` (reference:
    nn.py image_resize_short — static-shape composition over image_resize)."""
    in_shape = input.shape
    if len(in_shape) != 4:
        raise ValueError(
            "The rank of input must be 4 (num_batches, channels, in_h, in_w).")
    hw = list(in_shape[2:4])
    short_idx = hw.index(min(hw))
    long_idx = 1 - short_idx
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[long_idx] = int(
        float(out_shape[long_idx]) * (float(out_short_len) / float(hw[short_idx]))
        + 0.5)
    return image_resize(input=input, out_shape=out_shape, resample=resample)


def sampled_softmax_with_cross_entropy(logits, label, num_samples, num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None, seed=0):
    """Sampled-softmax cross entropy (reference: nn.py
    sampled_softmax_with_cross_entropy — composes the sample_logits op with
    soft-label softmax_with_cross_entropy)."""
    if num_true != 1:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: num_true>1 (the reference's "
            "one_hot(depth=num_samples+1) construction is only consistent "
            "for a single true label)")
    helper = LayerHelper("sample_logits")
    samples = helper.create_variable_for_type_inference("int64",
                                                        stop_gradient=True)
    probabilities = helper.create_variable_for_type_inference(logits.dtype,
                                                              stop_gradient=True)
    sampled_logits = helper.create_variable_for_type_inference(logits.dtype)
    sampled_label = helper.create_variable_for_type_inference("int64",
                                                              stop_gradient=True)
    inputs = {"Logits": logits, "Labels": label}
    if customized_samples is not None:
        inputs["CustomizedSamples"] = customized_samples
    if customized_probabilities is not None:
        inputs["CustomizedProbabilities"] = customized_probabilities
    helper.append_op(
        "sample_logits",
        inputs=inputs,
        outputs={"Samples": samples, "Probabilities": probabilities,
                 "SampledLogits": sampled_logits,
                 "SampledLabels": sampled_label},
        attrs={"use_customized_samples": use_customized_samples, "uniq": True,
               "remove_accidental_hits": remove_accidental_hits,
               "num_samples": num_samples, "seed": seed},
    )
    soft = one_hot(sampled_label, depth=num_true + num_samples)
    loss = softmax_with_cross_entropy(sampled_logits, soft, soft_label=True)
    return loss / num_true


def hash(input, hash_size, num_hash=1, name=None):
    """Row-wise integer hashing into [0, hash_size) (reference: nn.py hash,
    operators/hash_op.cc)."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op("hash", inputs={"X": input}, outputs={"Out": out},
                     attrs={"num_hash": num_hash, "mod_by": hash_size})
    return out


def merge_selected_rows(x, name=None):
    """Sum duplicate rows of a SelectedRows value (reference: nn.py
    merge_selected_rows)."""
    helper = LayerHelper("merge_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("merge_selected_rows", inputs={"X": x},
                     outputs={"Out": out})
    return out


def get_tensor_from_selected_rows(x, name=None):
    """Densify a SelectedRows' value block (reference: nn.py
    get_tensor_from_selected_rows)."""
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("get_tensor_from_selected_rows", inputs={"X": x},
                     outputs={"Out": out})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1, max_depth=2,
              act="tanh", param_attr=None, bias_attr=None, name=None):
    """Tree-based convolution (reference: nn.py tree_conv, TBCNN)."""
    helper = LayerHelper("tree_conv", bias_attr=bias_attr, act=act, name=name)
    feature_size = nodes_vector.shape[2]
    w = helper.create_parameter(
        param_attr, shape=[feature_size, 3, output_size, num_filters],
        dtype=nodes_vector.dtype)
    pre_bias = helper.create_variable_for_type_inference(nodes_vector.dtype)
    helper.append_op(
        "tree_conv",
        inputs={"NodesVector": nodes_vector, "EdgeSet": edge_set, "Filter": w},
        outputs={"Out": pre_bias},
        attrs={"max_depth": max_depth},
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        bias = helper.create_parameter(
            ParamAttr.to_attr(bias_attr), shape=[num_filters],
            dtype=nodes_vector.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(nodes_vector.dtype)
        helper.append_op("elementwise_add", inputs={"X": pre_bias, "Y": bias},
                         outputs={"Out": pre_act}, attrs={"axis": -1})
    return helper.append_activation(pre_act)


__all__ += ["adaptive_pool2d", "adaptive_pool3d", "conv3d_transpose",
            "spectral_norm", "dice_loss", "image_resize_short",
            "sampled_softmax_with_cross_entropy", "hash",
            "merge_selected_rows", "get_tensor_from_selected_rows",
            "tree_conv"]
