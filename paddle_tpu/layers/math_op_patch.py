"""Operator overloading on Variable (reference: layers/math_op_patch.py)."""

from __future__ import annotations

import numpy as np

from ..core.framework import Variable


def binary_op(x: Variable, other, op_name: str, reverse: bool = False) -> Variable:
    from .layer_helper import LayerHelper
    from . import tensor as tensor_layers

    helper = LayerHelper(op_name)
    if not isinstance(other, Variable):
        # scalar fast path for add/sub/mul/div via scale op
        scalar = float(other)
        if not reverse:
            if op_name == "elementwise_add":
                return tensor_layers.scale(x, scale=1.0, bias=scalar)
            if op_name == "elementwise_sub":
                return tensor_layers.scale(x, scale=1.0, bias=-scalar)
            if op_name == "elementwise_mul":
                return tensor_layers.scale(x, scale=scalar)
            if op_name == "elementwise_div":
                return tensor_layers.scale(x, scale=1.0 / scalar)
        else:
            if op_name == "elementwise_add":
                return tensor_layers.scale(x, scale=1.0, bias=scalar)
            if op_name == "elementwise_sub":
                return tensor_layers.scale(x, scale=-1.0, bias=scalar)
            if op_name == "elementwise_mul":
                return tensor_layers.scale(x, scale=scalar)
        other = tensor_layers.fill_constant([1], x.dtype, scalar)
    a, b = (other, x) if reverse else (x, other)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_name, inputs={"X": a, "Y": b}, outputs={"Out": out}, attrs={"axis": -1})
    return out
