from . import attention, beam_search as beam_search_mod, control_flow, detection, io, learning_rate_scheduler, nn, rnn, sequence, tensor  # noqa: F401
from .detection import (  # noqa: F401
    anchor_generator,
    bipartite_match,
    box_clip,
    box_coder,
    density_prior_box,
    detection_output,
    generate_proposals,
    iou_similarity,
    mine_hard_examples,
    multi_box_head,
    multiclass_nms,
    polygon_box_transform,
    prior_box,
    detection_map,
    generate_mask_labels,
    generate_proposal_labels,
    roi_align,
    roi_perspective_transform,
    roi_pool,
    rpn_target_assign,
    ssd_loss,
    target_assign,
    yolov3_loss,
)
from .learning_rate_scheduler import (  # noqa: F401
    append_LARS,
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    linear_lr_warmup,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
from .beam_search import (  # noqa: F401
    array_length,
    array_read,
    array_to_tensor,
    array_write,
    beam_search,
    beam_search_decode,
    create_array,
)
from .attention import multi_head_attention, scaled_dot_product_attention  # noqa: F401
from .rnn import dynamic_lstm, dynamic_lstmp, dynamic_gru, lstm, lstm_unit, gru_unit  # noqa: F401
from .control_flow import (  # noqa: F401
    DynamicRNN,
    IfElse,
    StaticRNN,
    Switch,
    While,
    cond,
    equal,
    greater_equal,
    greater_than,
    less_equal,
    less_than,
    logical_and,
    logical_not,
    logical_or,
    not_equal,
)
from .sequence import *  # noqa: F401,F403
from .io import (batch, create_py_reader_by_data, data, double_buffer, load,  # noqa: F401
                 py_reader, read_file, shuffle)
from .control_flow import is_empty  # noqa: F401
from .layer_helper import LayerHelper, ParamAttr  # noqa: F401
from .nn import *  # noqa: F401,F403
from .layer_function_generator import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    assign,
    cast,
    concat,
    create_global_var,
    fill_constant,
    ones,
    reshape,
    scale,
    transpose,
    zeros,
)
