from . import attention, beam_search as beam_search_mod, control_flow, detection, io, learning_rate_scheduler, nn, rnn, sequence, tensor  # noqa: F401
from .detection import (  # noqa: F401
    anchor_generator,
    bipartite_match,
    box_clip,
    box_coder,
    density_prior_box,
    detection_output,
    generate_proposals,
    iou_similarity,
    mine_hard_examples,
    multi_box_head,
    multiclass_nms,
    polygon_box_transform,
    prior_box,
    roi_align,
    roi_pool,
    ssd_loss,
    target_assign,
    yolov3_loss,
)
from .beam_search import (  # noqa: F401
    array_length,
    array_read,
    array_to_tensor,
    array_write,
    beam_search,
    beam_search_decode,
    create_array,
)
from .attention import multi_head_attention, scaled_dot_product_attention  # noqa: F401
from .rnn import dynamic_lstm, dynamic_lstmp, dynamic_gru, lstm, lstm_unit, gru_unit  # noqa: F401
from .control_flow import (  # noqa: F401
    DynamicRNN,
    IfElse,
    StaticRNN,
    Switch,
    While,
    cond,
    equal,
    greater_equal,
    greater_than,
    less_equal,
    less_than,
    logical_and,
    logical_not,
    logical_or,
    not_equal,
)
from .sequence import *  # noqa: F401,F403
from .io import create_py_reader_by_data, data, double_buffer, py_reader, read_file  # noqa: F401
from .layer_helper import LayerHelper, ParamAttr  # noqa: F401
from .nn import *  # noqa: F401,F403
from .layer_function_generator import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    assign,
    cast,
    concat,
    create_global_var,
    fill_constant,
    ones,
    reshape,
    scale,
    transpose,
    zeros,
)
