from . import io, nn, tensor  # noqa: F401
from .io import data  # noqa: F401
from .layer_helper import LayerHelper, ParamAttr  # noqa: F401
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    assign,
    cast,
    concat,
    create_global_var,
    fill_constant,
    ones,
    reshape,
    scale,
    transpose,
    zeros,
)
