"""Detection layer builders (reference: python/paddle/fluid/layers/detection.py
— 2.2k LoC: prior_box:1109, box_coder:345, multiclass_nms:2108,
detection_output:204, ssd_loss:875, multi_box_head:1355, yolov3_loss:508,
bipartite_match:703, target_assign:789, anchor_generator:1601,
generate_proposals:1973, box_clip:2060, iou_similarity:317).

Dense-batch conventions (see ops/detection_ops.py): ground-truth inputs are
[B, Ng, ...] with zero-area padding rows instead of LoD; variable-size
outputs are padded + Length.
"""

from __future__ import annotations

from .layer_helper import LayerHelper
from . import nn as nn_layers
from . import tensor as tensor_layers

__all__ = [
    "iou_similarity", "box_coder", "prior_box", "density_prior_box",
    "anchor_generator", "box_clip", "bipartite_match", "target_assign",
    "multiclass_nms", "detection_output", "ssd_loss", "multi_box_head",
    "roi_align", "roi_pool", "yolov3_loss", "generate_proposals",
    "polygon_box_transform", "mine_hard_examples",
]


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("iou_similarity", inputs={"X": x, "Y": y},
                     outputs={"Out": out}, attrs={"box_normalized": box_normalized})
    return out


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, name=None, axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(prior_box.dtype)
    inputs = {"PriorBox": prior_box, "TargetBox": target_box}
    attrs = {"code_type": code_type, "box_normalized": box_normalized, "axis": axis}
    if isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = [float(v) for v in prior_box_var]
    elif prior_box_var is not None:
        inputs["PriorBoxVar"] = prior_box_var
    helper.append_op("box_coder", inputs=inputs, outputs={"OutputBox": out}, attrs=attrs)
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "prior_box", inputs={"Input": input, "Image": image},
        outputs={"Boxes": boxes, "Variances": var},
        attrs={
            "min_sizes": [float(s) for s in (min_sizes if isinstance(min_sizes, (list, tuple)) else [min_sizes])],
            "max_sizes": [float(s) for s in (max_sizes or [])] if not isinstance(max_sizes, (int, float)) else [float(max_sizes)],
            "aspect_ratios": [float(a) for a in aspect_ratios],
            "variances": [float(v) for v in variance],
            "flip": flip, "clip": clip,
            "step_w": float(steps[0]), "step_h": float(steps[1]),
            "offset": float(offset),
            "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
        })
    return boxes, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "density_prior_box", inputs={"Input": input, "Image": image},
        outputs={"Boxes": boxes, "Variances": var},
        attrs={
            "densities": [int(d) for d in densities],
            "fixed_sizes": [float(s) for s in fixed_sizes],
            "fixed_ratios": [float(r) for r in fixed_ratios],
            "variances": [float(v) for v in variance],
            "clip": clip, "step_w": float(steps[0]), "step_h": float(steps[1]),
            "offset": float(offset),
        })
    if flatten_to_2d:
        boxes = tensor_layers.reshape(boxes, shape=[-1, 4])
        var = tensor_layers.reshape(var, shape=[-1, 4])
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "anchor_generator", inputs={"Input": input},
        outputs={"Anchors": anchors, "Variances": var},
        attrs={
            "anchor_sizes": [float(s) for s in anchor_sizes],
            "aspect_ratios": [float(r) for r in aspect_ratios],
            "variances": [float(v) for v in variance],
            "stride": [float(s) for s in stride],
            "offset": float(offset),
        })
    return anchors, var


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("box_clip", inputs={"Input": input, "ImInfo": im_info},
                     outputs={"Output": out})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        "bipartite_match", inputs={"DistMat": dist_matrix},
        outputs={"ColToRowMatchIndices": idx, "ColToRowMatchDist": dist},
        attrs={"match_type": match_type or "bipartite",
               "dist_threshold": float(dist_threshold or 0.5)})
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """``negative_indices`` takes the NegMask [B, M] produced by
    mine_hard_examples (static-shape stand-in for the reference's LoD)."""
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "target_assign",
        inputs={"X": input, "MatchIndices": matched_indices, "NegMask": negative_indices},
        outputs={"Out": out, "OutWeight": out_weight},
        attrs={"mismatch_value": mismatch_value or 0})
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, match_dist, neg_pos_ratio=3.0,
                       neg_dist_threshold=0.5, name=None):
    helper = LayerHelper("mine_hard_examples", name=name)
    neg_mask = helper.create_variable_for_type_inference("int32")
    updated = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "mine_hard_examples",
        inputs={"ClsLoss": cls_loss, "MatchIndices": match_indices,
                "MatchDist": match_dist},
        outputs={"NegMask": neg_mask, "UpdatedMatchIndices": updated},
        attrs={"neg_pos_ratio": float(neg_pos_ratio),
               "neg_dist_threshold": float(neg_dist_threshold)})
    return neg_mask, updated


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_length=False):
    """Out [B, keep_top_k, 6] padded with -1 (+ Length [B] when
    ``return_length``) — padded+Length replacing the reference's LoD out."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    length = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "multiclass_nms", inputs={"BBoxes": bboxes, "Scores": scores},
        outputs={"Out": out, "Length": length},
        attrs={"background_label": background_label,
               "score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k), "nms_threshold": float(nms_threshold),
               "nms_eta": float(nms_eta), "keep_top_k": int(keep_top_k),
               "normalized": normalized})
    return (out, length) if return_length else out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_length=False):
    """SSD inference head (reference: detection.py:204): decode loc against
    priors, then class-wise NMS. loc [B, P, 4], scores [B, P, C] (softmax'd
    here), priors [P, 4]."""
    # loc [B, P, 4]: priors vary along dim 1 → axis=0 (reference
    # DecodeCenterSize indexes priors by the second target dim when axis==0)
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size", axis=0)
    scores = nn_layers.softmax(scores, axis=-1)
    scores_t = tensor_layers.transpose(scores, perm=[0, 2, 1])  # [B, C, P]
    return multiclass_nms(
        decoded, scores_t, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k, nms_threshold=nms_threshold,
        nms_eta=nms_eta, background_label=background_label,
        return_length=return_length)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box, prior_box_var=None,
             background_label=0, overlap_threshold=0.5, neg_pos_ratio=3.0,
             neg_overlap=0.5, loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative",
             normalize=True, sample_size=None):
    """SSD multibox loss (reference: detection.py:875 ssd_loss).

    location [B, P, 4], confidence [B, P, C], gt_box [B, Ng, 4] (zero-area
    rows = padding), gt_label [B, Ng, 1] int, prior_box [P, 4]. Returns the
    per-image loss [B, 1]: matching → hard-negative mining → weighted
    loc (smooth-L1) + conf (softmax CE) losses, normalized by matched count.
    """
    if mining_type != "max_negative":
        raise NotImplementedError("only max_negative mining is implemented "
                                  "(the reference's hard_example path is unused upstream)")
    # 1. match priors to gt by IoU
    iou = iou_similarity(gt_box, prior_box)                  # [B, Ng, P]
    matched_index, matched_dist = bipartite_match(iou, match_type, overlap_threshold)

    # 2. conf loss with current confidences (for mining)
    gt_lbl_f = tensor_layers.cast(gt_label, "int64")
    tgt_lbl, _ = target_assign(gt_lbl_f, matched_index,
                               mismatch_value=background_label)  # [B, P, 1]
    conf_loss_all = nn_layers.softmax_with_cross_entropy(
        confidence, tensor_layers.cast(tgt_lbl, "int64"))        # [B, P, 1]
    conf_loss_2d = tensor_layers.reshape(conf_loss_all, shape=[0, -1])

    # 3. mine hard negatives
    neg_mask, _ = mine_hard_examples(conf_loss_2d, matched_index, matched_dist,
                                     neg_pos_ratio=neg_pos_ratio,
                                     neg_dist_threshold=neg_overlap)

    # 4. targets: encoded loc for positives; labels incl. mined negatives
    encoded = box_coder(prior_box, prior_box_var, gt_box)        # [B, Ng, P, 4]
    loc_tgt, loc_w = target_assign(encoded, matched_index)       # [B, P, 4], [B, P, 1]
    conf_tgt, conf_w = target_assign(gt_lbl_f, matched_index,
                                     negative_indices=neg_mask,
                                     mismatch_value=background_label)

    # 5. weighted losses (2D per-prior rows, reference __reshape_to_2d)
    loc_2d = tensor_layers.reshape(location, shape=[-1, 4])
    tgt_2d = tensor_layers.reshape(loc_tgt, shape=[-1, 4])
    loc_loss = nn_layers.smooth_l1(loc_2d, tgt_2d)               # [B*P, 1]
    loc_loss = tensor_layers.reshape(loc_loss, shape=[0, -1])    # keep 2D
    conf_loss = nn_layers.softmax_with_cross_entropy(
        confidence, tensor_layers.cast(conf_tgt, "int64"))
    loc_w2 = tensor_layers.reshape(loc_w, shape=[-1, 1])
    conf_w2 = tensor_layers.reshape(conf_w, shape=[-1, 1])
    conf_2d = tensor_layers.reshape(conf_loss, shape=[-1, 1])

    b_rows = tensor_layers.reshape(
        nn_layers.elementwise_add(
            tensor_layers.scale(nn_layers.elementwise_mul(loc_loss, loc_w2),
                                scale=loc_loss_weight),
            tensor_layers.scale(nn_layers.elementwise_mul(conf_2d, conf_w2),
                                scale=conf_loss_weight)),
        shape=[-1, int(location.shape[1])])
    loss = nn_layers.reduce_sum(b_rows, dim=1, keep_dim=True)    # [B, 1]
    if normalize:
        denom = nn_layers.reduce_sum(
            tensor_layers.reshape(loc_w, shape=[0, -1]), dim=1, keep_dim=True)
        denom = nn_layers.clip(denom, min=1.0, max=1e30)
        loss = nn_layers.elementwise_div(loss, denom)
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None, max_sizes=None,
                   steps=None, step_w=None, step_h=None, offset=0.5,
                   variance=(0.1, 0.1, 0.2, 0.2), flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD head over multiple feature maps (reference: detection.py:1355):
    per-level conv predictions for loc/conf + priors, concatenated."""
    n_layer = len(inputs)
    if min_sizes is None:
        # evenly spaced ratios between min_ratio and max_ratio (reference alg)
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2)) if n_layer > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n_layer - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n_layer - 1]

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) else [min_sizes[i]]
        maxs = (max_sizes[i] if isinstance(max_sizes[i], (list, tuple)) else [max_sizes[i]]) if max_sizes else []
        ars = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) else [aspect_ratios[i]]
        step_pair = (steps[i] if steps else (step_w[i] if step_w else 0.0,
                                             step_h[i] if step_h else 0.0))
        if not isinstance(step_pair, (list, tuple)):
            step_pair = (step_pair, step_pair)
        box, var = prior_box(feat, image, mins, maxs, ars, variance, flip, clip,
                             step_pair, offset,
                             min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        boxes_l.append(tensor_layers.reshape(box, shape=[-1, 4]))
        vars_l.append(tensor_layers.reshape(var, shape=[-1, 4]))
        # count must mirror the op: 1 (min) + extra ARs + (1 if max)
        ar_n = 1
        seen = [1.0]
        for r in ars:
            r = float(r)
            if all(abs(r - s) > 1e-6 for s in seen):
                seen.append(r)
                ar_n += 1
                if flip:
                    seen.append(1.0 / r)
                    ar_n += 1
        num_priors = ar_n * len(mins) + (len(maxs) if maxs else 0)

        loc = nn_layers.conv2d(feat, num_filters=num_priors * 4,
                               filter_size=kernel_size, padding=pad, stride=stride)
        loc = tensor_layers.transpose(loc, perm=[0, 2, 3, 1])
        locs.append(tensor_layers.reshape(loc, shape=[0, -1, 4]))
        conf = nn_layers.conv2d(feat, num_filters=num_priors * num_classes,
                                filter_size=kernel_size, padding=pad, stride=stride)
        conf = tensor_layers.transpose(conf, perm=[0, 2, 3, 1])
        confs.append(tensor_layers.reshape(conf, shape=[0, -1, num_classes]))

    mbox_locs = tensor_layers.concat(locs, axis=1)
    mbox_confs = tensor_layers.concat(confs, axis=1)
    boxes = tensor_layers.concat(boxes_l, axis=0)
    vars_ = tensor_layers.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, vars_


def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, batch_id=None, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "roi_align", inputs={"X": input, "ROIs": rois, "BatchId": batch_id},
        outputs={"Out": out},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": float(spatial_scale),
               "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             batch_id=None, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "roi_pool", inputs={"X": input, "ROIs": rois, "BatchId": batch_id},
        outputs={"Out": out},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": float(spatial_scale)})
    return out


def yolov3_loss(x, gtbox, gtlabel, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, name=None):
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "yolov3_loss", inputs={"X": x, "GTBox": gtbox, "GTLabel": gtlabel},
        outputs={"Loss": loss},
        attrs={"anchors": [int(a) for a in anchors],
               "anchor_mask": [int(m) for m in anchor_mask],
               "class_num": int(class_num),
               "ignore_thresh": float(ignore_thresh),
               "downsample_ratio": int(downsample_ratio)})
    return loss


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, name=None, return_length=False):
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    length = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "generate_proposals",
        inputs={"Scores": scores, "BboxDeltas": bbox_deltas, "ImInfo": im_info,
                "Anchors": anchors, "Variances": variances},
        outputs={"RpnRois": rois, "RpnRoiProbs": probs, "Length": length},
        attrs={"pre_nms_topN": int(pre_nms_top_n), "post_nms_topN": int(post_nms_top_n),
               "nms_thresh": float(nms_thresh), "min_size": float(min_size)})
    if return_length:
        return rois, probs, length
    return rois, probs


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("polygon_box_transform", inputs={"Input": input},
                     outputs={"Output": out})
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var, gt_boxes,
                      is_crowd=None, im_info=None, rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      use_random=True):
    """RPN anchor sampling (reference: detection.py:57 → 
    rpn_target_assign_op.cc). Static redesign: returns
    (score_mask [B, A] {-1 ignore, 0 bg, 1 fg}, target_label [B, A],
    target_bbox [B, A, 4], bbox_inside_weight [B, A, 4]) instead of the
    reference's ragged gathered index lists; the losses mask with
    score_mask >= 0 (score) and == 1 (loc)."""
    helper = LayerHelper("rpn_target_assign")
    score_mask = helper.create_variable_for_type_inference("int32")
    tgt_lbl = helper.create_variable_for_type_inference("int32")
    tgt_bbox = helper.create_variable_for_type_inference(gt_boxes.dtype)
    inw = helper.create_variable_for_type_inference(gt_boxes.dtype)
    helper.append_op(
        "rpn_target_assign",
        inputs={"Anchor": anchor_box, "GtBoxes": gt_boxes, "IsCrowd": is_crowd,
                "ImInfo": im_info},
        outputs={"ScoreMask": score_mask, "TargetLabel": tgt_lbl,
                 "TargetBBox": tgt_bbox, "BBoxInsideWeight": inw},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "use_random": use_random})
    return score_mask, tgt_lbl, tgt_bbox, inw


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes, im_info,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """Second-stage RoI sampling (reference: detection.py:1744)."""
    helper = LayerHelper("generate_proposal_labels")
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference("int32")
    tgts = helper.create_variable_for_type_inference(rpn_rois.dtype)
    iw = helper.create_variable_for_type_inference(rpn_rois.dtype)
    ow = helper.create_variable_for_type_inference(rpn_rois.dtype)
    roiw = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "generate_proposal_labels",
        inputs={"RpnRois": rpn_rois, "GtClasses": gt_classes,
                "IsCrowd": is_crowd, "GtBoxes": gt_boxes, "ImInfo": im_info},
        outputs={"Rois": rois, "LabelsInt32": labels, "BboxTargets": tgts,
                 "BboxInsideWeights": iw, "BboxOutsideWeights": ow,
                 "RoiWeights": roiw},
        attrs={"batch_size_per_im": batch_size_per_im, "fg_fraction": fg_fraction,
               "fg_thresh": fg_thresh, "bg_thresh_hi": bg_thresh_hi,
               "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": int(class_nums), "use_random": use_random})
    return rois, labels, tgts, iw, ow, roiw


__all__ += ["rpn_target_assign", "generate_proposal_labels"]


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              batch_id=None):
    """Perspective-warp quad RoIs (reference: detection.py
    roi_perspective_transform → roi_perspective_transform_op.cc). rois:
    [R, 8] quad corners; batch_id [R] replaces the reference's RoI LoD."""
    helper = LayerHelper("roi_perspective_transform")
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": input, "ROIs": rois}
    if batch_id is not None:
        inputs["BatchId"] = batch_id
    helper.append_op(
        "roi_perspective_transform", inputs=inputs, outputs={"Out": out},
        attrs={"transformed_height": int(transformed_height),
               "transformed_width": int(transformed_width),
               "spatial_scale": float(spatial_scale)})
    return out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_poly_length=None):
    """Mask R-CNN mask targets (reference: detection.py generate_mask_labels
    → generate_mask_labels_op.cc). gt_segms: [B, Ng, L, 2] padded polygons
    (+ gt_poly_length [B, Ng]) replace the 3-level LoD."""
    helper = LayerHelper("generate_mask_labels")
    mask = helper.create_variable_for_type_inference("int32")
    has = helper.create_variable_for_type_inference("int32")
    inputs = {"ImInfo": im_info, "GtClasses": gt_classes, "IsCrowd": is_crowd,
              "GtSegms": gt_segms, "Rois": rois, "LabelsInt32": labels_int32}
    if gt_poly_length is not None:
        inputs["GtPolyLength"] = gt_poly_length
    helper.append_op(
        "generate_mask_labels", inputs=inputs,
        outputs={"MaskInt32": mask, "RoiHasMaskInt32": has},
        attrs={"num_classes": int(num_classes), "resolution": int(resolution)})
    return mask, has


def detection_map(detect_res, label, class_num=None, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral", det_length=None):
    """Per-batch mAP (reference: detection.py detection_map →
    detection_map_op.cc). Padded convention: detect_res [B, K, 6]
    (+ det_length [B]), label [B, Ng, 5]. Cross-batch accumulation lives in
    metrics.DetectionMAP; the reference's streaming state inputs are not
    supported here."""
    if input_states is not None or out_states is not None or has_state is not None:
        raise NotImplementedError(
            "detection_map: streaming accumulator states are handled by "
            "paddle_tpu.metrics.DetectionMAP; per-batch mAP only here")
    helper = LayerHelper("detection_map")
    out = helper.create_variable_for_type_inference("float32")
    inputs = {"DetectRes": detect_res, "Label": label}
    if det_length is not None:
        inputs["DetLength"] = det_length
    helper.append_op(
        "detection_map", inputs=inputs, outputs={"MAP": out},
        attrs={"overlap_threshold": float(overlap_threshold),
               "ap_type": ap_version,
               "evaluate_difficult": bool(evaluate_difficult),
               "background_label": int(background_label)})
    return out


__all__ += ["roi_perspective_transform", "generate_mask_labels",
            "detection_map"]
