"""Beam search + TensorArray layers.

Reference API: ``python/paddle/fluid/layers/nn.py`` beam_search /
beam_search_decode and ``layers/control_flow.py`` array_write / array_read /
array_length / create_array (LoDTensorArray ops).

Deviations from Fluid, by TPU design (see ops/beam_search_ops.py):
- ``beam_search`` consumes the FULL per-step log-prob tensor ``[B, K, V]``
  (Fluid takes pre-topk'd candidate ids/scores per beam); the batched
  ``top_k`` over K·V runs on-device and removes the host-side LoD walk.
- TensorArrays are fixed-capacity buffers; pass ``capacity`` (e.g. the
  decode max_len) on the first ``array_write``. There is no dynamic growth:
  writes past capacity are dropped by XLA's out-of-bounds scatter rule (the
  write count saturates at capacity so ``array_length`` stays truthful) —
  size ``capacity`` generously.
- An array that is carried through a ``While`` loop must receive its first
  ``array_write`` BEFORE the loop (Fluid's own idiom — the init write at
  i=0): the buffer allocation fixes the carry pytree structure.
"""

from __future__ import annotations

from typing import Optional

from ..core import unique_name
from .layer_helper import LayerHelper

__all__ = ["beam_search", "beam_search_decode", "create_array", "array_write",
           "array_read", "array_length", "array_to_tensor"]


def create_array(dtype="float32", name=None):
    """reference: layers/control_flow.py create_array."""
    helper = LayerHelper("create_array", name=name)
    out = helper.main_program.current_block().create_var(
        name=unique_name.generate("tensor_array"), dtype=dtype)
    helper.append_op("create_array", inputs={}, outputs={"Out": out}, attrs={})
    out.elem_shape = None
    out.elem_dtype = dtype
    return out


def array_write(x, i, array=None, capacity=512):
    """reference: layers/control_flow.py array_write. Returns the (new)
    array; ``capacity`` bounds the buffer allocated on first write."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        "write_to_array", inputs={"X": x, "I": i, "Array": array},
        outputs={"Out": array}, attrs={"capacity": int(capacity)})
    array.elem_shape = x.shape
    array.elem_dtype = x.dtype
    return array


def array_read(array, i):
    """reference: layers/control_flow.py array_read."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(
        getattr(array, "elem_dtype", "float32"))
    out.shape = getattr(array, "elem_shape", None)
    helper.append_op("read_from_array", inputs={"Array": array, "I": i},
                     outputs={"Out": out})
    return out


def array_length(array):
    """reference: layers/control_flow.py array_length."""
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    out.shape = (1,)
    helper.append_op("lod_array_length", inputs={"Array": array},
                     outputs={"Out": out})
    return out


def array_to_tensor(array, name=None):
    """Stack the array into a [capacity, ...] tensor + its write count
    (reference: layers/control_flow.py array_to_lod_tensor /
    operators/array_to_lod_tensor_op.cc — LoD re-assembly becomes simple
    stacking under padded+Length)."""
    helper = LayerHelper("array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(
        getattr(array, "elem_dtype", "float32"))
    elem = getattr(array, "elem_shape", None)
    if elem is not None:
        out.shape = (-1,) + tuple(elem)
    idx = helper.create_variable_for_type_inference("int64")
    idx.shape = (1,)
    helper.append_op("array_to_tensor", inputs={"Array": array},
                     outputs={"Out": out, "OutIndex": idx})
    return out, idx


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id, level=0,
                is_accumulated=False, name=None, return_parent_idx=True):
    """One beam-search step (reference: layers/nn.py beam_search,
    operators/beam_search_op.cc).

    pre_ids/pre_scores: [B, K]; scores: [B, K, V] per-step log-probs
    (``ids`` is accepted for Fluid signature parity and must be None — the
    TPU-native op expands all K·V candidates itself).
    Returns (selected_ids, selected_scores, parent_idx).
    """
    if ids is not None:
        raise ValueError(
            "TPU beam_search consumes full [B, K, V] log-probs via `scores`; "
            "pass ids=None (pre-topk candidate lists are a GPU/LoD-ism)")
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference(pre_ids.dtype)
    sel_scores = helper.create_variable_for_type_inference(pre_scores.dtype)
    parent = helper.create_variable_for_type_inference("int64")
    if pre_ids.shape is not None:
        sel_ids.shape = pre_ids.shape
        sel_scores.shape = pre_ids.shape
        parent.shape = pre_ids.shape
    helper.append_op(
        "beam_search",
        inputs={"PreIds": pre_ids, "PreScores": pre_scores, "Scores": scores},
        outputs={"SelectedIds": sel_ids, "SelectedScores": sel_scores,
                 "ParentIdx": parent},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level), "is_accumulated": bool(is_accumulated)})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size=None, end_id=0, name=None,
                       parents=None):
    """Backtrack a decode run into final sequences (reference: layers/nn.py
    beam_search_decode, operators/beam_search_decode_op.cc).

    ids/parents: TensorArrays written once per step with [B, K] selected ids
    and parent indices; scores: final accumulated [B, K] scores.
    Returns (sentence_ids [B, K, T], sentence_scores [B, K]).
    """
    if parents is None:
        raise ValueError("TPU beam_search_decode needs the parents array "
                         "(Fluid encodes parents in LoD; here they are data)")
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference("int64")
    sent_scores = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        "beam_search_decode",
        inputs={"Ids": ids, "Parents": parents, "Scores": scores},
        outputs={"SentenceIds": sent_ids, "SentenceScores": sent_scores},
        attrs={"end_id": int(end_id)})
    return sent_ids, sent_scores
