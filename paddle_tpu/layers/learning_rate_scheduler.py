"""LR schedules as graph ops (reference: layers/learning_rate_scheduler.py).

Each scheduler builds a tiny op subgraph reading the auto-incremented global
step counter, exactly Fluid's design — the schedule is part of the program,
so it compiles into the jitted step and checkpoints with the counter.
"""

from __future__ import annotations

import math

from . import nn, tensor
from .layer_helper import LayerHelper

__all__ = [
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def _decay_step_counter(begin=0):
    counter = nn.autoincreased_step_counter(counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1)
    return tensor.cast(counter, "float32")


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = lr0 * d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""
    step = _decay_step_counter(begin=1)
    a = nn.pow(step, -0.5)
    b = tensor.scale(step, scale=float(warmup_steps) ** -1.5)
    lr = nn.elementwise_min(a, b)
    return tensor.scale(lr, scale=float(learning_rate) * float(d_model) ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = tensor.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference(div.dtype)
        helper.append_op("floor", inputs={"X": div}, outputs={"Out": out})
        div = out
    return tensor.scale(nn.elementwise_pow(
        tensor.fill_constant([1], "float32", decay_rate), div), scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = tensor.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference(div.dtype)
        helper.append_op("floor", inputs={"X": div}, outputs={"Out": out})
        div = out
    helper = LayerHelper("exp")
    e = helper.create_variable_for_type_inference(div.dtype)
    helper.append_op("exp", inputs={"X": tensor.scale(div, scale=-float(decay_rate))},
                     outputs={"Out": e})
    return tensor.scale(e, scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = tensor.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference(div.dtype)
        helper.append_op("floor", inputs={"X": div}, outputs={"Out": out})
        div = out
    denom = tensor.scale(div, scale=float(decay_rate), bias=1.0)
    one = tensor.fill_constant([1], "float32", float(learning_rate))
    return nn.elementwise_div(one, denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4, power=1.0, cycle=False):
    step = _decay_step_counter()
    ds = tensor.fill_constant([1], "float32", float(decay_steps))
    capped = nn.elementwise_min(step, ds)
    frac = nn.elementwise_div(capped, ds)
    one_minus = tensor.scale(frac, scale=-1.0, bias=1.0)
    powd = nn.pow(one_minus, factor=float(power))
    return tensor.scale(powd, scale=float(learning_rate) - float(end_learning_rate),
                        bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    """lr = values[i] for step in (boundaries[i-1], boundaries[i]]."""
    assert len(values) == len(boundaries) + 1
    step = _decay_step_counter()
    lr = tensor.fill_constant([1], "float32", float(values[0]))
    helper = LayerHelper("piecewise_decay")
    for b, v in zip(boundaries, values[1:]):
        # lr = step > b ? v : lr  — via where op
        cond = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            "greater_than",
            inputs={"X": step, "Y": tensor.fill_constant([1], "float32", float(b))},
            outputs={"Out": cond},
        )
        lr = nn.where(cond, tensor.fill_constant([1], "float32", float(v)), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr = 0.5 * lr0 * (1 + cos(pi * epoch / epochs))."""
    step = _decay_step_counter()
    epoch = tensor.scale(step, scale=1.0 / float(step_each_epoch))
    helper = LayerHelper("floor")
    epoch_f = helper.create_variable_for_type_inference("float32")
    helper.append_op("floor", inputs={"X": epoch}, outputs={"Out": epoch_f})
    helper2 = LayerHelper("cos")
    cosv = helper2.create_variable_for_type_inference("float32")
    helper2.append_op("cos", inputs={"X": tensor.scale(epoch_f, scale=math.pi / float(epochs))},
                      outputs={"Out": cosv})
    return tensor.scale(cosv, scale=0.5 * float(learning_rate), bias=0.5 * float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp start_lr→end_lr over warmup_steps, then learning_rate.

    ``learning_rate`` may be a float or a Variable from another scheduler.
    """
    from ..core.framework import Variable

    step = _decay_step_counter()
    ws = tensor.fill_constant([1], "float32", float(warmup_steps))
    frac = nn.elementwise_div(nn.elementwise_min(step, ws), ws)
    warm = tensor.scale(frac, scale=float(end_lr) - float(start_lr), bias=float(start_lr))
    if not isinstance(learning_rate, Variable):
        learning_rate = tensor.fill_constant([1], "float32", float(learning_rate))
    helper = LayerHelper("warmup_switch")
    cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("less_than", inputs={"X": step, "Y": ws}, outputs={"Out": cond})
    return nn.where(cond, warm, learning_rate)


def append_LARS(params_grads, learning_rate, weight_decay):
    """LARS layer-wise lr scaling (reference:
    layers/learning_rate_scheduler.py append_LARS):
    lr_i = lr · ||p|| / (||g|| + weight_decay·||p||) per parameter.
    Returns the list of per-parameter decayed learning rates. (For the
    optimizer-integrated variant see optimizer.LarsMomentumOptimizer.)"""
    from . import nn, tensor

    decayed = []
    for param, grad in params_grads:
        p_norm = nn.sqrt(nn.reduce_sum(nn.square(param)))
        g_norm = nn.sqrt(nn.reduce_sum(nn.square(grad)))
        # reference _balanced_weight: wd == 1.0 → ||g|| + ||p||, else
        # ||g|| + wd·||p||
        ratio = nn.elementwise_add(
            g_norm, p_norm if weight_decay == 1.0
            else tensor.scale(p_norm, scale=float(weight_decay)))
        local = nn.elementwise_div(p_norm, ratio)
        decayed.append(nn.elementwise_mul(local, learning_rate)
                       if hasattr(learning_rate, "name")
                       else tensor.scale(local, scale=float(learning_rate)))
    return decayed


__all__.append("append_LARS")
