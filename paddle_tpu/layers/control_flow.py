"""Control-flow layers (reference: python/paddle/fluid/layers/control_flow.py —
While :504, StaticRNN :278, less_than/equal helpers, increment).

Sub-blocks hold the body ops, exactly Fluid's representation; execution lowers
to lax.while_loop / lax.cond / lax.scan (see ops/control_flow_ops.py).
DynamicRNN's LoD-bucketed batching has no XLA analog — use StaticRNN over
padded [T, B, ...] tensors with masks (see sequence ops), the idiomatic
replacement.
"""

from __future__ import annotations

import contextlib
from typing import Callable, List, Optional, Sequence, Tuple

from ..core import unique_name
from ..core.framework import Variable, default_main_program
from .layer_helper import LayerHelper
from . import tensor as tl

__all__ = ["While", "cond", "StaticRNN", "DynamicRNN", "less_than",
           "less_equal", "greater_than", "greater_equal", "equal", "not_equal",
           "logical_and", "logical_or", "logical_not", "increment", "is_empty"]


def _cmp_layer(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op(op_type, inputs={"X": x, "Y": y}, outputs={"Out": cond})
    return cond


def less_than(x, y, cond=None, force_cpu=None):
    return _cmp_layer("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp_layer("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp_layer("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp_layer("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp_layer("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp_layer("not_equal", x, y, cond)


def logical_and(x, y, out=None):
    return _cmp_layer("logical_and", x, y, out)


def logical_or(x, y, out=None):
    return _cmp_layer("logical_or", x, y, out)


def logical_not(x, out=None):
    helper = LayerHelper("logical_not")
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    helper.append_op("logical_not", inputs={"X": x}, outputs={"Out": out})
    return out


increment = tl.increment


class While:
    """Fluid-style while loop (reference: control_flow.py:504).

        i = fluid.layers.fill_constant([1], 'int64', 0)
        n = fluid.layers.fill_constant([1], 'int64', 10)
        s = fluid.layers.fill_constant([1], 'float32', 0.0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            layers.assign(s + 1.0, s)
            layers.increment(i, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)  # update condition

    Loop-carried vars are detected automatically: any pre-existing var
    re-assigned inside the body (Fluid's scope-mutation contract, made
    functional as the lax.while_loop carry).
    """

    def __init__(self, cond: Variable, is_test: bool = False, name: Optional[str] = None):
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool Variable")
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    @contextlib.contextmanager
    def block(self):
        program = default_main_program()
        parent_block = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        # carry set: vars written in the body that already existed outside
        carry = []
        for op in sub.ops:
            for name in op.output_arg_names:
                if name not in sub.vars and name not in carry:
                    carry.append(name)
        if self.cond_var.name not in carry:
            raise ValueError(
                "While body never updates the condition %r — infinite loop"
                % self.cond_var.name)
        parent_block.append_op(
            "while",
            inputs={"Condition": self.cond_var},
            outputs={"Out": carry},
            attrs={"sub_block": sub.idx, "carry_vars": carry},
        )


def cond(pred: Variable, true_fn: Callable, false_fn: Optional[Callable] = None):
    """Functional two-branch conditional lowering to lax.cond.

    Returns the true_fn/false_fn result (a Variable or tuple of Variables;
    both branches must return matching shapes/dtypes — XLA requirement).
    """
    program = default_main_program()
    parent_block = program.current_block()
    helper = LayerHelper("cond")

    def build(fn):
        blk = program._create_block()
        try:
            res = fn()
        finally:
            program._rollback()
        if res is None:
            res = ()
        res_t = res if isinstance(res, (list, tuple)) else (res,)
        return blk, tuple(res_t), not isinstance(res, (list, tuple))

    true_blk, true_outs, single = build(true_fn)
    if false_fn is None:
        raise ValueError("cond requires false_fn returning the same structure "
                         "(XLA needs both branches)")
    false_blk, false_outs, _ = build(false_fn)
    if len(true_outs) != len(false_outs):
        raise ValueError("cond branches return different arities: %d vs %d"
                         % (len(true_outs), len(false_outs)))

    out_vars = []
    for tv, fv in zip(true_outs, false_outs):
        out = parent_block.create_var(
            name=unique_name.generate("cond_out"), dtype=tv.dtype, shape=tv.shape)
        # bind each branch's result to the shared output name
        true_blk.append_op("assign", inputs={"X": tv}, outputs={"Out": out})
        false_blk.append_op("assign", inputs={"X": fv}, outputs={"Out": out})
        out_vars.append(out)

    parent_block.append_op(
        "conditional_block",
        inputs={"Cond": pred},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={"true_block": true_blk.idx, "false_block": false_blk.idx},
    )
    if single and out_vars:
        return out_vars[0]
    return tuple(out_vars)


class DynamicRNN:
    """Variable-length RNN over padded batch-major inputs
    (reference: control_flow.py:1394).

    Fluid's DynamicRNN sorts sequences by length (lod_rank_table), converts
    LoD tensors to step arrays (lod_tensor_to_array) and SHRINKS the live
    batch as shorter sequences finish (shrink_rnn_memory). That dynamic
    re-batching is hostile to XLA's static shapes, so the TPU-native redesign
    scans the full padded batch and masks instead: carried memories freeze and
    step outputs are zeroed for rows where t ≥ length — identical results,
    constant shapes, one lax.scan (see ops/rnn_ops.py dynamic_rnn_op).

        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sentence, length=sent_len)  # [B,T,D] → [B,D]
            prev = drnn.memory(shape=[H], value=0.0)           # [B,H] zeros
            h = fluid.layers.fc([word, prev], size=H, act='tanh')
            drnn.update_memory(prev, h)
            drnn.output(h)
        out = drnn()                                           # [B,T,H]
    """

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._sub_block = None
        self._parent_block = None
        self._step_inputs: List[Tuple[str, str]] = []
        self._static_inputs: List[Tuple[str, str]] = []
        self._memories: List[list] = []
        self._mem_inits_deferred: List[Tuple[str, list, float, str]] = []
        self._step_outputs: List[Variable] = []
        self._outputs: List[Variable] = []
        self._final_states: List[Variable] = []
        self._length: Optional[Variable] = None
        self._max_len = None
        self._in_block = False

    @contextlib.contextmanager
    def block(self):
        program = default_main_program()
        self._parent_block = program.current_block()
        self._sub_block = program._create_block()
        self._in_block = True
        try:
            yield
        except BaseException:
            self._in_block = False
            program._rollback()
            raise  # don't mask the user's error with a _complete() one
        else:
            self._in_block = False
            program._rollback()
            self._complete()

    def step_input(self, x: Variable, length: Optional[Variable] = None) -> Variable:
        """x: padded [B, T, ...]; returns the per-step view [B, ...]."""
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("DynamicRNN.step_input needs a [B, T, ...] Variable")
        if self._max_len is None:
            self._max_len = x.shape[1]
        if length is not None:
            self._length = length
        inner = self._sub_block.create_var(
            name=unique_name.generate("drnn_step_in"),
            dtype=x.dtype, shape=(x.shape[0],) + tuple(x.shape[2:]))
        self._step_inputs.append((x.name, inner.name))
        return inner

    def static_input(self, x: Variable) -> Variable:
        """Non-sequence input visible whole at every step (reference:
        DynamicRNN.static_input — there it is rank-sorted; here it is simply
        closed over, batch order never changes)."""
        inner = self._sub_block.create_var(
            name=unique_name.generate("drnn_static_in"),
            dtype=x.dtype, shape=x.shape)
        self._static_inputs.append((x.name, inner.name))
        return inner

    def memory(self, init: Optional[Variable] = None, shape=None, value=0.0,
               need_reorder: bool = False, dtype="float32") -> Variable:
        if not self._in_block:
            raise ValueError("DynamicRNN.memory must be called inside block()")
        if init is not None:
            prev = self._sub_block.create_var(
                name=unique_name.generate("drnn_mem_prev"),
                dtype=init.dtype, shape=init.shape)
            self._memories.append([prev.name, None, init.name])
            return prev
        if shape is None:
            raise ValueError("memory needs init= or shape=")
        prev = self._sub_block.create_var(
            name=unique_name.generate("drnn_mem_prev"), dtype=dtype,
            shape=tuple([-1] + list(shape)))
        self._memories.append([prev.name, None, None])
        self._mem_inits_deferred.append((prev.name, list(shape), value, dtype))
        return prev

    def update_memory(self, prev: Variable, new: Variable):
        for m in self._memories:
            if m[0] == prev.name:
                m[1] = new.name
                return
        raise ValueError("update_memory: %r is not a memory of this RNN" % prev.name)

    def output(self, *outputs: Variable):
        self._step_outputs.extend(outputs)

    step_output = output

    def _complete(self):
        if not self._step_inputs:
            raise ValueError("DynamicRNN needs at least one step_input")
        for m in self._memories:
            if m[1] is None:
                raise ValueError("memory %r was never update_memory'd" % m[0])
        # deferred zero-valued memories: batch-sized like the first step input
        first_outer = self._parent_block._find_var_recursive(self._step_inputs[0][0])
        for prev_name, shape, value, dtype in self._mem_inits_deferred:
            init = tl.fill_constant_batch_size_like(
                first_outer, [-1] + shape, dtype, value, input_dim_idx=0,
                output_dim_idx=0)
            for m in self._memories:
                if m[0] == prev_name:
                    m[2] = init.name
        outer_outs = []
        for o in self._step_outputs:
            shape = (-1, self._max_len) + tuple((o.shape or ())[1:])
            outer = self._parent_block.create_var(
                name=unique_name.generate("drnn_out"), dtype=o.dtype, shape=shape)
            outer_outs.append(outer)
        finals = []
        for prev_name, _, init_name in self._memories:
            init_var = self._parent_block._find_var_recursive(init_name)
            fs = self._parent_block.create_var(
                name=unique_name.generate("drnn_final"), dtype=init_var.dtype,
                shape=init_var.shape)
            finals.append(fs)
        self._outputs = outer_outs
        self._final_states = finals
        inputs = {
            "X": [outer for outer, _ in self._step_inputs],
            "Boot": [m[2] for m in self._memories],
            "Static": [outer for outer, _ in self._static_inputs],
        }
        if self._length is not None:
            inputs["Length"] = self._length
        self._parent_block.append_op(
            "dynamic_rnn",
            inputs=inputs,
            outputs={"Out": outer_outs, "FinalStates": finals},
            attrs={
                "sub_block": self._sub_block.idx,
                "step_inputs": [list(p) for p in self._step_inputs],
                "static_inputs": [list(p) for p in self._static_inputs],
                "memories": [list(m) for m in self._memories],
                "step_outputs": [o.name for o in self._step_outputs],
            },
        )

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return tuple(self._outputs)

    @property
    def final_states(self):
        return self._final_states


class StaticRNN:
    """Static (unrolled-shape) RNN over time-major inputs
    (reference: control_flow.py:278), lowering to lax.scan — differentiable.

        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tm)           # x_tm: [T, B, D]
            prev = rnn.memory(init=h0)         # h0:   [B, H]
            h = fluid.layers.fc([w, prev], size=H, act='tanh')
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        outs = rnn()                           # [T, B, H]
    """

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._sub_block = None
        self._parent_block = None
        self._step_inputs: List[Tuple[str, str]] = []
        self._memories: List[Tuple[str, str, str]] = []
        self._mem_updates = {}
        self._step_outputs: List[Variable] = []
        self._outputs: List[Variable] = []
        self._final_states: List[Variable] = []
        self._seq_len = None

    @contextlib.contextmanager
    def step(self):
        program = default_main_program()
        self._parent_block = program.current_block()
        self._sub_block = program._create_block()
        try:
            yield
        except BaseException:
            program._rollback()
            raise  # don't mask the user's error with a _complete() one
        else:
            program._rollback()
            self._complete()

    def step_input(self, x: Variable) -> Variable:
        if x.shape is None or len(x.shape) < 1:
            raise ValueError("step_input needs a [T, ...] shaped Variable")
        if self._seq_len is None:
            self._seq_len = x.shape[0]
        inner = self._sub_block.create_var(
            name=unique_name.generate("rnn_step_in"),
            dtype=x.dtype, shape=x.shape[1:])
        self._step_inputs.append((x.name, inner.name))
        return inner

    def memory(self, init: Optional[Variable] = None, shape=None, value=0.0,
               batch_ref: Optional[Variable] = None, dtype="float32") -> Variable:
        if init is None:
            if batch_ref is None or shape is None:
                raise ValueError("memory needs init=Variable, or shape+batch_ref")
            init = tl.fill_constant_batch_size_like(
                batch_ref, [d if d != -1 else 1 for d in shape], dtype, value)
        prev = self._sub_block.create_var(
            name=unique_name.generate("rnn_mem_prev"),
            dtype=init.dtype, shape=init.shape)
        self._memories.append([prev.name, None, init.name])
        return prev

    def update_memory(self, prev: Variable, new: Variable):
        for m in self._memories:
            if m[0] == prev.name:
                m[1] = new.name
                return
        raise ValueError("update_memory: %r is not a memory of this RNN" % prev.name)

    def step_output(self, o: Variable):
        self._step_outputs.append(o)

    output = step_output

    def _complete(self):
        for m in self._memories:
            if m[1] is None:
                raise ValueError("memory %r was never update_memory'd" % m[0])
        outer_outs = []
        for o in self._step_outputs:
            shape = (self._seq_len,) + tuple(o.shape or ())
            outer = self._parent_block.create_var(
                name=unique_name.generate("rnn_out"), dtype=o.dtype, shape=shape)
            outer_outs.append(outer)
        finals = []
        for prev_name, _, init_name in self._memories:
            init_var = self._parent_block.var(init_name)
            fs = self._parent_block.create_var(
                name=unique_name.generate("rnn_final"), dtype=init_var.dtype,
                shape=init_var.shape)
            finals.append(fs)
        self._outputs = outer_outs
        self._final_states = finals
        self._parent_block.append_op(
            "recurrent",
            inputs={
                "X": [outer for outer, _ in self._step_inputs],
                "Boot": [init for _, _, init in self._memories],
            },
            outputs={"Out": outer_outs, "FinalStates": finals},
            attrs={
                "sub_block": self._sub_block.idx,
                "step_inputs": [list(p) for p in self._step_inputs],
                "memories": [list(m) for m in self._memories],
                "step_outputs": [o.name for o in self._step_outputs],
            },
        )

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return tuple(self._outputs)

    @property
    def final_states(self):
        return self._final_states


class IfElse:
    """Per-row two-branch routing (reference: control_flow.py:1264).

    The reference physically splits rows by the bool mask
    (split_lod_tensor), runs each branch on its subset, and merges
    (merge_lod_tensor) — data-dependent shapes. The TPU-native redesign
    computes BOTH branches over the full batch and blends rows with the
    mask: identical row-wise results, fully static shapes, and XLA prunes
    whatever a branch doesn't contribute to. Same API:

        ie = fluid.layers.IfElse(cond)         # cond: [N, 1] bool
        with ie.true_block():
            ie.output(f(ie.input(x)))
        with ie.false_block():
            ie.output(g(ie.input(x)))
        out, = ie()
    """

    OUT, IN_TRUE, IN_FALSE = 0, 1, 2

    def __init__(self, cond: Variable, name: Optional[str] = None):
        if not isinstance(cond, Variable):
            raise TypeError("cond must be a Variable")
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.status = IfElse.OUT
        self.output_table = ([], [])  # (false_outs, true_outs)

    def input(self, x: Variable) -> Variable:
        if self.status == IfElse.OUT:
            raise ValueError("IfElse.input() must be called inside a block")
        return x  # both branches see the full rows; the mask blends later

    @contextlib.contextmanager
    def _block(self, is_true: bool):
        if self.status != IfElse.OUT:
            raise ValueError("cannot nest IfElse blocks")
        self.status = IfElse.IN_TRUE if is_true else IfElse.IN_FALSE
        try:
            yield
        finally:
            self.status = IfElse.OUT

    def true_block(self):
        return self._block(True)

    def false_block(self):
        return self._block(False)

    def output(self, *outs):
        if self.status == IfElse.OUT:
            raise ValueError("output() can only be invoked inside a block")
        table = self.output_table[1 if self.status == IfElse.IN_TRUE else 0]
        table.extend(outs)

    def __call__(self):
        if self.status != IfElse.OUT:
            raise ValueError("IfElse() must be called outside the blocks")
        false_outs, true_outs = self.output_table
        if not false_outs and not true_outs:
            raise ValueError("invoke true_block/false_block before __call__")
        if not false_outs or not true_outs:
            return list(true_outs or false_outs)
        if len(false_outs) != len(true_outs):
            raise ValueError("branches produced different output counts")
        from . import tensor as tensor_layers
        from .nn import elementwise_add, elementwise_mul

        res = []
        for fv, tv in zip(false_outs, true_outs):
            mask = tensor_layers.cast(self.cond, tv.dtype)  # [N, 1]
            keep = tensor_layers.scale(mask, scale=-1.0, bias=1.0)
            res.append(elementwise_add(elementwise_mul(tv, mask),
                                       elementwise_mul(fv, keep)))
        return res


class Switch:
    """First-matching-case execution (reference: control_flow.py Switch —
    the LR-schedule workhorse). Each case body is captured into a sub-block
    and executed under ``conditional_block`` with an effective condition
    ``case_cond AND NOT any_earlier_match``; vars it writes carry out, the
    false branch keeps their previous values.

        with fluid.layers.Switch() as switch:
            with switch.case(step < warmup):
                fluid.layers.assign(lr_warm, lr)
            with switch.default():
                fluid.layers.assign(lr_base, lr)
    """

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("switch", name=name)
        self._inside = False
        self._matched: Optional[Variable] = None  # running "already taken"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @contextlib.contextmanager
    def _case(self, condition: Optional[Variable]):
        if self._inside:
            raise ValueError("cannot nest Switch cases")
        self._inside = True
        program = default_main_program()
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
            self._inside = False
        written = []
        for op in sub.ops:
            for n in op.output_arg_names:
                if n not in sub.vars and n not in written:
                    written.append(n)
        from . import tensor as tensor_layers

        if self._matched is None:
            self._matched = tensor_layers.fill_constant([1], "bool", False)
        if condition is None:  # default: runs iff nothing matched yet
            eff = logical_not(self._matched)
            new_matched = None
        else:
            eff = logical_and(condition, logical_not(self._matched))
            new_matched = logical_or(self._matched, condition)
        # identity false-branch: carry vars keep their previous values
        false_blk = program._create_block()
        program._rollback()
        for n in written:
            false_blk.append_op("assign", inputs={"X": n}, outputs={"Out": n})
        parent.append_op(
            "conditional_block",
            inputs={"Cond": eff},
            outputs={"Out": written},
            attrs={"true_block": sub.idx, "false_block": false_blk.idx},
        )
        if new_matched is not None:
            self._matched = new_matched

    def case(self, condition: Variable):
        return self._case(condition)

    def default(self):
        return self._case(None)


__all__ += ["IfElse", "Switch"]


def is_empty(x, cond=None):
    """True iff x has zero elements (reference: control_flow.py is_empty →
    operators/is_empty_op.cc)."""
    helper = LayerHelper("is_empty")
    out = cond if cond is not None else helper.create_variable_for_type_inference("bool")
    helper.append_op("is_empty", inputs={"X": x}, outputs={"Out": out})
    return out
