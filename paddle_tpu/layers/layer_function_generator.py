"""Auto-generated layer wrappers.

Reference: ``python/paddle/fluid/layers/layer_function_generator.py`` +
``layers/ops.py`` — Fluid code-generates ``fluid.layers.*`` functions from
the C++ OpProtos. The TPU-native registry has no protos (one pure-JAX impl
per op, ``core/registry.py``), so the slot mapping each wrapper needs is
declared here in ``_SPECS`` and ``generate_layer_fn`` builds the function:
reference-matching signature (visible to ``tools/print_signatures.py`` via
``__signature__``), LayerHelper output-var creation, one ``append_op``.

Every *registered op* reachable from the reference's public layer surface
must have a wrapper — ``tests/test_layer_surface.py`` enforces the sweep.
"""

from __future__ import annotations

import inspect

from .layer_helper import LayerHelper

__all__ = [
    "generate_layer_fn",
    "bpr_loss",
    "rank_loss",
    "margin_rank_loss",
    "teacher_student_sigmoid_loss",
    "similarity_focus",
    "add_position_encoding",
    "pad_constant_like",
    "random_crop",
    "logical_xor",
    "affine_channel",
    "lod_reset",
    "sampling_id",
    "crop",
    "affine_grid",
    "lod_reset",
]

_REQ = inspect.Parameter.empty  # sentinel: parameter has no default


# Each row: (python param, kind, op slot/attr name[, default]).
# kind: "in" required input, "in_opt" optional input, "attr" attribute.
_SPECS = {
    "bpr_loss": dict(
        params=[("input", "in", "X"), ("label", "in", "Label")],
        out="Y", name_arg=True,
        doc="Bayesian Personalized Ranking loss (operators/bpr_loss_op.cc)."),
    "rank_loss": dict(
        params=[("label", "in", "Label"), ("left", "in", "Left"),
                ("right", "in", "Right")],
        name_arg=True,
        doc="RankNet pairwise loss (operators/rank_loss_op.cc)."),
    "margin_rank_loss": dict(
        params=[("label", "in", "Label"), ("left", "in", "X1"),
                ("right", "in", "X2"), ("margin", "attr", "margin", 0.1)],
        name_arg=True,
        doc="Margin ranking loss (operators/margin_rank_loss_op.cc)."),
    "teacher_student_sigmoid_loss": dict(
        params=[("input", "in", "X"), ("label", "in", "Label"),
                ("soft_max_up_bound", "attr", "soft_max_up_bound", 15.0),
                ("soft_max_lower_bound", "attr", "soft_max_lower_bound", -15.0)],
        out="Y",
        doc="CTR distillation loss (operators/teacher_student_sigmoid_loss_op.cc)."),
    "similarity_focus": dict(
        params=[("input", "in", "X"), ("axis", "attr", "axis"),
                ("indexes", "attr", "indexes")],
        name_arg=True,
        doc="Similarity-focus mask (operators/similarity_focus_op.cc)."),
    "add_position_encoding": dict(
        params=[("input", "in", "X"), ("alpha", "attr", "alpha"),
                ("beta", "attr", "beta")],
        name_arg=True,
        doc="Sinusoidal position encoding mix-in "
            "(operators/add_position_encoding_op.cc)."),
    "pad_constant_like": dict(
        params=[("x", "in", "X"), ("y", "in", "Y"),
                ("pad_value", "attr", "pad_value", 0.0)],
        name_arg=True,
        doc="Pad Y to X's shape with a constant (operators/pad_constant_like_op.cc)."),
    "random_crop": dict(
        params=[("x", "in", "X"), ("shape", "attr", "shape"),
                ("seed", "attr", "seed", 0)],
        doc="Random spatial crop to `shape` (operators/random_crop_op.cc)."),
    "logical_xor": dict(
        params=[("x", "in", "X"), ("y", "in", "Y")],
        dtype="bool", name_arg=True, allow_out=True,
        doc="Elementwise logical xor (operators/controlflow/logical_op.cc)."),
    "affine_channel": dict(
        params=[("x", "in", "X"), ("scale", "in_opt", "Scale"),
                ("bias", "in_opt", "Bias"),
                ("data_layout", "attr", "data_layout", "NCHW")],
        name_arg=True,
        doc="Per-channel affine transform (operators/affine_channel_op.cc)."),
    "sampling_id": dict(
        params=[("x", "in", "X"), ("min", "attr", "min", 0.0),
                ("max", "attr", "max", 1.0), ("seed", "attr", "seed", 0),
                ("dtype", "py", None, "float32")],
        dtype="int64",
        doc="Sample one column index per probability row "
            "(operators/sampling_id_op.cc)."),
}


def generate_layer_fn(name: str, spec: dict):
    """Build a ``fluid.layers``-style wrapper for a registered op from a slot
    spec (the TPU-native analog of the reference's OpProto template codegen)."""
    op_type = spec.get("op", name)
    out_slot = spec.get("out", "Out")
    out_dtype = spec.get("dtype")
    extra_outs = spec.get("extra_outs", ())

    sig_params = []
    for row in spec["params"]:
        pname, kind = row[0], row[1]
        default = row[3] if len(row) > 3 else (
            None if kind == "in_opt" else _REQ)
        sig_params.append(inspect.Parameter(
            pname, inspect.Parameter.POSITIONAL_OR_KEYWORD, default=default))
    if spec.get("allow_out"):
        sig_params.append(inspect.Parameter(
            "out", inspect.Parameter.POSITIONAL_OR_KEYWORD, default=None))
    if spec.get("name_arg"):
        sig_params.append(inspect.Parameter(
            "name", inspect.Parameter.POSITIONAL_OR_KEYWORD, default=None))
    sig = inspect.Signature(sig_params)

    def layer(*args, **kwargs):
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
        vals = bound.arguments
        inputs, attrs = {}, {}
        for row in spec["params"]:
            pname, kind, slot = row[0], row[1], row[2]
            v = vals[pname]
            if kind in ("in", "in_opt"):
                if v is None:
                    if kind == "in":
                        raise ValueError("%s(): %r is required" % (name, pname))
                    continue
                inputs[slot] = v
            elif kind == "attr" and v is not None:
                attrs[slot] = v
        helper = LayerHelper(op_type, name=vals.get("name"))
        ref_in = next(iter(inputs.values()))
        out = vals.get("out") or helper.create_variable_for_type_inference(
            out_dtype or ref_in.dtype)
        outputs = {out_slot: out}
        for slot, dt in extra_outs:
            outputs[slot] = helper.create_variable_for_type_inference(
                dt or ref_in.dtype, stop_gradient=True)
        helper.append_op(op_type, inputs=inputs, outputs=outputs, attrs=attrs)
        if name == "sampling_id" and vals.get("dtype") not in (None, "int64"):
            from .tensor import cast

            return cast(out, vals["dtype"])
        return out

    layer.__name__ = layer.__qualname__ = name
    layer.__signature__ = sig
    layer.__doc__ = "%s\n\nAuto-generated wrapper for the %r op (reference: " \
        "layers auto-generation via layer_function_generator.py)." % (
            spec.get("doc", ""), op_type)
    return layer


for _n, _s in _SPECS.items():
    globals()[_n] = generate_layer_fn(_n, _s)


# -- wrappers with input-vs-attr routing (can't be table-generated) -----------


def lod_reset(x, y=None, target_lod=None):
    """Replace the sequence-length descriptor (reference: nn.py lod_reset,
    operators/lod_reset_op.cc). Under the padded+Length convention the data
    passes through and the new per-row lengths come back explicitly:
    returns ``(out, new_length)`` — downstream sequence layers take the
    length var via their ``length=`` argument."""
    if y is None and target_lod is None:
        raise ValueError("lod_reset(): provide y or target_lod")
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int32",
                                                        stop_gradient=True)
    inputs = {"X": x}
    if y is not None:
        inputs["Y"] = y
    helper.append_op("lod_reset", inputs=inputs,
                     outputs={"Out": out, "OutLength": out_len},
                     attrs={"target_lod": target_lod} if target_lod else {})
    return out, out_len


def crop(x, shape=None, offsets=None, name=None):
    """Crop x to `shape` at `offsets` (reference: nn.py crop, crop_op.cc).

    Static lists only: a Variable shape would be data-dependent under XLA.
    """
    from ..core.framework import Variable

    if isinstance(shape, Variable) or isinstance(offsets, Variable):
        raise TypeError(
            "crop(): Variable shape/offsets are data-dependent shapes, which "
            "XLA cannot compile; pass Python lists")
    if shape is None:
        raise ValueError("crop(): shape is required")
    if offsets is None:
        offsets = [0] * len(shape)
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("crop", inputs={"X": x}, outputs={"Out": out},
                     attrs={"shape": list(shape), "offsets": list(offsets)})
    return out


def affine_grid(theta, out_shape, name=None):
    """Affine sampling grid for STNs (reference: nn.py affine_grid,
    operators/affine_grid_op.cc). ``out_shape`` may be a Variable (wired to
    the OutputShape input) or a static list (attr)."""
    from ..core.framework import Variable

    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": theta}
    attrs = {}
    if isinstance(out_shape, Variable):
        inputs["OutputShape"] = out_shape
    else:
        attrs["output_shape"] = [int(s) for s in out_shape]
    helper.append_op("affine_grid", inputs=inputs, outputs={"Output": out},
                     attrs=attrs)
    return out
