"""Attention layers.

Fluid composes attention from primitives in model scripts (reference:
tests/unittests/dist_transformer.py multi_head_attention); here it is a
first-class layer backed by the fused Pallas flash-attention op on TPU.
"""

from __future__ import annotations

from typing import Optional

from .layer_helper import LayerHelper
from . import nn, tensor

__all__ = ["scaled_dot_product_attention", "multi_head_attention"]


def _segment_bias(seg_q, seg_kv):
    """[b, sq]/[b, sk] segment ids -> additive [b, 1, sq, sk] bias: 0 where
    ids match, -1e9 where they don't (the primitive-composition equivalent
    of the fused op's segment where-mask)."""
    from . import control_flow as cf

    sq = nn.unsqueeze(nn.unsqueeze(seg_q, axes=[1]), axes=[3])    # [b,1,sq,1]
    skv = nn.unsqueeze(nn.unsqueeze(seg_kv, axes=[1]), axes=[2])  # [b,1,1,sk]
    mask = tensor.cast(cf.equal(sq, skv), "float32")
    return tensor.scale(mask, scale=1e9, bias=-1e9)


def scaled_dot_product_attention(q, k, v, bias=None, causal=False, sm_scale=1.0,
                                 dropout_rate=0.0, is_test=False, name=None,
                                 segment_ids_q=None, segment_ids_kv=None,
                                 unfused=None):
    """q/k/v: [batch, heads, seq, head_dim].

    ``unfused`` (default: ``FLAGS_unfused_attention``) emits the
    reference-style primitive composition — ``matmul(Q, K^T, alpha) ->
    [+bias] -> softmax -> [dropout] -> matmul(probs, V)`` — instead of the
    fused op. The default trace-time optimizer's ``flash_attention_rewrite``
    (``PADDLE_TPU_OPT_LEVEL>=1``) fuses the composition back onto the
    Pallas kernel path at prepare time, so the emitted graph is
    inspectable/portable without giving up the fused kernels. Segment ids
    are lowered to an additive-bias composition (CSE merges identical
    chains across layers); only CAUSAL attention always uses the fused op
    (the primitive pattern cannot express the mask losslessly).
    """
    if unfused is None:
        from ..flags import get_flag

        unfused = get_flag("unfused_attention")
    if unfused and not causal:
        if segment_ids_q is not None:
            # lower segment masking to an additive bias so the whole site is
            # expressible in primitives: 0 where segments match, -1e9 where
            # not (identical post-softmax to the fused where-mask; identical
            # chains across layers are CSE'd by the default optimizer)
            seg_bias = _segment_bias(
                segment_ids_q,
                segment_ids_kv if segment_ids_kv is not None else segment_ids_q)
            bias = seg_bias if bias is None \
                else nn.elementwise_add(bias, seg_bias)
        scores = nn.matmul(q, k, transpose_y=True, alpha=float(sm_scale),
                           name=name and name + "_qk")
        if bias is not None:
            scores = nn.elementwise_add(scores, bias)
        probs = nn.softmax(scores)
        if dropout_rate:
            probs = nn.dropout(probs, dropout_rate, is_test=is_test,
                               dropout_implementation="upscale_in_train")
        return nn.matmul(probs, v, name=name and name + "_pv")
    helper = LayerHelper("sdpa", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": q, "K": k, "V": v}
    if bias is not None:
        inputs["Bias"] = bias
    if segment_ids_q is not None:
        inputs["SegmentIdsQ"] = segment_ids_q
        inputs["SegmentIdsKV"] = segment_ids_kv if segment_ids_kv is not None else segment_ids_q
    helper.append_op(
        "scaled_dot_product_attention",
        inputs=inputs,
        outputs={"Out": out},
        attrs={"causal": causal, "sm_scale": float(sm_scale),
               "dropout_rate": float(dropout_rate), "is_test": is_test},
    )
    return out


def multi_head_attention(
    queries,
    keys,
    values,
    attn_bias,
    d_key: int,
    d_value: int,
    d_model: int,
    n_head: int,
    dropout_rate: float = 0.0,
    causal: bool = False,
    is_test: bool = False,
    param_initializer=None,
    name: Optional[str] = None,
    segment_ids_q=None,
    segment_ids_kv=None,
):
    """reference: dist_transformer.py multi_head_attention — q/k/v projections,
    split heads, fused attention, combine heads, output projection.
    Inputs are [batch, seq, d_model]."""
    self_attn = keys is None and values is None
    keys = queries if keys is None else keys
    values = keys if values is None else values

    def _proj(x, size, nm):
        return nn.fc(x, size=size, num_flatten_dims=2, bias_attr=False,
                     param_attr=param_initializer, name=nm)

    if (self_attn and queries.shape is not None
            and queries.shape[-1] is not None):
        # fused QKV: one [D, 3·D'] matmul instead of three — the input
        # activation is read once, not three times (measured ~2.6GB/step of
        # HBM on the Transformer-base bench), and the bigger matmul tiles
        # the MXU better. The projection is ONE merged parameter, not three
        # concatenated ones: a concat's backward slices the [D, 3·D'] grad
        # matmul before the optimizer, and that slice blocks XLA from
        # vertically fusing each Adam update into the fusion producing the
        # gradient (measured +6 standalone update kernels per encoder layer
        # on BERT-base — benchmarks/diag_adam_fusion.py). Checkpoints from
        # builds that stored q/k/v separately can be migrated by
        # concatenating the three weights along axis 1.
        d_in = int(queries.shape[-1])
        sizes = (d_key * n_head, d_key * n_head, d_value * n_head)
        helper = LayerHelper("fc", param_attr=param_initializer,
                             name=name and name + "_qkv")
        wqkv = helper.create_parameter(param_initializer,
                                       shape=[d_in, sum(sizes)],
                                       dtype=queries.dtype)
        qkv = helper.create_variable_for_type_inference(queries.dtype)
        helper.append_op("mul", inputs={"X": queries, "Y": wqkv},
                         outputs={"Out": qkv},
                         attrs={"x_num_col_dims": 2, "y_num_col_dims": 1})
        q, k, v = nn.split(qkv, list(sizes), dim=2)
    else:
        q = _proj(queries, d_key * n_head, name and name + "_q")
        k = _proj(keys, d_key * n_head, name and name + "_k")
        v = _proj(values, d_value * n_head, name and name + "_v")

    def _split_heads(x, d):
        x = tensor.reshape(x, [0, 0, n_head, d])
        return tensor.transpose(x, [0, 2, 1, 3])

    q = _split_heads(q, d_key)
    k = _split_heads(k, d_key)
    v = _split_heads(v, d_value)

    ctx = scaled_dot_product_attention(
        q, k, v, bias=attn_bias, causal=causal, sm_scale=d_key ** -0.5,
        dropout_rate=dropout_rate, is_test=is_test, name=name,
        segment_ids_q=segment_ids_q, segment_ids_kv=segment_ids_kv,
    )
    ctx = tensor.transpose(ctx, [0, 2, 1, 3])
    ctx = tensor.reshape(ctx, [0, 0, n_head * d_value])
    return _proj(ctx, d_model, name and name + "_out")
