"""LayerHelper: parameter creation + op-append glue.

Reference: ``python/paddle/fluid/layer_helper.py:42`` and
``layer_helper_base.py:252``. Creates Parameters in BOTH the startup program
(with the init op) and the main program (as input), mirroring Fluid's
two-program convention.
"""

from __future__ import annotations

from typing import Optional

from ..core import framework, unique_name
from ..core.framework import Parameter, Variable, default_main_program, default_startup_program
from .. import initializer as init_mod

__all__ = ["LayerHelper", "ParamAttr"]


class ParamAttr:
    """Reference: python/paddle/fluid/param_attr.py."""

    def __init__(
        self,
        name: Optional[str] = None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
        do_model_average: bool = False,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, init_mod.Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else ParamAttr(trainable=False)
        raise TypeError("cannot interpret %r as ParamAttr" % (arg,))


class WeightNormParamAttr(ParamAttr):
    """Weight-normalization reparameterization w = g·v/‖v‖ (reference:
    param_attr.py:178). ``dim`` selects the slice axis whose magnitudes
    ``g`` are learned independently (None → one global magnitude)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 gradient_clip=None, do_model_average=False):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate, regularizer=regularizer,
                         trainable=trainable, gradient_clip=gradient_clip,
                         do_model_average=do_model_average)
        self.dim = dim


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def create_variable_for_type_inference(self, dtype, stop_gradient=False) -> Variable:
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    # Fluid-compatible alias
    create_tmp_variable = create_variable_for_type_inference

    def create_parameter(
        self,
        attr,
        shape,
        dtype,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Parameter:
        attr = ParamAttr.to_attr(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "b" if is_bias else "w"]))
        if isinstance(attr, WeightNormParamAttr):
            return self._create_weight_normalized(
                attr, shape, dtype, default_initializer)
        if default_initializer is None:
            if is_bias:
                default_initializer = init_mod._global_bias_initializer()
            else:
                default_initializer = init_mod._global_weight_initializer()
        initializer = attr.initializer or default_initializer

        startup_block = self.startup_program.global_block
        sp = startup_block.create_parameter(
            name=attr.name,
            shape=shape,
            dtype=dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
        )
        initializer(sp, startup_block)
        main_block = self.main_program.global_block
        param = main_block.create_parameter(
            name=attr.name,
            shape=shape,
            dtype=dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        return param

    def _create_weight_normalized(self, attr, shape, dtype,
                                  default_initializer):
        """Weight normalization (reference: param_attr.py WeightNormParamAttr
        + layer_helper.py __weight_normalize): w = g · v/‖v‖ with direction
        ``v`` and per-slice magnitude ``g`` as the trainable parameters.
        ``g`` is initialized to ‖v‖ in the startup program so training
        starts at w == v, matching the reference."""
        dim = attr.dim
        base = ParamAttr(name=attr.name + ".w_v", initializer=attr.initializer,
                         learning_rate=attr.learning_rate,
                         regularizer=attr.regularizer,
                         trainable=attr.trainable,
                         gradient_clip=attr.gradient_clip)
        v = self.create_parameter(base, shape, dtype,
                                  default_initializer=default_initializer)
        g_shape = [shape[dim]] if dim is not None else [1]
        reduce_axes = ([a for a in range(len(shape)) if a != dim]
                       if dim is not None else list(range(len(shape))))
        bshape = [1] * len(shape)
        if dim is not None:
            bshape[dim] = shape[dim]

        def norm_ops(block, v_var, out_name_hint):
            sq = block.create_var(name=unique_name.generate(out_name_hint + ".sq"),
                                  shape=list(shape), dtype=dtype)
            block.append_op("square", inputs={"X": v_var}, outputs={"Out": sq},
                            attrs={})
            ssum = block.create_var(name=unique_name.generate(out_name_hint + ".ss"),
                                    shape=g_shape, dtype=dtype)
            block.append_op("reduce_sum", inputs={"X": sq},
                            outputs={"Out": ssum},
                            attrs={"dim": reduce_axes, "keep_dim": False,
                                   "reduce_all": dim is None})
            nrm = block.create_var(name=unique_name.generate(out_name_hint + ".n"),
                                   shape=g_shape, dtype=dtype)
            block.append_op("sqrt", inputs={"X": ssum}, outputs={"Out": nrm},
                            attrs={})
            return nrm

        # startup: g := ||v|| (so the initial effective weight equals v)
        startup_block = self.startup_program.global_block
        sg = startup_block.create_parameter(
            name=attr.name + ".w_g", shape=g_shape, dtype=dtype,
            trainable=attr.trainable)
        s_norm = norm_ops(startup_block, startup_block.var(v.name), attr.name)
        startup_block.append_op("assign", inputs={"X": s_norm},
                                outputs={"Out": sg}, attrs={})
        main_block = self.main_program.global_block
        g = main_block.create_parameter(name=attr.name + ".w_g", shape=g_shape,
                                        dtype=dtype, trainable=attr.trainable)
        g.optimize_attr = {"learning_rate": attr.learning_rate}

        # main: w = v * (g / ||v||), broadcast over the kept dim
        m_norm = norm_ops(main_block, v, attr.name + ".m")
        scale = main_block.create_var(
            name=unique_name.generate(attr.name + ".scale"), shape=g_shape,
            dtype=dtype)
        main_block.append_op("elementwise_div", inputs={"X": g, "Y": m_norm},
                             outputs={"Out": scale}, attrs={"axis": -1})
        scale_r = main_block.create_var(
            name=unique_name.generate(attr.name + ".scale_r"),
            shape=bshape, dtype=dtype)
        main_block.append_op("reshape", inputs={"X": scale},
                             outputs={"Out": scale_r},
                             attrs={"shape": bshape})
        w = main_block.create_var(name=unique_name.generate(attr.name),
                                  shape=list(shape), dtype=dtype)
        main_block.append_op("elementwise_mul", inputs={"X": v, "Y": scale_r},
                             outputs={"Out": w}, attrs={"axis": -1})
        return w

    def create_global_variable(self, shape, dtype, name=None, persistable=False, stop_gradient=True):
        return self.main_program.global_block.create_var(
            name=name or unique_name.generate(".".join([self.name, "global"])),
            shape=shape,
            dtype=dtype,
            persistable=persistable,
            stop_gradient=stop_gradient,
        )

    def create_or_get_global_variable(self, shape, dtype, name, persistable=True, initializer=None):
        """Persistent state var (e.g. BN running stats) with startup init."""
        main_block = self.main_program.global_block
        if main_block.has_var(name):
            return main_block.var(name)
        var = main_block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=persistable, stop_gradient=True
        )
        startup_block = self.startup_program.global_block
        sv = startup_block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=persistable, stop_gradient=True
        )
        (initializer or init_mod.Constant(0.0))(sv, startup_block)
        return var

    def input(self, name="input"):
        inputs = self.kwargs.get(name)
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != 1:
                raise ValueError("expected one input for %s" % self.layer_type)
            return inputs[0]
        return inputs

    def input_dtype(self, name="input"):
        return self.input(name).dtype

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        if input_var.shape is None:
            raise ValueError(
                "shape inference failed for %r (produced by op %r) — the layer "
                "geometry is likely invalid (e.g. spatial dims shrank to zero)"
                % (input_var.name, input_var.op.type if input_var.op else None)
            )
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = ParamAttr.to_attr(self.kwargs.get("bias_attr"))
        if not bias_attr.trainable and bias_attr.name is None and self.kwargs.get("bias_attr") is False:
            return input_var
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            "elementwise_add",
            inputs={"X": input_var, "Y": b},
            outputs={"Out": out},
            attrs={"axis": dim_start},
        )
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(act_type, inputs={"X": input_var}, outputs={"Out": out}, attrs=act)
        return out
