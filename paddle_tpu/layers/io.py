"""IO layers: data declaration (reference: python/paddle/fluid/layers/io.py).

``data`` declares a feed variable with a leading batch dim of -1 (dynamic),
matching Fluid (``io.py data, append_batch_size=True``). py_reader /
double_buffer prefetching lives in paddle_tpu/reader.py (host pipeline +
jax.device_put prefetch), since under XLA the graph itself doesn't own IO.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core import unique_name
from ..core.framework import default_main_program, default_startup_program

__all__ = ["data", "py_reader", "create_py_reader_by_data", "read_file", "double_buffer"]


def data(name: str, shape: Sequence[int], dtype="float32", append_batch_size: bool = True,
         lod_level: int = 0, stop_gradient: bool = True):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().global_block
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=stop_gradient,
    )
    var.lod_level = lod_level
    return var


def py_reader(capacity: int, shapes: Sequence[Sequence[int]], dtypes: Sequence,
              lod_levels: Optional[Sequence[int]] = None, name: Optional[str] = None,
              use_double_buffer: bool = True):
    """Async graph input (reference: python/paddle/fluid/layers/io.py:636).

    Creates one data variable per (shape, dtype) and binds a PyReader whose
    queue the Executor drains each step — see reader/py_reader.py for the
    TPU-native design (host thread + device prefetch replaces the C++
    blocking-queue `read` op).

        reader = fluid.layers.py_reader(64, [[-1,784],[-1,1]], ['float32','int64'])
        img, label = fluid.layers.read_file(reader)
        ...
        reader.decorate_paddle_reader(train_reader)
        reader.start()
        try:
            while True: exe.run(fetch_list=[loss])
        except fluid.core.EOFException:
            reader.reset()
    """
    from ..reader.py_reader import PyReader

    base = name or unique_name.generate("py_reader")
    prog = default_main_program()
    block = prog.global_block
    vars_ = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        var = block.create_var(
            name="%s_slot_%d" % (base, i),
            shape=list(shape),
            dtype=dtype,
            is_data=True,
            stop_gradient=True,
        )
        var.lod_level = (lod_levels[i] if lod_levels else 0)
        vars_.append(var)
    reader = PyReader(vars_, capacity, use_double_buffer=use_double_buffer, name=base)
    prog._py_readers.append(reader)
    return reader


def create_py_reader_by_data(capacity: int, feed_list, name: Optional[str] = None,
                             use_double_buffer: bool = True):
    """Bind a PyReader to existing data variables (reference: io.py
    create_py_reader_by_data)."""
    from ..reader.py_reader import PyReader

    prog = default_main_program()
    reader = PyReader(list(feed_list), capacity, use_double_buffer=use_double_buffer,
                      name=name or unique_name.generate("py_reader"))
    prog._py_readers.append(reader)
    return reader


def read_file(reader):
    """The data variables fed by a py_reader (reference: io.py read_file)."""
    vars_ = reader.data_vars
    return vars_[0] if len(vars_) == 1 else list(vars_)


def double_buffer(reader, place=None, name=None):
    """Compat shim: py_reader(use_double_buffer=True) already device-prefetches
    (reader/prefetcher.py); returns the reader unchanged."""
    return reader


def shuffle(reader, buffer_size):
    """reference: layers/io.py shuffle (create_shuffle_reader op). The
    file-reader op stack is replaced by host-side reader decorators
    (SURVEY §2 reader infra): this delegates to
    ``paddle_tpu.reader.shuffle`` for Python readers."""
    if callable(reader):
        from ..reader.decorator import shuffle as _shuffle

        return _shuffle(reader, buffer_size)
    raise TypeError(
        "layers.shuffle expects a Python reader callable; the reference's "
        "graph reader Variables (open_files) are replaced by py_reader + "
        "reader decorators on this backend")


def batch(reader, batch_size):
    """reference: layers/io.py batch (create_batch_reader op); delegates to
    ``paddle_tpu.reader.batch`` for Python readers."""
    if callable(reader):
        from ..reader.decorator import batch as _batch

        return _batch(reader, batch_size)
    raise TypeError(
        "layers.batch expects a Python reader callable; the reference's "
        "graph reader Variables (open_files) are replaced by py_reader + "
        "reader decorators on this backend")


def load(out, file_path, load_as_fp16=None):
    """Load a saved variable into ``out`` (reference: layers/io.py load →
    operators/load_op.cc); reads the .npy written by io.save_vars."""
    from .layer_helper import LayerHelper

    helper = LayerHelper("load")
    helper.append_op("load", inputs={}, outputs={"Out": out},
                     attrs={"file_path": file_path,
                            "load_as_fp16": bool(load_as_fp16 or False)})
    return out


__all__ += ["shuffle", "batch", "load"]
