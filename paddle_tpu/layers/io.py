"""IO layers: data declaration (reference: python/paddle/fluid/layers/io.py).

``data`` declares a feed variable with a leading batch dim of -1 (dynamic),
matching Fluid (``io.py data, append_batch_size=True``). py_reader /
double_buffer prefetching lives in paddle_tpu/reader.py (host pipeline +
jax.device_put prefetch), since under XLA the graph itself doesn't own IO.
"""

from __future__ import annotations

from typing import Sequence

from ..core.framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name: str, shape: Sequence[int], dtype="float32", append_batch_size: bool = True,
         lod_level: int = 0, stop_gradient: bool = True):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().global_block
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=stop_gradient,
    )
    var.lod_level = lod_level
    return var
