"""Tensor-construction layers (reference: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import unique_name
from ..core.framework import Variable
from .layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "ones_like",
    "zeros_like",
    "reshape",
    "transpose",
    "reverse",
    "scale",
    "increment",
    "cumsum",
    "range",
    "linspace",
    "has_inf",
    "has_nan",
    "isfinite",
    "sum",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_global_variable(shape=(), dtype=dtype, name=name, persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    from .layer_helper import ParamAttr

    attr = ParamAttr.to_attr(attr)
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from .. import initializer as init_mod

    helper = LayerHelper("global_var", name=name)
    name = name or unique_name.generate("global_var")
    var = helper.create_or_get_global_variable(
        list(shape), dtype, name, persistable=persistable,
        initializer=init_mod.Constant(float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": x}, outputs={"Out": out},
                     attrs={"out_dtype": dtype, "in_dtype": x.dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": list(input)}, outputs={"Out": out}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": out})
    return out


def sum(x):
    helper = LayerHelper("sum")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("sum", inputs={"X": list(xs)}, outputs={"Out": out})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": input}, outputs={"Out": output})
    else:
        value = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(value.dtype.name)
        helper.append_op(
            "assign_value",
            outputs={"Out": output},
            attrs={"shape": list(value.shape), "dtype": value.dtype.name,
                   "values": value.reshape(-1).tolist()},
        )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype, "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant_batch_size_like", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype, "value": float(value),
                            "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": x}, outputs={"Out": out})
    helper.append_op("increment", inputs={"X": out}, outputs={"Out": out}, attrs={"step": 1.0})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": x}, outputs={"Out": out})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("reshape2", inputs={"X": x}, outputs={"Out": out, "XShape": xshape},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("transpose2", inputs={"X": x}, outputs={"Out": out, "XShape": xshape},
                     attrs={"axis": list(perm)})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    axis = axis if isinstance(axis, (list, tuple)) else [axis]
    helper.append_op("reverse", inputs={"X": x}, outputs={"Out": out}, attrs={"axis": list(axis)})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": x}, outputs={"Out": out},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": x}, outputs={"Out": out}, attrs={"step": float(value)})
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if axis is not None:
        attrs["axis"] = axis
    if exclusive is not None:
        attrs["exclusive"] = exclusive
    if reverse is not None:
        attrs["reverse"] = reverse
    helper.append_op("cumsum", inputs={"X": x}, outputs={"Out": out}, attrs=attrs)
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    start = fill_constant([1], dtype, start) if not isinstance(start, Variable) else start
    end = fill_constant([1], dtype, end) if not isinstance(end, Variable) else end
    step = fill_constant([1], dtype, step) if not isinstance(step, Variable) else step
    out = helper.create_variable_for_type_inference(start.dtype)
    helper.append_op("range", inputs={"Start": start, "End": end, "Step": step}, outputs={"Out": out})
    return out


def linspace(start, stop, num, dtype):
    helper = LayerHelper("linspace")
    start = fill_constant([1], dtype, start) if not isinstance(start, Variable) else start
    stop = fill_constant([1], dtype, stop) if not isinstance(stop, Variable) else stop
    num = fill_constant([1], "int32", num) if not isinstance(num, Variable) else num
    out = helper.create_variable_for_type_inference(start.dtype)
    helper.append_op("linspace", inputs={"Start": start, "Stop": stop, "Num": num}, outputs={"Out": out})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("isfinite", inputs={"X": x}, outputs={"Out": out})
    return out


def has_inf(x):
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("has_inf", inputs={"X": x}, outputs={"Out": out})
    return out


def has_nan(x):
    helper = LayerHelper("has_nan")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("has_nan", inputs={"X": x}, outputs={"Out": out})
    return out


def tensor_array_to_tensor(input, axis=1, name=None):
    """Concatenate a TensorArray's entries along ``axis`` (reference:
    layers/tensor.py tensor_array_to_tensor →
    operators/tensor_array_to_tensor_op.cc). Returns (out, out_index) where
    out_index holds each entry's extent along the axis."""
    from .layer_helper import LayerHelper

    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(
        getattr(input, "elem_dtype", "float32"))
    idx = helper.create_variable_for_type_inference("int32")
    helper.append_op("tensor_array_to_tensor", inputs={"X": input},
                     outputs={"Out": out, "OutIndex": idx},
                     attrs={"axis": int(axis)})
    return out, idx


__all__.append("tensor_array_to_tensor")
