"""Recurrent layers: dynamic_lstm / dynamic_lstmp / dynamic_gru / lstm /
lstm_unit / gru_unit.

Reference API surface: ``python/paddle/fluid/layers/nn.py`` (dynamic_lstm,
dynamic_lstmp, dynamic_gru, gru_unit, lstm_unit, lstm). Fluid consumes
LoD-packed sequences; the TPU-native contract is a padded batch-major tensor
``[B, T, ...]`` plus an optional per-row ``length`` Variable (the repo-wide
padded+Length replacement for LoD). The ops lower to one ``lax.scan`` whose
step is a fused MXU matmul+gates block — see ops/rnn_ops.py.

As in the reference, dynamic_lstm/dynamic_gru expect the INPUT projection to
be done by a preceding ``fc`` (input size 4*hidden / 3*hidden): that keeps
the big [D, 4H] matmul outside the scan where XLA batches it over all
timesteps at once.
"""

from __future__ import annotations

from typing import Optional

from .layer_helper import LayerHelper, ParamAttr

__all__ = ["dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "lstm",
           "lstm_unit", "gru_unit"]


def dynamic_lstm(input, size, length=None, h_0=None, c_0=None,
                 param_attr=None, bias_attr=None, use_peepholes=True,
                 is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 dtype="float32", name=None):
    """reference: layers/nn.py dynamic_lstm (operators/lstm_op.cc).

    input: [B, T, 4*hidden] (x-projection from an fc); returns
    (hidden [B,T,H], cell [B,T,H]). ``size`` is 4*hidden for Fluid parity.
    """
    assert size % 4 == 0, "size must be 4*hidden"
    hidden = size // 4
    helper = LayerHelper("dynamic_lstm", name=name)
    weight = helper.create_parameter(param_attr, shape=[hidden, 4 * hidden],
                                     dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(bias_attr, shape=bias_size, dtype=dtype,
                                   is_bias=True)
    h = helper.create_variable_for_type_inference(dtype)
    c = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": input, "Weight": weight, "Bias": bias}
    if length is not None:
        inputs["Length"] = length
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op(
        "dynamic_lstm", inputs=inputs, outputs={"Hidden": h, "Cell": c},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return h, c


def dynamic_lstmp(input, size, proj_size, length=None, param_attr=None,
                  bias_attr=None, is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    """reference: layers/nn.py dynamic_lstmp (operators/lstmp_op.cc).
    Returns (projection [B,T,P], cell [B,T,H])."""
    assert size % 4 == 0
    hidden = size // 4
    helper = LayerHelper("dynamic_lstmp", name=name)
    weight = helper.create_parameter(param_attr, shape=[proj_size, 4 * hidden],
                                     dtype=dtype)
    proj_weight = helper.create_parameter(
        ParamAttr(name=(name or helper.name) + "_proj_w"),
        shape=[hidden, proj_size], dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[1, 4 * hidden],
                                   dtype=dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": input, "Weight": weight, "ProjWeight": proj_weight,
              "Bias": bias}
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "dynamic_lstmp", inputs=inputs,
        outputs={"Projection": proj, "Cell": cell},
        attrs={"is_reverse": is_reverse, "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj, cell


def dynamic_gru(input, size, length=None, h_0=None, param_attr=None,
                bias_attr=None, is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", origin_mode=False,
                dtype="float32", name=None):
    """reference: layers/nn.py dynamic_gru (operators/gru_op.cc).

    input: [B, T, 3*size]; returns hidden [B, T, size].
    """
    helper = LayerHelper("dynamic_gru", name=name)
    weight = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                     dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    h = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": input, "Weight": weight, "Bias": bias}
    if length is not None:
        inputs["Length"] = length
    if h_0 is not None:
        inputs["H0"] = h_0
    helper.append_op(
        "dynamic_gru", inputs=inputs, outputs={"Hidden": h},
        attrs={"is_reverse": is_reverse, "origin_mode": origin_mode,
               "gate_activation": gate_activation,
               "candidate_activation": candidate_activation})
    return h


def lstm(input, init_h=None, init_c=None, max_len=None, hidden_size=None,
         num_layers=1, length=None, dropout_prob=0.0, is_bidirec=False,
         dtype="float32", name=None):
    """Stacked (optionally bidirectional) LSTM over raw features — the
    cudnn_lstm analog (reference: layers/nn.py lstm,
    operators/cudnn_lstm_op.cu.cc). input: [B, T, D].

    Returns (out [B,T,H*dirs], last_h [L*dirs,B,H], last_c [L*dirs,B,H]).
    """
    assert hidden_size, "hidden_size is required"
    helper = LayerHelper("lstm", name=name)
    dirs = 2 if is_bidirec else 1
    in_dim = input.shape[-1]
    wx, wh, bs = [], [], []
    for layer in range(num_layers):
        d_in = in_dim if layer == 0 else hidden_size * dirs
        for d in range(dirs):
            sfx = "_l%d%s" % (layer, "_rev" if d else "")
            wx.append(helper.create_parameter(
                ParamAttr(name=helper.name + "_wx" + sfx),
                shape=[d_in, 4 * hidden_size], dtype=dtype))
            wh.append(helper.create_parameter(
                ParamAttr(name=helper.name + "_wh" + sfx),
                shape=[hidden_size, 4 * hidden_size], dtype=dtype))
            bs.append(helper.create_parameter(
                ParamAttr(name=helper.name + "_b" + sfx),
                shape=[4 * hidden_size], dtype=dtype, is_bias=True))
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": input, "WeightX": wx, "WeightH": wh, "Bias": bs}
    if init_h is not None:
        inputs["InitH"] = init_h
    if init_c is not None:
        inputs["InitC"] = init_c
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "lstm", inputs=inputs,
        outputs={"Out": out, "LastH": last_h, "LastC": last_c},
        attrs={"num_layers": num_layers, "is_bidirec": is_bidirec,
               "dropout_prob": dropout_prob})
    return out, last_h, last_c


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step (reference: layers/nn.py lstm_unit,
    operators/lstm_unit_op.cc): projects [x_t, h_prev] to 4H gates with an
    fc, then applies the cell. Returns (hidden [B,H], cell [B,H])."""
    from . import nn as nn_layers

    helper = LayerHelper("lstm_unit", name=name)
    size = cell_t_prev.shape[-1] * 4
    gates = nn_layers.fc([x_t, hidden_t_prev], size=size,
                         param_attr=param_attr, bias_attr=bias_attr)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        "lstm_unit", inputs={"X": gates, "C_prev": cell_t_prev},
        outputs={"H": h, "C": c}, attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", origin_mode=False,
             dtype="float32", name=None):
    """One GRU step (reference: layers/nn.py gru_unit,
    operators/gru_unit_op.cc). input: [B, 3*hidden] x-projection; ``size`` is
    3*hidden for Fluid parity. Returns (hidden [B,H], gate placeholder,
    reset_hidden placeholder) — Fluid returns a 3-tuple."""
    assert size % 3 == 0
    hidden_dim = size // 3
    helper = LayerHelper("gru_unit", name=name)
    weight = helper.create_parameter(param_attr, shape=[hidden_dim, 3 * hidden_dim],
                                     dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[1, 3 * hidden_dim],
                                   dtype=dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "gru_unit",
        inputs={"Input": input, "HiddenPrev": hidden, "Weight": weight,
                "Bias": bias},
        outputs={"Hidden": out},
        attrs={"origin_mode": origin_mode,
               "gate_activation": gate_activation,
               "candidate_activation": activation})
    return out, None, None
