"""Sequence layers over padded batches + lengths (reference:
layers/sequence_* wrappers in python/paddle/fluid/layers/nn.py).

See ops/sequence_ops.py for the LoD→padded+Length representation note.
Every layer takes an optional ``length`` Variable [B]; omitted means "all
rows full length".
"""

from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = [
    "sequence_mask",
    "sequence_pool",
    "sequence_softmax",
    "sequence_reverse",
    "sequence_expand",
    "sequence_expand_as",
    "sequence_concat",
    "sequence_pad",
    "sequence_unpad",
    "sequence_erase",
    "sequence_enumerate",
    "sequence_slice",
    "sequence_scatter",
    "sequence_first_step",
    "sequence_last_step",
    "im2sequence",
    "row_conv",
    "sequence_conv",
    "sequence_reshape",
]


def _seq_op(op_type, inputs, attrs=None, dtype=None, out_slot="Out", extra_outs=()):
    helper = LayerHelper(op_type)
    ref = next(iter(inputs.values()))
    out = helper.create_variable_for_type_inference(dtype or ref.dtype)
    outputs = {out_slot: out}
    extras = []
    for slot in extra_outs:
        v = helper.create_variable_for_type_inference("int32", stop_gradient=True)
        outputs[slot] = v
        extras.append(v)
    helper.append_op(op_type, inputs=inputs, outputs=outputs, attrs=attrs or {})
    return (out, *extras) if extras else out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    return _seq_op("sequence_mask", {"X": x},
                   {"maxlen": maxlen or -1, "out_dtype": dtype},
                   dtype=dtype, out_slot="Y")


def sequence_pool(input, pool_type, length=None, is_test=False, pad_value=0.0):
    inputs = {"X": input}
    if length is not None:
        inputs["Length"] = length
    return _seq_op("sequence_pool", inputs, {"pooltype": pool_type})


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    inputs = {"X": input}
    if length is not None:
        inputs["Length"] = length
    return _seq_op("sequence_softmax", inputs)


def sequence_reverse(x, length=None, name=None):
    inputs = {"X": x}
    if length is not None:
        inputs["Length"] = length
    return _seq_op("sequence_reverse", inputs, out_slot="Y")


def sequence_expand(x, y, ref_level=-1, name=None):
    return _seq_op("sequence_expand", {"X": x, "Y": y}, {"ref_level": ref_level})


def sequence_expand_as(x, y, name=None):
    return _seq_op("sequence_expand_as", {"X": x, "Y": y})


def sequence_concat(input, length=None, name=None):
    inputs = {"X": list(input)}
    if length is not None:
        inputs["Length"] = list(length)
        return _seq_op("sequence_concat", inputs, extra_outs=("LengthOut",))
    return _seq_op("sequence_concat", inputs)


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    inputs = {"X": x, "PadValue": pad_value}
    if length is not None:
        inputs["Length"] = length
    return _seq_op("sequence_pad", inputs, {"padded_length": maxlen or -1},
                   extra_outs=("Length",))


def sequence_unpad(x, length, name=None):
    return _seq_op("sequence_unpad", {"X": x, "Length": length})


def sequence_erase(x, tokens, name=None):
    return _seq_op("sequence_erase", {"X": x}, {"tokens": list(tokens)},
                   extra_outs=("Length",))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    return _seq_op("sequence_enumerate", {"X": input},
                   {"win_size": win_size, "pad_value": pad_value})


def sequence_slice(input, offset, length, out_maxlen=None, name=None):
    return _seq_op("sequence_slice",
                   {"X": input, "Offset": offset, "Length": length},
                   {"out_maxlen": out_maxlen or 0})


def sequence_scatter(input, index, updates, name=None):
    return _seq_op("sequence_scatter",
                   {"X": input, "Ids": index, "Updates": updates})


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    st = stride if isinstance(stride, (list, tuple)) else [stride] * 2
    return _seq_op("im2sequence", {"X": input}, {"kernels": list(fs), "strides": list(st)})


def row_conv(input, future_context_size, param_attr=None, act=None, name=None):
    from .layer_helper import ParamAttr

    helper = LayerHelper("row_conv", name=name)
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[future_context_size + 1, d],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", inputs={"X": input, "Filter": w}, outputs={"Out": out})
    return helper.append_activation(out) if act else out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  length=None, name=None):
    """Convolution over the time axis with a context window (reference:
    nn.py sequence_conv, operators/sequence_conv_op.cc). ``length`` is the
    padded+Length convention's per-row length var."""
    if filter_stride != 1:
        raise NotImplementedError("sequence_conv: only filter_stride=1 "
                                  "(matching the reference kernel)")
    helper = LayerHelper("sequence_conv", bias_attr=bias_attr, act=act,
                         name=name)
    filter_shape = [filter_size * input.shape[2], num_filters]
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype)
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": input, "Filter": w}
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "sequence_conv", inputs=inputs, outputs={"Out": pre_bias},
        attrs={"contextLength": filter_size,
               "contextStart": -(filter_size // 2), "contextStride": 1})
    if bias_attr is False:
        pre_act = pre_bias
    else:
        from .layer_helper import ParamAttr

        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[num_filters],
                                       dtype=input.dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": pre_bias, "Y": bias},
                         outputs={"Out": pre_act}, attrs={"axis": -1})
    return helper.append_activation(pre_act)


def sequence_reshape(input, new_dim, length=None):
    """Re-chunk the feature dim: [B, T, D] -> [B, T*D/new_dim, new_dim]
    (reference: nn.py sequence_reshape). Returns (out, new_length) when
    ``length`` is given, else out."""
    inputs = {"X": input}
    if length is not None:
        inputs["Length"] = length
        return _seq_op("sequence_reshape", inputs, {"new_dim": new_dim},
                       extra_outs=("OutLength",))
    return _seq_op("sequence_reshape", inputs, {"new_dim": new_dim})
