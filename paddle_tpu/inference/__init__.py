from .predictor import AnalysisConfig, Predictor, create_predictor  # noqa: F401
from .export import export_stablehlo, load_stablehlo  # noqa: F401
