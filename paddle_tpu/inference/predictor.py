"""Inference predictor API.

Reference: ``PaddlePredictor``/``CreatePaddlePredictor``
(``inference/api/paddle_api.h:186,314``) and ``AnalysisPredictor``
(``api/analysis_predictor.cc:183,337``). The reference loads a ProgramDesc,
runs an IR-pass fusion pipeline, and executes with NaiveExecutor. Here the
saved program desc is loaded and jit-compiled whole — XLA performs the
fusions the reference's analysis passes hand-roll (conv+bn fold, fc fuse,
transpose-flatten-concat, ...) — with a compile cache keyed on input shapes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.scope import Scope, scope_guard

__all__ = ["AnalysisConfig", "Predictor", "create_predictor"]


class AnalysisConfig:
    """reference: inference/api/paddle_analysis_config.h:34."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_tpu = True
        self._ir_optim = True  # accepted; XLA always optimizes
        self._memory_optim = True

    # GPU-era API parity: the accelerator here is the TPU.
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    enable_use_tpu = enable_use_gpu

    def disable_gpu(self):
        self._use_tpu = False

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def enable_memory_optim(self, x: bool = True):
        self._memory_optim = x

    def use_gpu(self) -> bool:
        return self._use_tpu


class _IOHandle:
    """Zero-copy-style tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        self._owner._staged_inputs[self.name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the array itself

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._owner._last_outputs[self.name])


class Predictor:
    def __init__(self, config: AnalysisConfig):
        from .. import io as io_mod
        from ..executor import Executor
        from ..core.place import CPUPlace, TPUPlace

        self.config = config
        self._scope = Scope()
        place = TPUPlace(0) if config.use_gpu() else CPUPlace()
        self._exe = Executor(place)
        with scope_guard(self._scope):
            self._program, self._feed_names, self._fetch_names = (
                io_mod.load_inference_model(
                    config.model_dir, self._exe,
                    model_filename=config.prog_file,
                    params_filename=config.params_file))
        self._staged_inputs: Dict[str, np.ndarray] = {}
        self._last_outputs: Dict[str, np.ndarray] = {}

    # -- modern handle API ----------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, True)

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, False)

    # -- execution ------------------------------------------------------------
    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """run([x1, x2, ...]) positional over feed names, or run() after
        staging via input handles. Returns outputs in fetch order."""
        if inputs is not None:
            feed = {n: np.asarray(a) for n, a in zip(self._feed_names, inputs)}
        else:
            feed = dict(self._staged_inputs)
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
        self._last_outputs = dict(zip(self._fetch_names, outs))
        return outs


def create_predictor(config: AnalysisConfig) -> Predictor:
    """reference: CreatePaddlePredictor (paddle_api.h:314)."""
    return Predictor(config)
