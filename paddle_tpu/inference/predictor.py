"""Inference predictor API.

Reference: ``PaddlePredictor``/``CreatePaddlePredictor``
(``inference/api/paddle_api.h:186,314``) and ``AnalysisPredictor``
(``api/analysis_predictor.cc:183,337``). The reference loads a ProgramDesc,
runs an IR-pass fusion pipeline, and executes with NaiveExecutor. Here the
saved program desc is loaded and jit-compiled whole — XLA performs the
fusions the reference's analysis passes hand-roll (conv+bn fold, fc fuse,
transpose-flatten-concat, ...) — with a compile cache keyed on input shapes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.scope import Scope, scope_guard

__all__ = ["AnalysisConfig", "Predictor", "create_predictor"]


class AnalysisConfig:
    """reference: inference/api/paddle_analysis_config.h:34."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_tpu = True
        self._ir_optim = True  # accepted; XLA always optimizes
        self._memory_optim = True
        # Round batch sizes up to power-of-two buckets so a varying-batch
        # client compiles O(log max_batch) specializations instead of one
        # per unique batch size (the executor's plan/compile caches key on
        # feed shapes). Outputs are sliced back to the true batch.
        self._batch_bucketing = True

    # GPU-era API parity: the accelerator here is the TPU.
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    enable_use_tpu = enable_use_gpu

    def disable_gpu(self):
        self._use_tpu = False

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def enable_memory_optim(self, x: bool = True):
        self._memory_optim = x

    def switch_batch_bucketing(self, x: bool = True):
        """Opt out (``switch_batch_bucketing(False)``) to compile per exact
        batch size — e.g. when a fixed-batch client wants zero padding."""
        self._batch_bucketing = bool(x)

    def batch_bucketing(self) -> bool:
        return self._batch_bucketing

    def use_gpu(self) -> bool:
        return self._use_tpu


class _IOHandle:
    """Zero-copy-style tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        arr = np.asarray(arr)
        want = self._owner._declared_shapes.get(self.name)
        if want is not None and tuple(arr.shape) != want:
            raise ValueError(
                "input %r staged with shape %s but reshape() declared %s"
                % (self.name, tuple(arr.shape), want))
        self._owner._staged_inputs[self.name] = arr

    def reshape(self, shape):
        """Declare the input's shape (reference ZeroCopyTensor::Reshape).
        Validated against the staged array — a mismatch raises instead of
        silently running with whatever was staged."""
        if not self._is_input:
            raise ValueError("reshape() is only valid on input handles")
        want = tuple(int(s) for s in shape)
        staged = self._owner._staged_inputs.get(self.name)
        if staged is not None and tuple(staged.shape) != want:
            raise ValueError(
                "reshape(%s) conflicts with already-staged array of shape %s "
                "for input %r" % (want, tuple(staged.shape), self.name))
        self._owner._declared_shapes[self.name] = want

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._owner._last_outputs[self.name])


class Predictor:
    def __init__(self, config: AnalysisConfig):
        from .. import io as io_mod
        from ..executor import Executor
        from ..core.place import CPUPlace, TPUPlace

        self.config = config
        self._scope = Scope()
        place = TPUPlace(0) if config.use_gpu() else CPUPlace()
        self._exe = Executor(place)
        with scope_guard(self._scope):
            self._program, self._feed_names, self._fetch_names = (
                io_mod.load_inference_model(
                    config.model_dir, self._exe,
                    model_filename=config.prog_file,
                    params_filename=config.params_file))
        self._staged_inputs: Dict[str, np.ndarray] = {}
        self._declared_shapes: Dict[str, tuple] = {}
        self._last_outputs: Dict[str, np.ndarray] = {}

    # -- modern handle API ----------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, True)

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, False)

    # -- execution ------------------------------------------------------------
    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """run([x1, x2, ...]) positional over feed names, or run() after
        staging via input handles. Returns outputs in fetch order.

        With batch bucketing on (the default; ``AnalysisConfig.
        switch_batch_bucketing(False)`` opts out), the leading dim is
        padded up to the next power of two before the step and sliced back
        after, bounding the compile cache to O(log max_batch) entries for a
        varying-batch client. Models with a batch-reducing fetch fall back
        to an exact-shape run (the reduction over padded rows would be
        wrong); the one undetectable edge is an output whose NON-batch
        leading dim coincidentally equals the padded batch while every
        other output is per-row — opt out of bucketing for such models."""
        if inputs is not None:
            feed = {n: np.asarray(a) for n, a in zip(self._feed_names, inputs)}
        else:
            feed = dict(self._staged_inputs)
            # staged inputs are consumed by the run (ZeroCopyTensor
            # semantics): the next iteration stages fresh arrays, and a new
            # reshape()/copy_from_cpu pair never collides with this one's
            self._staged_inputs.clear()
            self._declared_shapes.clear()
        exact_feed = dict(feed) if self.config.batch_bucketing() else None
        batch = self._bucket_batch(feed) if exact_feed is not None else None
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
            if batch is not None:
                real, padded = batch
                if all(getattr(o, "shape", ()) and o.shape[0] == padded
                       for o in outs):
                    # every fetch is per-row: drop the padding rows
                    outs = [o[:real] for o in outs]
                else:
                    # some fetch reduced over (or reshaped away) the batch
                    # dim — its value over the padded rows would be WRONG,
                    # and there is no way to un-reduce it. Re-run at the
                    # exact batch: correctness wins over the bucketed
                    # compile bound for this model (opt out of bucketing to
                    # skip the padded attempt entirely).
                    outs = self._exe.run(self._program, feed=exact_feed,
                                         fetch_list=self._fetch_names)
        self._last_outputs = dict(zip(self._fetch_names, outs))
        return outs

    @staticmethod
    def _bucket_batch(feed):
        """Pad every feed's leading dim up to the next power of two, in
        place; returns (real_batch, padded_batch) or None when the feeds
        don't share a positive leading dim (nothing to bucket). Padding
        repeats the last row (edge mode) so models with log/div ops never
        see synthetic zeros."""
        dims = {int(v.shape[0]) for v in feed.values()
                if getattr(v, "ndim", 0) >= 1}
        if len(dims) != 1:
            return None
        real = dims.pop()
        if real < 1 or any(getattr(v, "ndim", 0) < 1 for v in feed.values()):
            return None
        padded = 1
        while padded < real:
            padded *= 2
        if padded == real:
            return None
        for n, v in feed.items():
            feed[n] = np.pad(v, [(0, padded - real)] + [(0, 0)] * (v.ndim - 1),
                             mode="edge")
        return real, padded


def create_predictor(config: AnalysisConfig) -> Predictor:
    """reference: CreatePaddlePredictor (paddle_api.h:314)."""
    return Predictor(config)
