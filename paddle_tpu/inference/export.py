"""Compiled-artifact export via jax.export (StableHLO).

The XLA-native analog of shipping a serialized ProgramDesc for deployment
(reference: save_inference_model io.py:863 + the C++ predictor loading it):
the pruned inference function is lowered to StableHLO and serialized — a
self-contained, version-stable artifact runnable without the Python graph
builder.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np

__all__ = ["export_stablehlo", "load_stablehlo"]

_ARTIFACT = "__stablehlo__.bin"
_META = "__stablehlo_meta__.json"


def export_stablehlo(
    dirname: str,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    example_feeds: Dict[str, np.ndarray],
    program=None,
    scope=None,
    batch_polymorphic: bool = True,
):
    """Export the program's feed→fetch function as serialized StableHLO.

    Params are baked into the artifact as constants (deployment-style).
    With ``batch_polymorphic`` the leading dim is exported symbolically so
    any batch size runs without re-export.
    """
    import json

    from ..core.framework import default_main_program
    from ..core.scope import global_scope
    from ..executor import _CompiledStep, Executor

    program = (program or default_main_program()).clone(for_test=True)
    scope = scope or global_scope()
    exe = Executor()
    state_names = exe._persistable_names(program, scope)
    state = exe._gather_state(program, scope, state_names)

    step = _CompiledStep(program, tuple(sorted(feed_names)), tuple(fetch_names),
                         tuple(sorted(state)), is_test=True, jit=False)
    step_idx = np.uint32(0)  # the step fn derives its PRNG key internally

    def infer_fn(feeds):
        _, fetches = step.fn(state, feeds, step_idx)
        return list(fetches)

    if batch_polymorphic:
        b = jax.export.symbolic_shape("b")[0]
        args = {
            n: jax.ShapeDtypeStruct((b,) + np.asarray(v).shape[1:],
                                    np.asarray(v).dtype)
            for n, v in example_feeds.items()
        }
    else:
        args = {n: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
                for n, v in example_feeds.items()}

    exported = jax.export.export(jax.jit(infer_fn))(args)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, _ARTIFACT), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, _META), "w") as f:
        json.dump({"feed_names": list(feed_names),
                   "fetch_names": list(fetch_names)}, f)
    return os.path.join(dirname, _ARTIFACT)


class _LoadedModule:
    def __init__(self, exported, feed_names, fetch_names):
        self._exported = exported
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self._call = jax.jit(exported.call)

    def run(self, feed: Dict[str, np.ndarray]):
        out = self._call({n: np.asarray(v) for n, v in feed.items()})
        return [np.asarray(o) for o in out]


def load_stablehlo(dirname: str) -> _LoadedModule:
    import json

    with open(os.path.join(dirname, _ARTIFACT), "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    with open(os.path.join(dirname, _META)) as f:
        meta = json.load(f)
    return _LoadedModule(exported, meta["feed_names"], meta["fetch_names"])
