"""Runtime flag system (reference: gflags DEFINE_* + the FLAGS_* env
whitelist in python/paddle/fluid/__init__.py:128-160).

Flags are read from ``FLAGS_*`` environment variables at import (the
``--tryfromenv`` path of init.cc:44) and mutable at runtime via set_flag().
Only flags that mean something under XLA are wired; the rest are accepted
and ignored for script compatibility.
"""

from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["get_flag", "set_flag", "flags"]

_DEFAULTS: Dict[str, Any] = {
    # honored
    "check_nan_inf": False,          # post-step NaN/Inf scan (operator.cc:947)
    "benchmark": False,              # block_until_ready every step (operator.cc:942)
    "strict_fused_attention": False, # raise (not warn+fallback) if the Pallas
                                     # flash-attention call fails on TPU
    "flash_attention_min_seq": 2048, # perf crossover: with v5e-tuned
                                     # BlockSizes (r4 sweep) flash beats
                                     # composed 1.6x at S=2048 up to 4.2x at
                                     # S=8192; composed wins below (its single
                                     # fused HLO beats the kernel's fixed
                                     # grid overhead at short S)
    "unfused_attention": False,      # layers.attention emits the reference-
                                     # style primitive composition (matmul/
                                     # scale/softmax/dropout/matmul) instead
                                     # of the fused op for non-causal, non-
                                     # segmented attention; the default
                                     # optimizer's flash_attention_rewrite
                                     # (PADDLE_TPU_OPT_LEVEL>=1) fuses it
                                     # back — the graph stays inspectable,
                                     # the kernel still gets hit
    "attention_softmax_f32": False,  # composed-attention softmax in f32:
                                     # +5 GB/step on Transformer-base (XLA
                                     # materializes the f32 probs for bwd);
                                     # default bf16 matches raw-JAX practice
    "ring_flash_min_block": 2048,    # ring attention: local shard length at
                                     # which the per-block compute switches
                                     # from composed to the Pallas flash
                                     # kernel (same crossover as above)
    "sparse_update_kernel": "auto",  # row-wise Pallas sparse-Adam/SGD kernel
                                     # (pallas_kernels/sparse_adam.py) instead
                                     # of the 3 XLA scatter fusions on
                                     # SelectedRows updates: "auto" = compiled
                                     # kernel on TPU, scatter elsewhere;
                                     # "on" = kernel everywhere (interpreted
                                     # off-TPU); "interpret" = force the
                                     # interpreter (parity tests); "off" =
                                     # always scatter
    "paged_attention_kernel": "auto", # ragged paged-attention Pallas decode
                                     # kernel (pallas_kernels/
                                     # paged_attention.py) instead of the XLA
                                     # page-gather + decode_attention in the
                                     # serving decode scan: "auto" = compiled
                                     # kernel on TPU, gather elsewhere;
                                     # "on" = kernel everywhere (interpreted
                                     # off-TPU); "interpret" = force the
                                     # interpreter (parity tests); "off" =
                                     # always gather
    "ctr_alltoall_update": False,    # sharded-table sparse updates route
                                     # (ids, rows) to owner shards with an
                                     # explicit lax.all_to_all (PS split_ids
                                     # parity) instead of replicating the
                                     # merged rows to every model shard;
                                     # exact (worst-case bucket capacity),
                                     # see benchmarks/COLLECTIVES.md §7
    "eager_delete_tensor_gb": 0.0,   # accepted; XLA buffer liveness handles it
    # accepted for compatibility, no-ops under XLA
    "fraction_of_gpu_memory_to_use": 0.92,
    "allocator_strategy": "naive_best_fit",
    "cpu_deterministic": True,       # XLA is deterministic by construction
    "sync_nccl_allreduce": False,
    "paddle_num_threads": 1,
    "init_allocated_mem": False,
    "limit_of_tmp_allocation": -1,
    "rpc_deadline": 180000,
}

_flags: Dict[str, Any] = {}


def _coerce(default, raw: str):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, float):
        return float(raw)
    if isinstance(default, int):
        return int(raw)
    return raw


def _load_env():
    for name, default in _DEFAULTS.items():
        raw = os.environ.get("FLAGS_" + name)
        _flags[name] = _coerce(default, raw) if raw is not None else default


_load_env()


def get_flag(name: str):
    if name not in _flags:
        raise KeyError("unknown flag %r (known: %s)" % (name, sorted(_flags)))
    return _flags[name]


def set_flag(name: str, value):
    if name not in _flags:
        raise KeyError("unknown flag %r" % name)
    _flags[name] = value


class _Flags:
    def __getattr__(self, name):
        return get_flag(name)

    def __setattr__(self, name, value):
        set_flag(name, value)


flags = _Flags()
