"""py_reader: async, double-buffered graph input.

Reference: ``python/paddle/fluid/layers/io.py:636 py_reader`` +
``operators/reader/buffered_reader.cc`` + the LoDTensorBlockingQueue. The
reference's design is a C++ blocking queue drained by a ``read`` op inside
the graph; the TPU-native equivalent keeps the graph pure — the Executor
drains the queue at step boundaries and feeds the arrays as ordinary jit
args, while a background thread (plus DevicePrefetcher when
``use_double_buffer``) converts and device_puts the NEXT batch during the
current step. Same UX: ``start()`` / step until ``EOFException`` /
``reset()``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..monitor import metrics as _mx

__all__ = ["PyReader", "EOFException"]

# Input-pipeline health: a queue depth pinned at 0 with a fat wait-time
# histogram = the step loop is input-bound (the buffered_reader starvation
# signal the reference surfaces only via timeline gaps).
_m_queue_depth = _mx.gauge("reader/queue_depth",
                           help="py_reader queue depth at next_feed")
_m_wait_ms = _mx.histogram("reader/wait_time_ms",
                           help="time Executor.run blocked waiting for a batch")
_m_batches = _mx.counter("reader/batches", help="batches drained via next_feed")


class EOFException(Exception):
    """Raised by Executor.run when a started py_reader is exhausted
    (reference: fluid.core.EOFException from the read op)."""


class PyReader:
    """Queue-backed reader bound to a set of data variables.

    Created via ``fluid.layers.py_reader``; the Executor pulls one batch per
    ``run`` for the reader's variables when no explicit feed provides them.
    """

    _END = object()

    def __init__(self, data_vars, capacity: int, use_double_buffer: bool = True,
                 name: Optional[str] = None):
        self.data_vars = list(data_vars)
        self.var_names = [v.name for v in self.data_vars]
        self.capacity = int(capacity)
        self.use_double_buffer = use_double_buffer
        self.name = name
        self._source: Optional[Callable] = None
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._err = None
        self._started = False
        self._gen = 0  # incremented by reset() so stale workers die

    # -- decoration (reference: py_reader.decorate_paddle_reader) -------------
    def decorate_paddle_reader(self, reader: Callable, places=None):
        """``reader()`` yields batches as lists of per-sample tuples (the
        output of paddle.batch); samples are stacked per slot."""

        def gen():
            for batch in reader():
                slots = list(zip(*batch))
                yield tuple(np.asarray(np.stack(s)) for s in slots)

        self._source = gen

    def decorate_tensor_provider(self, reader: Callable, places=None):
        """``reader()`` yields tuples of ready batch arrays, one per var."""

        def gen():
            for batch in reader():
                yield tuple(np.asarray(a) for a in batch)

        self._source = gen

    decorate_batch_generator = decorate_tensor_provider

    def decorate_sample_list_generator(self, reader: Callable, places=None):
        self.decorate_paddle_reader(reader, places)

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        if self._source is None:
            raise RuntimeError(
                "py_reader has no data source; call decorate_paddle_reader / "
                "decorate_tensor_provider first")
        if self._started:
            raise RuntimeError("py_reader already started; call reset() between epochs")
        self._q = queue.Queue(maxsize=self.capacity)
        self._err = None
        self._started = True
        gen_token = self._gen

        def worker(q=self._q, token=gen_token):
            try:
                it = self._source()
                if self.use_double_buffer:
                    from .prefetcher import DevicePrefetcher

                    it = DevicePrefetcher(
                        ({n: a for n, a in zip(self.var_names, batch)} for batch in it),
                        capacity=2)
                    for feed in it:
                        if self._gen != token:
                            return
                        q.put(tuple(feed[n] for n in self.var_names))
                else:
                    for batch in it:
                        if self._gen != token:
                            return
                        q.put(batch)
            except Exception as e:
                self._err = e
            finally:
                q.put(self._END)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        """Stop the current pass (after EOF or mid-epoch) so start() can be
        called again (reference: reader->ReInit())."""
        self._gen += 1
        self._started = False
        q = self._q
        if q is not None:
            while True:  # drain so a blocked worker can exit
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        self._q = None

    # -- executor hook --------------------------------------------------------
    def next_feed(self) -> dict:
        """One batch as {var_name: array}; EOFException when exhausted."""
        if not self._started:
            raise RuntimeError("py_reader not started; call reader.start()")
        if _mx.enabled():
            _m_queue_depth.set(self._q.qsize())
            t0 = time.perf_counter()
            item = self._q.get()
            if item is not self._END:
                _m_wait_ms.observe((time.perf_counter() - t0) * 1e3)
                _m_batches.inc()
        else:
            item = self._q.get()
        if item is self._END:
            self._started = False
            if self._err is not None:
                raise self._err
            raise EOFException("py_reader %r exhausted" % (self.name or "py_reader"))
        if len(item) != len(self.var_names):
            raise ValueError(
                "py_reader produced %d arrays per batch but is bound to %d "
                "variables %s" % (len(item), len(self.var_names), self.var_names))
        return dict(zip(self.var_names, item))
