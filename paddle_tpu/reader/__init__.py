from .decorator import (  # noqa: F401
    batch,
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
    xmap_readers,
)
from .prefetcher import DevicePrefetcher  # noqa: F401
from .py_reader import EOFException, PyReader  # noqa: F401
