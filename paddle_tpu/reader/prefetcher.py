"""Device prefetcher — the double-buffer reader.

Reference: ``operators/reader/buffered_reader.cc`` (create_double_buffer
reader: async H2D copy on a dedicated stream) and py_reader's
``LoDTensorBlockingQueue``. Here a background thread converts + device_puts
the NEXT feed dict while the current step computes, overlapping host→HBM
transfer with TPU compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterator, Optional

import jax
import numpy as np

__all__ = ["DevicePrefetcher"]


class DevicePrefetcher:
    """Wrap an iterator of feed dicts; yields dicts whose arrays are already
    on device.

        for feed in DevicePrefetcher(feed_iter(), capacity=2):
            exe.run(main, feed=feed, fetch_list=[loss])
    """

    _END = object()

    def __init__(self, feeds: Iterator[Dict[str, np.ndarray]], capacity: int = 2,
                 device=None, sharding=None):
        self._src = feeds
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._device = device
        self._sharding = sharding
        self._thread: Optional[threading.Thread] = None
        self._err = None

    def _target(self):
        if self._sharding is not None:
            return self._sharding
        if self._device is not None:
            return self._device
        return jax.devices()[0]

    def _worker(self):
        try:
            tgt = self._target()
            for feed in self._src:
                self._q.put({k: jax.device_put(v, tgt) for k, v in feed.items()})
        except Exception as e:  # propagate into the consumer
            self._err = e
        finally:
            self._q.put(self._END)

    def __iter__(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        while True:
            item = self._q.get()
            if item is self._END:
                if self._err is not None:
                    raise self._err
                return
            yield item
