"""Device prefetcher — the double-buffer reader.

Reference: ``operators/reader/buffered_reader.cc`` (create_double_buffer
reader: async H2D copy on a dedicated stream) and py_reader's
``LoDTensorBlockingQueue``. Here a background thread converts + device_puts
the NEXT feed dict while the current step computes, overlapping host→HBM
transfer with TPU compute.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterator, Optional

import jax
import numpy as np

from ..monitor import metrics as _mx

__all__ = ["DevicePrefetcher"]

_m_depth = _mx.gauge("prefetcher/queue_depth",
                     help="ready-on-device batches buffered ahead")
_m_h2d_ms = _mx.histogram("prefetcher/h2d_ms",
                          help="host→device put dispatch time per batch")
_m_wait_ms = _mx.histogram("prefetcher/wait_time_ms",
                           help="consumer wait for the next device batch")


class DevicePrefetcher:
    """Wrap an iterator of feed dicts; yields dicts whose arrays are already
    on device.

        for feed in DevicePrefetcher(feed_iter(), capacity=2):
            exe.run(main, feed=feed, fetch_list=[loss])

    Also a context manager: ``with DevicePrefetcher(...) as pf:`` guarantees
    the worker thread is stopped (and its buffered device batches dropped)
    when the block exits, even if the consumer abandons the loop early —
    without ``stop()``, a walked-away-from iterator would leave the worker
    blocked on a full queue forever, pinning ``capacity`` batches of device
    memory. Worker exceptions surface in the consumer with the worker's
    original traceback, as soon as the failing batch's slot is reached.
    """

    _END = object()

    def __init__(self, feeds: Iterator[Dict[str, np.ndarray]], capacity: int = 2,
                 device=None, sharding=None):
        self._src = feeds
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._device = device
        self._sharding = sharding
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._finished = False  # consumer saw _END: source exhausted

    def _target(self):
        if self._sharding is not None:
            return self._sharding
        if self._device is not None:
            return self._device
        return jax.devices()[0]

    def _put(self, item) -> bool:
        """Queue.put that stays responsive to stop(); False = stopping."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            tgt = self._target()
            for feed in self._src:
                if self._stop.is_set():
                    return
                if _mx.enabled():
                    t0 = time.perf_counter()
                    out = {k: jax.device_put(v, tgt) for k, v in feed.items()}
                    _m_h2d_ms.observe((time.perf_counter() - t0) * 1e3)
                else:
                    out = {k: jax.device_put(v, tgt) for k, v in feed.items()}
                if not self._put(out):
                    return
        except BaseException as e:  # propagate into the consumer
            # __traceback__ rides along, so the consumer's re-raise shows
            # the worker frame that actually failed, not this one
            self._err = e
        finally:
            self._put(self._END)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "DevicePrefetcher":
        """Start the background H2D thread (idempotent; __iter__ calls it)."""
        if self._stop.is_set():
            raise RuntimeError("DevicePrefetcher was stopped; create a new one")
        if self._thread is None:
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the worker and release its buffered device batches.

        Safe to call from any state (not started / mid-iteration / already
        stopped). After stop() the iterator terminates; a worker blocked on
        the full queue is unblocked and exits instead of holding device
        buffers for the life of the process.
        """
        self._stop.set()
        q = self._q
        while True:  # drop buffered batches so a blocked worker can exit
            try:
                q.get_nowait()
            except queue.Empty:
                break
        try:
            # wake a consumer blocked in q.get(): the drain above may have
            # swallowed the worker's _END, and a stopped worker won't enqueue
            # another one
            q.put_nowait(self._END)
        except queue.Full:
            pass
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=1.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def _raise_worker_error(self):
        err = self._err
        self._err = None
        # re-raising the stored exception keeps the worker thread's original
        # traceback (its __traceback__) under this consumer-side frame
        raise err

    def __iter__(self):
        if self._finished:
            # source already drained: a second epoch loop over the same
            # prefetcher terminates immediately (there is one worker per
            # prefetcher now, so waiting on the queue would block forever)
            return
        self.start()
        while True:
            if self._stop.is_set():
                if self._err is not None:
                    self._raise_worker_error()
                return
            if _mx.enabled():
                _m_depth.set(self._q.qsize())
                t0 = time.perf_counter()
                item = self._q.get()
                _m_wait_ms.observe((time.perf_counter() - t0) * 1e3)
            else:
                item = self._q.get()
            if item is self._END:
                self._finished = True
                if self._err is not None:
                    self._raise_worker_error()
                return
            yield item
