"""Device prefetcher — the double-buffer reader.

Reference: ``operators/reader/buffered_reader.cc`` (create_double_buffer
reader: async H2D copy on a dedicated stream) and py_reader's
``LoDTensorBlockingQueue``. Here a background thread converts + device_puts
the NEXT feed dict while the current step computes, overlapping host→HBM
transfer with TPU compute.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, Optional

import jax
import numpy as np

from ..monitor import metrics as _mx

__all__ = ["DevicePrefetcher"]

_m_depth = _mx.gauge("prefetcher/queue_depth",
                     help="ready-on-device batches buffered ahead")
_m_h2d_ms = _mx.histogram("prefetcher/h2d_ms",
                          help="host→device put dispatch time per batch")
_m_wait_ms = _mx.histogram("prefetcher/wait_time_ms",
                           help="consumer wait for the next device batch")


class DevicePrefetcher:
    """Wrap an iterator of feed dicts; yields dicts whose arrays are already
    on device.

        for feed in DevicePrefetcher(feed_iter(), capacity=2):
            exe.run(main, feed=feed, fetch_list=[loss])
    """

    _END = object()

    def __init__(self, feeds: Iterator[Dict[str, np.ndarray]], capacity: int = 2,
                 device=None, sharding=None):
        self._src = feeds
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._device = device
        self._sharding = sharding
        self._thread: Optional[threading.Thread] = None
        self._err = None

    def _target(self):
        if self._sharding is not None:
            return self._sharding
        if self._device is not None:
            return self._device
        return jax.devices()[0]

    def _worker(self):
        try:
            tgt = self._target()
            for feed in self._src:
                if _mx.enabled():
                    t0 = time.perf_counter()
                    out = {k: jax.device_put(v, tgt) for k, v in feed.items()}
                    _m_h2d_ms.observe((time.perf_counter() - t0) * 1e3)
                else:
                    out = {k: jax.device_put(v, tgt) for k, v in feed.items()}
                self._q.put(out)
        except Exception as e:  # propagate into the consumer
            self._err = e
        finally:
            self._q.put(self._END)

    def __iter__(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        while True:
            if _mx.enabled():
                _m_depth.set(self._q.qsize())
                t0 = time.perf_counter()
                item = self._q.get()
                _m_wait_ms.observe((time.perf_counter() - t0) * 1e3)
            else:
                item = self._q.get()
            if item is self._END:
                if self._err is not None:
                    raise self._err
                return
            yield item
