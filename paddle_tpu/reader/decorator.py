"""Reader decorators (reference: python/paddle/reader/decorator.py).

A reader is a zero-arg callable returning an iterator of examples. Decorators
compose readers — batching, shuffling, buffering, parallel mapping — exactly
the reference's API.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Callable, Iterable, List

__all__ = ["map_readers", "shuffle", "chain", "compose", "batch", "buffered",
           "firstn", "cache", "xmap_readers"]


def map_readers(func: Callable, *readers):
    def reader():
        iters = [r() for r in readers]
        for items in zip(*iters):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int, seed=None):
    def shuffled():
        rnd = _random.Random(seed)
        buf: List = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rnd.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rnd.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, check_alignment: bool = True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        iters = [r() for r in readers]
        if check_alignment:
            for items in zip(*iters):
                yield sum((make_tuple(i) for i in items), ())
            for it in iters:
                try:
                    next(it)
                    raise RuntimeError("composed readers have different lengths")
                except StopIteration:
                    pass
        else:
            for items in itertools.zip_longest(*iters):
                yield sum((make_tuple(i) for i in items if i is not None), ())

    return reader


def batch(reader, batch_size: int, drop_last: bool = False):
    def batched():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batched


def buffered(reader, size: int):
    """Background-thread read-ahead (reference: decorator.py buffered)."""

    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def worker():
            try:
                for e in reader():
                    q.put(e)
            finally:
                q.put(_End)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return buffered_reader


def firstn(reader, n: int):
    def reader_n():
        return itertools.islice(reader(), n)

    return reader_n


def cache(reader):
    all_data = []
    filled = []

    def cached():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)

    return cached


def xmap_readers(mapper, reader, process_num: int, buffer_size: int, order: bool = False):
    """Parallel map over a reader with worker threads (reference:
    decorator.py xmap_readers)."""

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feeder():
            for i, e in enumerate(reader()):
                in_q.put((i, e))
            for _ in range(process_num):
                in_q.put(end)

        def worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, e = item
                out_q.put((i, mapper(e)))

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, v = item
                pending[i] = v
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]

    return xreader
