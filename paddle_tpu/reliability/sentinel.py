"""Divergence sentinel: declarative rules + checkpoint rollback = self-heal.

PR 7 made crashes survivable and the CHECK_NUMERICS=2 watchdog *names* the
op a NaN was born at — but the job still dies. The sentinel closes the
loop (the Tensor Processing Primitives thesis — attribution should drive
automated *recovery*, not just diagnosis): :func:`~.supervisor
.run_supervised` evaluates a :class:`DivergenceSentinel` against every
fused chunk's fetched losses (and against the watchdog's typed exception
when the guarded step trips first); a rule firing **rolls the run back**
to the last good rotating checkpoint — model + optimizer state + per-step
RNG counter + data-reader position, all three restored together —
**quarantines** the data window that preceded the trip (reader-mode feed
sources only; the records are skipped on replay and on every later epoch),
optionally backs off the LR, and resumes. The healed trajectory is
bit-identical to a run that never saw the poisoned batches (the chaos
drill asserts this in hex).

Rules (all declarative constructor knobs):

``nan``             non-finite loss in the chunk, or the numerics
                    watchdog's typed exception (level 1 or 2; the level-2
                    ``<slot>:<type>`` op name is carried into the trip
                    record, the flight dump and the fatal error).
``spike_z``         windowed loss-spike z-score: trip when a chunk loss
                    deviates from the trailing ``spike_window`` committed
                    losses by more than ``spike_z`` standard deviations.
``plateau_window``  no improvement of at least ``plateau_min_delta`` over
                    the last ``plateau_window`` committed losses (pair it
                    with ``lr_backoff``; a plateau rollback alone replays
                    the same plateau).
``max_grad_norm``   ceiling on the ``optimizer/grad_global_norm`` gauge
                    (requires ``PADDLE_TPU_GRAD_NORM=1``).

The rollback budget is bounded: ``max_trips`` total, and a SECOND trip at
the same chunk-start step is immediately fatal (the quarantine did not
help — the divergence is systematic, not data). Fatal = flight-recorder
``sentinel_fatal`` event + typed :class:`SentinelFatal` carrying the trip
history and the watchdog-named op. ``sentinel/*`` counters ride the
telemetry exporter like every other family.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from ..monitor import metrics as _mx

__all__ = ["DivergenceSentinel", "SentinelTrip", "SentinelFatal"]

_m_trips = _mx.counter("sentinel/trips",
                       help="divergence rules tripped (all rules)")
_m_rollbacks = _mx.counter(
    "sentinel/rollbacks",
    help="checkpoint rollbacks performed by the supervisor on a trip")
_m_quarantined = _mx.counter(
    "sentinel/records_quarantined",
    help="records quarantined as part of a tripped data window")
_m_lr_backoffs = _mx.counter(
    "sentinel/lr_backoffs", help="LR backoffs applied on a trip")
_m_fatals = _mx.counter(
    "sentinel/fatals",
    help="trips escalated to SentinelFatal (budget exhausted or repeat "
         "trip at the same step)")
_m_rule = {r: _mx.counter("sentinel/trips_%s" % r,
                          help="trips attributed to the %s rule" % r)
           for r in ("nan", "spike", "plateau", "grad_norm", "drift")}

_WATCHDOG_OP_RE = re.compile(r"first produced by op (\S+)")


class SentinelTrip:
    """One rule firing: where, why, and (for watchdog trips) which op."""

    __slots__ = ("step", "rule", "reason", "named_op", "chunk_steps")

    def __init__(self, step: int, rule: str, reason: str,
                 named_op: Optional[str] = None, chunk_steps: int = 1):
        self.step = int(step)
        self.rule = rule
        self.reason = reason
        self.named_op = named_op
        self.chunk_steps = int(chunk_steps)

    def to_doc(self) -> dict:
        return {"step": self.step, "rule": self.rule, "reason": self.reason,
                "named_op": self.named_op, "chunk_steps": self.chunk_steps}

    def __repr__(self):
        return "SentinelTrip(step=%d, rule=%s, op=%s: %s)" % (
            self.step, self.rule, self.named_op, self.reason)


class SentinelFatal(RuntimeError):
    """The sentinel gave up: rollback budget exhausted or a repeat trip at
    the same step. Carries the full trip history; the watchdog-named op of
    the final trip rides in the message and in ``.trips[-1].named_op``."""

    def __init__(self, msg: str, trips: Sequence[SentinelTrip]):
        super().__init__(msg)
        self.trips = list(trips)


class DivergenceSentinel:
    """Rule set + trip bookkeeping. One instance supervises one
    ``run_supervised`` call (trip history is per-run state)."""

    def __init__(self, *,
                 nan: bool = True,
                 spike_z: Optional[float] = None,
                 spike_window: int = 32,
                 spike_min_history: int = 8,
                 plateau_window: Optional[int] = None,
                 plateau_min_delta: float = 0.0,
                 max_grad_norm: Optional[float] = None,
                 drift: bool = False,
                 loss_index: int = 0,
                 max_trips: int = 3,
                 lr_backoff: Optional[float] = None,
                 lr_var: Optional[str] = None):
        if spike_z is not None and spike_z <= 0:
            raise ValueError("spike_z must be positive")
        if lr_backoff is not None and not (0 < lr_backoff < 1):
            raise ValueError("lr_backoff must be a factor in (0, 1)")
        if lr_backoff is not None and not lr_var:
            raise ValueError("lr_backoff needs lr_var (the scope name of "
                             "the learning-rate variable to scale)")
        self.nan = bool(nan)
        self.spike_z = spike_z
        self.spike_window = int(spike_window)
        self.spike_min_history = int(spike_min_history)
        self.plateau_window = plateau_window
        self.plateau_min_delta = float(plateau_min_delta)
        self.max_grad_norm = max_grad_norm
        # opt-in: trip on monitor.numerics drift early-warnings (an op's
        # absmax trending toward overflow / collapsing to zero) — the
        # PREDICTIVE rule; it fires chunks before the nan rule can see a
        # non-finite loss, while a rollback + LR backoff can still help.
        # Inert unless PADDLE_TPU_NUMERICS is also armed (no stats, no
        # drift events to drain).
        self.drift = bool(drift)
        self.loss_index = int(loss_index)
        self.max_trips = int(max_trips)
        self.lr_backoff = lr_backoff
        self.lr_var = lr_var
        self.trips: List[SentinelTrip] = []
        self._trip_steps = {}  # chunk-start step -> trip count

    # -- rule evaluation ------------------------------------------------------
    def _loss(self, row) -> float:
        return float(np.asarray(row[self.loss_index]).ravel()[0])

    def history_window(self) -> int:
        """How many trailing committed losses the rules actually read —
        the supervisor slices its loss list to this, so a long run's
        per-chunk evaluation stays O(window), not O(steps so far)."""
        need = max(self.spike_window, self.spike_min_history, 1)
        if self.plateau_window is not None:
            need = max(need, 2 * int(self.plateau_window))
        return need

    def check_exception(self, exc: BaseException) -> Optional[SentinelTrip]:
        """Map a chunk-dispatch exception to a trip: the numerics
        watchdog's typed errors (level 2 names the originating op; level 1
        is the fetch/state-level backstop) are divergence, everything else
        is the retry ladder's business."""
        if not self.nan:
            return None
        txt = str(exc)
        from ..core.enforce import EnforceNotMet

        if isinstance(exc, EnforceNotMet) and "CHECK_NUMERICS" in txt:
            m = _WATCHDOG_OP_RE.search(txt)
            return SentinelTrip(
                -1, "nan", txt.splitlines()[0],
                named_op=m.group(1) if m else None)
        if isinstance(exc, RuntimeError) and "check_nan_inf" in txt:
            return SentinelTrip(-1, "nan", txt.splitlines()[0])
        return None

    def check_rows(self, rows: Sequence,
                   history: Sequence[float]) -> Optional[SentinelTrip]:
        """Evaluate the rules against one committed-candidate chunk.
        ``rows``: per-step fetch rows of the chunk; ``history``: committed
        per-step losses BEFORE this chunk (the supervisor's loss list, so
        a rollback rewinds the window for free)."""
        losses = [self._loss(r) for r in rows]
        if self.nan:
            for i, v in enumerate(losses):
                if not np.isfinite(v):
                    return SentinelTrip(
                        i, "nan", "non-finite loss %r at step %d of the "
                        "chunk" % (v, i), chunk_steps=len(rows))
        if self.spike_z is not None and \
                len(history) >= self.spike_min_history:
            win = np.asarray(history[-self.spike_window:], np.float64)
            mean = float(win.mean())
            std = float(win.std())
            floor = max(1e-12, 1e-6 * abs(mean))
            std = max(std, floor)
            for i, v in enumerate(losses):
                z = abs(v - mean) / std
                if z > self.spike_z:
                    return SentinelTrip(
                        i, "spike",
                        "loss %.6g is %.1f sigma from the trailing-%d "
                        "window mean %.6g" % (v, z, len(win), mean),
                        chunk_steps=len(rows))
        if self.plateau_window is not None:
            w = int(self.plateau_window)
            full = list(history) + losses
            if len(full) >= 2 * w:
                recent = min(full[-w:])
                before = min(full[-2 * w:-w])
                if recent >= before - self.plateau_min_delta:
                    return SentinelTrip(
                        0, "plateau",
                        "best loss %.6g over the last %d steps did not "
                        "improve on %.6g by %g" % (recent, w, before,
                                                   self.plateau_min_delta),
                        chunk_steps=len(rows))
        if self.max_grad_norm is not None:
            # get-or-create returns the same instance the executor feeds
            # (PADDLE_TPU_GRAD_NORM=1); never-written stays silent
            g = _mx.gauge("optimizer/grad_global_norm")
            if getattr(g, "_written", False):
                gn = float(g.value)
                if not np.isfinite(gn) or gn > self.max_grad_norm:
                    return SentinelTrip(
                        0, "grad_norm",
                        "grad global norm %.6g exceeds ceiling %.6g"
                        % (gn, self.max_grad_norm), chunk_steps=len(rows))
        if self.drift:
            from ..monitor import numerics as _num

            events = _num.drain_drift_events()
            if events:
                ev = events[0]
                horizon = ev.get("chunks_to_overflow")
                return SentinelTrip(
                    0, "drift",
                    "op %s absmax %.6g %s%s" % (
                        ev["op"], ev["absmax"], ev["kind"],
                        "" if horizon is None else
                        " (~%.1f chunks to overflow)" % horizon),
                    named_op=ev["op"], chunk_steps=len(rows))
        return None

    # -- trip bookkeeping (called by the supervisor) --------------------------
    def register_trip(self, chunk_start: int, trip: SentinelTrip) -> None:
        """Record a trip at ``chunk_start``; raises :class:`SentinelFatal`
        when the budget is exhausted or this step tripped before."""
        trip.step = int(chunk_start)
        self.trips.append(trip)
        self._trip_steps[chunk_start] = \
            self._trip_steps.get(chunk_start, 0) + 1
        _m_trips.inc()
        if trip.rule in _m_rule:
            _m_rule[trip.rule].inc()
        if self._trip_steps[chunk_start] > 1:
            _m_fatals.inc()
            raise SentinelFatal(
                "sentinel: REPEAT trip at step %d after rollback+quarantine "
                "(%s%s) — divergence is systematic, not bad data; dying "
                "with state intact for the post-mortem"
                % (chunk_start, trip.reason,
                   ", watchdog op %s" % trip.named_op if trip.named_op
                   else ""), self.trips)
        if len(self.trips) > self.max_trips:
            _m_fatals.inc()
            raise SentinelFatal(
                "sentinel: rollback budget exhausted (%d trips > "
                "max_trips=%d; last: %s)"
                % (len(self.trips), self.max_trips, trip.reason), self.trips)

    def record_rollback(self, n_quarantined: int) -> None:
        _m_rollbacks.inc()
        if n_quarantined:
            _m_quarantined.inc(n_quarantined)

    def apply_lr_backoff(self, scope) -> bool:
        """Scale ``lr_var`` in ``scope`` by the backoff factor (configured
        only; returns False when inert). NOTE: backoff intentionally
        breaks bit-parity with an undisturbed twin — leave it off when the
        drill's bit-identity contract matters."""
        if self.lr_backoff is None:
            return False
        cur = scope.find_var(self.lr_var)
        if cur is None:
            from ..log import vlog

            vlog(0, "sentinel: lr_var %r not found in scope; backoff "
                    "skipped", self.lr_var)
            return False
        scope.set_var(self.lr_var,
                      np.asarray(cur, np.float32) * self.lr_backoff)
        _m_lr_backoffs.inc()
        return True
