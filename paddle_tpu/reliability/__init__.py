"""paddle_tpu.reliability — fault injection + crash-safe training/serving.

The production-traffic posture layer (ROADMAP item 4b): the PR 5 flight
recorder can *describe* a crash and the PR 6 serving stack can *count* one;
this package makes the system *survive* them —

* :mod:`~.faults`: a deterministic, seedable fault-injection framework
  (``PADDLE_TPU_FAULT_PLAN`` env grammar or programmatic
  :class:`~.faults.FaultPlan`) arming typed faults — preemption, transient
  dispatch/compile failure, RESOURCE_EXHAUSTED, injected NaN, latency
  spikes, pool exhaustion — at the chokepoints that already exist
  (Executor dispatch, the AOT compile path, the serving decode dispatch,
  ``io.save_checkpoint``, ``PagePool.alloc``), plus :func:`~.faults.classify`,
  the one transient/fatal/preemption retry-policy oracle.
* :mod:`~.supervisor`: :func:`~.supervisor.run_supervised` — the
  preemption-aware training driver: SIGTERM/SIGINT finish the in-flight
  fused chunk, write a rotating checkpoint and exit with
  :data:`~.supervisor.EXIT_PREEMPTED`; periodic auto-checkpoint (a
  checkpointable feed source's position — ``paddle_tpu.data`` — rides
  inside every serial); auto-resume with the per-step RNG counter AND
  the data-reader position rewound so the resumed trajectory is
  bit-identical and exactly-once; bounded retry with seeded-jitter
  backoff (:func:`~.supervisor.backoff_schedule`) for transient faults.
* :mod:`~.sentinel`: :class:`~.sentinel.DivergenceSentinel` — declarative
  divergence rules (NaN/watchdog, loss-spike z-score, plateau, grad-norm
  ceiling) evaluated per fused chunk; a trip rolls back to the last good
  checkpoint (model + RNG + reader state), quarantines the offending
  data window through the reader, optionally backs off LR, and resumes —
  bounded by ``max_trips`` with repeat-trip-at-same-step fatal
  (:class:`~.sentinel.SentinelFatal` carrying the watchdog-named op).

Serving-side recovery (per-request deadlines, decode-failure batch
recovery, ``engine.health()``) lives in :mod:`paddle_tpu.serving` and uses
:func:`~.faults.classify` for its retry ladder. Drills:
``python -m tools.chaos_drill --selftest`` (ROADMAP smoke gate) and the
multi-process kill/resume drill in ``tests/test_dist_multiprocess.py``.
"""

from . import faults  # noqa: F401
from . import sentinel  # noqa: F401
from .faults import (  # noqa: F401
    FaultPlan, FaultSpec, InjectedFault, TransientFault,
    InjectedResourceExhausted, PreemptionRequested, classify,
)
from .sentinel import (  # noqa: F401
    DivergenceSentinel, SentinelFatal, SentinelTrip,
)

__all__ = [
    "faults", "sentinel", "FaultPlan", "FaultSpec", "InjectedFault",
    "TransientFault", "InjectedResourceExhausted", "PreemptionRequested",
    "classify", "DivergenceSentinel", "SentinelFatal", "SentinelTrip",
    "EXIT_PREEMPTED", "SupervisorResult", "run_supervised",
    "backoff_schedule",
]

_SUPERVISOR_NAMES = ("EXIT_PREEMPTED", "SupervisorResult", "run_supervised",
                     "backoff_schedule")


def __getattr__(name):
    # supervisor imports the Executor/io stack; loading it lazily keeps
    # `executor -> reliability.faults` import-cycle-free
    if name in _SUPERVISOR_NAMES:
        from . import supervisor as _sup

        return getattr(_sup, name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
