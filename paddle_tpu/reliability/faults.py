"""Deterministic fault injection for crash drills (the chaos layer).

A :class:`FaultPlan` arms named **injection sites** — chokepoints that
already exist in the hot paths (``executor.dispatch``, ``executor.compile``,
``serving.decode``, ``io.save_checkpoint``, ``page_pool.alloc``) — with
typed faults fired at deterministic visit counts, so a drill reproduces the
same failure at the same step every run (seedable when probabilistic
entries are used). Sites poll the plan with :func:`poll`; with no plan
installed and ``PADDLE_TPU_FAULT_PLAN`` unset the whole subsystem costs one
module-global ``None`` check per chokepoint.

Plan grammar (``PADDLE_TPU_FAULT_PLAN`` or :meth:`FaultPlan.parse`)::

    plan    := entry (';' entry)*
    entry   := site '@' N '=' kind [ ':' times [ ':' ms ] ]

``site@N=kind`` fires ``kind`` on the Nth visit to ``site`` (1-based), for
``times`` consecutive visits (default 1); ``ms`` parameterizes ``latency``.
Example::

    PADDLE_TPU_FAULT_PLAN='serving.decode@3=transient:2;executor.dispatch@5=preempt'

Fault kinds:

``preempt``
    delivers SIGTERM to the current process (the preemption-notice shape a
    cloud scheduler sends) — :func:`~.supervisor.run_supervised`'s handlers
    turn it into checkpoint-and-exit.
``transient``
    raises :class:`TransientFault` (classified transient — retryable).
``resource``
    raises :class:`InjectedResourceExhausted` (``RESOURCE_EXHAUSTED``, the
    allocator-failure shape; classified fatal — retrying an OOM repeats it).
``fatal``
    raises :class:`InjectedFault` (classified fatal).
``nan``
    no raise; the executor dispatch site poisons one floating feed with NaN
    so the ``PADDLE_TPU_CHECK_NUMERICS`` watchdog is driven end-to-end.
``latency``
    sleeps ``ms`` milliseconds at the site (deadline/timeout drills).
``exhausted``
    the ``page_pool.alloc`` site raises ``PagePoolExhausted`` (the serving
    backpressure drill) and ``serving.decode`` raises it as an
    exhaustion-shaped dispatch failure (batch eviction); sites without a
    pool ignore it — arm ``resource`` there instead.

:func:`classify` is the one retry-policy oracle the supervisor and the
serving engine share: an exception is ``"preemption"``, ``"transient"``,
``"backpressure"`` or ``"fatal"``.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..monitor import metrics as _mx

__all__ = [
    "FaultPlan", "FaultSpec", "InjectedFault", "TransientFault",
    "InjectedResourceExhausted", "PreemptionRequested",
    "SITES", "KINDS", "install", "clear", "current_plan", "poll",
    "fire", "poison_feeds", "classify",
]

SITES = ("executor.dispatch", "executor.compile", "serving.decode",
         "io.save_checkpoint", "page_pool.alloc")
KINDS = ("preempt", "transient", "resource", "fatal", "nan", "latency",
         "exhausted")

_m_injected = _mx.counter(
    "reliability/faults_injected",
    help="faults fired by the active FaultPlan, all sites")
_m_feed_errors = _mx.counter(
    "reliability/feed_errors",
    help="typed executor.FeedError raises (feed source failed mid-chunk) — "
         "the data-side failure signal SLOs and dashboards watch")


def record_feed_error() -> None:
    """Tick ``reliability/feed_errors`` (called by the executor's typed
    FeedError paths, so data-pipeline failures are visible to telemetry,
    not just the flight recorder)."""
    _m_feed_errors.inc()


class InjectedFault(RuntimeError):
    """A deliberately injected failure (chaos drills). ``classify`` treats
    the base class as fatal; subclasses refine."""


class TransientFault(InjectedFault):
    """Injected failure of the kind that retry-with-backoff should absorb
    (flaky dispatch, dropped RPC, transient runtime hiccup)."""


class InjectedResourceExhausted(InjectedFault):
    """Injected RESOURCE_EXHAUSTED — the allocator-failure shape. Fatal to
    a retry loop (the same step will OOM again)."""


class PreemptionRequested(BaseException):
    """Raised by the supervisor's signal handler path when preemption must
    interrupt host-side work. ``BaseException`` so a broad ``except
    Exception`` retry loop can never swallow a preemption notice."""


class FaultSpec:
    """One armed site: fire ``kind`` on visits [at, at+times) (1-based)."""

    __slots__ = ("site", "kind", "at", "times", "ms", "p")

    def __init__(self, site: str, kind: str, at: int = 1, times: int = 1,
                 ms: float = 0.0, p: Optional[float] = None):
        if site not in SITES:
            raise ValueError("unknown fault site %r (sites: %s)"
                             % (site, ", ".join(SITES)))
        if kind not in KINDS:
            raise ValueError("unknown fault kind %r (kinds: %s)"
                             % (kind, ", ".join(KINDS)))
        if at < 1 or times < 1:
            raise ValueError("at/times are 1-based positive counts")
        self.site = site
        self.kind = kind
        self.at = int(at)
        self.times = int(times)
        self.ms = float(ms)
        # Probabilistic arming (programmatic only — the env grammar is
        # deterministic by design): fire with probability p per visit,
        # drawn from the plan's seeded RNG so a drill replays identically
        # for the same seed.
        self.p = p

    def __repr__(self):
        return ("FaultSpec(%s@%d=%s:%d%s)"
                % (self.site, self.at, self.kind, self.times,
                   ":%gms" % self.ms if self.ms else ""))


_ENTRY_RE = re.compile(
    r"^(?P<site>[\w.]+)@(?P<at>\d+)=(?P<kind>\w+)"
    r"(?::(?P<times>\d+))?(?::(?P<ms>\d+(?:\.\d+)?))?$")


class FaultPlan:
    """A deterministic, seedable schedule of faults. Thread-safe visit
    counting so serving/executor threads share one plan."""

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self._hits: Dict[str, int] = {}
        self._fired = 0
        self._lock = threading.Lock()
        self._rng = None  # built lazily, only for probabilistic entries

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        specs = []
        for raw in text.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if m is None:
                raise ValueError(
                    "bad fault-plan entry %r (grammar: site@N=kind[:times"
                    "[:ms]])" % raw)
            specs.append(FaultSpec(
                m.group("site"), m.group("kind"), at=int(m.group("at")),
                times=int(m.group("times") or 1),
                ms=float(m.group("ms") or 0.0)))
        return cls(specs, seed=seed)

    # -- introspection --------------------------------------------------------
    @property
    def fired(self) -> int:
        return self._fired

    def hits(self, site: str) -> int:
        return self._hits.get(site, 0)

    # -- the site-facing poll -------------------------------------------------
    def poll(self, site: str) -> Optional[FaultSpec]:
        """Count one visit to ``site``; return the armed spec if a fault
        fires on this visit, else None."""
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            for spec in self.specs:
                if spec.site != site:
                    continue
                if spec.p is not None:
                    if self._rng is None:
                        import numpy as np

                        self._rng = np.random.RandomState(self.seed)
                    if float(self._rng.random_sample()) < spec.p:
                        self._fired += 1
                        _m_injected.inc()
                        return spec
                    continue
                if spec.at <= n < spec.at + spec.times:
                    self._fired += 1
                    _m_injected.inc()
                    return spec
        return None

    # -- installation ---------------------------------------------------------
    def __enter__(self):
        install(self)
        return self

    def __exit__(self, *exc):
        clear()
        return False


_plan: Optional[FaultPlan] = None
_env_cache = (None, None)  # (env string, parsed plan)


def install(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide (wins over the env plan)."""
    global _plan
    _plan = plan
    return plan


def clear() -> None:
    global _plan
    _plan = None


def current_plan() -> Optional[FaultPlan]:
    """The installed plan, else the (cached) ``PADDLE_TPU_FAULT_PLAN`` env
    plan, else None. The None fast path is one global load + env read."""
    global _env_cache
    if _plan is not None:
        return _plan
    text = os.environ.get("PADDLE_TPU_FAULT_PLAN")
    if not text:
        return None
    if _env_cache[0] != text:
        _env_cache = (text, FaultPlan.parse(text))
    return _env_cache[1]


def poll(site: str) -> Optional[FaultSpec]:
    """Visit ``site``; returns the firing spec or None. The no-plan fast
    path is the single branch every chokepoint pays."""
    plan = current_plan()
    if plan is None:
        return None
    return plan.poll(site)


def fire(site: str) -> Optional[FaultSpec]:
    """Poll ``site`` and ACT on raise/sleep/signal kinds; returns the spec
    for kinds the call site must handle itself (``nan``, ``exhausted``) or
    None. The uniform chokepoint entry for sites without special kinds."""
    spec = poll(site)
    if spec is None:
        return None
    return act(spec, site)


def act(spec: FaultSpec, site: str) -> Optional[FaultSpec]:
    """Perform ``spec``'s generic action (raise / sleep / SIGTERM); hand
    back specs whose effect is site-specific."""
    if spec.kind == "latency":
        time.sleep(spec.ms / 1e3 if spec.ms else 0.01)
        return None
    if spec.kind == "preempt":
        import signal

        os.kill(os.getpid(), signal.SIGTERM)
        return None
    if spec.kind == "transient":
        raise TransientFault(
            "injected transient fault at %s (visit %d)" % (site, spec.at))
    if spec.kind == "resource":
        raise InjectedResourceExhausted(
            "RESOURCE_EXHAUSTED: injected allocator failure at %s" % site)
    if spec.kind == "fatal":
        raise InjectedFault("injected fatal fault at %s" % site)
    return spec  # nan / exhausted: the call site owns the effect


def poison_feeds(feeds: dict) -> dict:
    """The ``nan`` fault effect at the executor dispatch site: return a
    copy of ``feeds`` with one floating entry's first element NaN'd, so the
    numerics watchdog sees a non-finite value born at a real op."""
    import numpy as np

    out = dict(feeds)
    for name in sorted(out):
        v = np.asarray(out[name])
        if np.issubdtype(v.dtype, np.floating):
            v = v.copy()
            v.ravel()[0] = np.nan
            out[name] = v
            return out
    return out


_TRANSIENT_MSG = re.compile(
    r"UNAVAILABLE|ABORTED|DATA_LOSS|connection reset|socket closed|"
    r"injected transient", re.IGNORECASE)


def classify(exc: BaseException) -> str:
    """Retry-policy oracle: ``"preemption"`` | ``"transient"`` |
    ``"backpressure"`` | ``"fatal"``. Message heuristics cover runtime
    errors that arrive as bare ``XlaRuntimeError``/``RuntimeError``."""
    if isinstance(exc, (KeyboardInterrupt, PreemptionRequested)):
        return "preemption"
    if isinstance(exc, TransientFault):
        return "transient"
    if isinstance(exc, InjectedFault):  # resource / fatal
        return "fatal"
    try:  # lazy: serving must stay importable without reliability and v.v.
        from ..serving.request import BackpressureError

        if isinstance(exc, BackpressureError):
            return "backpressure"
    except Exception:
        pass
    if _TRANSIENT_MSG.search(str(exc)):
        return "transient"
    return "fatal"
