"""Preemption-aware training supervisor: checkpoint, resume, retry, heal.

:func:`run_supervised` wraps ``Executor.run_steps`` with the production
lifecycle the bare driver lacks:

* **Preemption**: SIGTERM/SIGINT set a flag; the in-flight fused chunk
  finishes, a rotating checkpoint is written (``io.save_checkpoint``), and
  the process exits with :data:`EXIT_PREEMPTED` (or the call returns with
  ``result.preempted`` when ``exit_on_preempt=False``) — the contract a
  cloud scheduler's preemption notice expects.
* **Auto-checkpoint**: every ``checkpoint_every_steps`` steps and/or
  ``checkpoint_every_s`` seconds.
* **Auto-resume**: on entry the latest complete checkpoint is restored
  (``io.load_checkpoint``), the per-step RNG counter is rewound to the
  checkpointed step (so dropout masks and every other per-step stream
  continue bit-identically), and the DATA STREAM rewinds with it: a
  checkpointable feed source (``paddle_tpu.data.CheckpointableReader`` or
  anything with ``state_dict``/``load_state_dict``) has its position
  persisted inside every checkpoint and restored here — exactly-once
  record consumption across kill/resume with **no caller bookkeeping**.
  The legacy ``feed_source(start_step) -> iterator`` callable contract is
  kept for back-compat.
* **Retry**: a failed chunk is classified (:func:`~.faults.classify`);
  transient failures retry with exponential backoff — now with
  deterministic seeded jitter (:func:`backoff_schedule`; restart-storm
  avoidance) — up to ``max_retries`` (the RNG step counter is rewound
  first, so a retried chunk replays the exact streams of the failed
  attempt); fatal failures record a supervisor event in the flight
  recorder and re-raise.
* **Self-healing** (``sentinel=``): a
  :class:`~.sentinel.DivergenceSentinel` evaluates every chunk's fetched
  losses (and the numerics watchdog's typed exception) against its rules;
  a trip rolls the run back to the last good checkpoint — model, RNG
  counter and reader position together — quarantines the offending data
  window through the reader, optionally backs off LR, and resumes. The
  rollback budget is bounded; exhaustion or a repeat trip at the same
  step raises :class:`~.sentinel.SentinelFatal` with the flight-recorder
  dump carrying the watchdog-named op.
"""

from __future__ import annotations

import signal
import sys
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..monitor import device as _dev, metrics as _mx, telemetry as _telemetry
from . import faults as _faults

__all__ = ["EXIT_PREEMPTED", "SupervisorResult", "run_supervised",
           "backoff_schedule"]

#: Marked exit code for a preemption-triggered checkpoint-and-exit — the
#: restart policy treats it as "resume me", unlike a crash code.
EXIT_PREEMPTED = 42

_m_preempt = _mx.counter("reliability/preemptions",
                         help="preemption notices honored (checkpoint+exit)")
_m_ckpt = _mx.counter("reliability/checkpoints_written",
                      help="rotating checkpoints written by the supervisor")
_m_resume = _mx.counter("reliability/resumes",
                        help="supervised runs that restored a checkpoint")
_m_retry = _mx.counter("reliability/retries",
                       help="transient chunk failures absorbed by retry")


def backoff_schedule(base_s: float, retries: int, seed: int = 0
                     ) -> List[float]:
    """The retry sleep schedule: exponential with deterministic seeded
    multiplicative jitter in ``[0.5, 1.0)`` per attempt. Jitter decorrelates
    a fleet of restarting workers (restart-storm avoidance) while staying
    byte-reproducible for a fixed seed — the drill's replay contract."""
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    return [base_s * (2 ** a) * (0.5 + 0.5 * float(rng.random_sample()))
            for a in range(max(0, int(retries)))]


def _quiesce_scope(scope) -> None:
    """Block until every jax array in ``scope`` (the fused chunk's carry)
    has materialized. Called before a rollback replaces live state: the
    tripping chunk's dispatch may still be executing asynchronously, and
    overwriting (then GC-ing) its carry mid-flight is exactly the
    lifetime hazard the restore path must not introduce."""
    import jax

    jax.block_until_ready([
        v for v in (scope.find_var(n) for n in list(scope.vars))
        if isinstance(v, jax.Array)])


def _is_reader_source(src) -> bool:
    """A checkpointable feed source: iterable with a serializable
    position. (The legacy contract is a CALLABLE ``feed_source(start)``.)"""
    return (hasattr(src, "state_dict") and hasattr(src, "load_state_dict")
            and hasattr(src, "__next__"))


class SupervisorResult:
    """Outcome of one :func:`run_supervised` invocation."""

    __slots__ = ("steps_done", "start_step", "resumed", "preempted",
                 "losses", "checkpoints_written", "retries", "last_serial",
                 "trips", "rollbacks", "records_quarantined")

    def __init__(self):
        self.steps_done = 0        # global step index reached
        self.start_step = 0        # where this invocation began (resume point)
        self.resumed = False
        self.preempted = False
        self.losses: List[Any] = []  # one fetch row per step run HERE
        self.checkpoints_written = 0
        self.retries = 0
        self.last_serial: Optional[int] = None
        self.trips: List[Any] = []   # SentinelTrip records, in trip order
        self.rollbacks = 0
        self.records_quarantined = 0

    def __repr__(self):
        return ("SupervisorResult(steps=%d from %d, resumed=%s, preempted=%s,"
                " ckpts=%d, retries=%d, trips=%d, rollbacks=%d)"
                % (self.steps_done, self.start_step, self.resumed,
                   self.preempted, self.checkpoints_written, self.retries,
                   len(self.trips), self.rollbacks))


def run_supervised(
    exe,
    program,
    feed_source,
    total_steps: int,
    fetch_list: Optional[Sequence] = None,
    *,
    checkpoint_dir: str,
    fetch_every: int = 1,
    checkpoint_every_steps: int = 0,
    checkpoint_every_s: float = 0.0,
    max_retries: int = 3,
    backoff_s: float = 0.05,
    backoff_seed: Optional[int] = None,
    trainer_id: int = 0,
    max_num_checkpoints: int = 3,
    exit_on_preempt: bool = True,
    install_signal_handlers: bool = True,
    sentinel=None,
    on_chunk: Optional[Callable[[int, List[Any]], None]] = None,
) -> SupervisorResult:
    """Drive ``total_steps`` training steps with preemption handling,
    rotating checkpoints, auto-resume, bounded jittered retry and
    (optionally) sentinel-guarded rollback healing.

    ``feed_source`` is either the legacy callable — ``feed_source(start)``
    returns an iterator of per-step feed dicts beginning at global step
    ``start`` — or a checkpointable reader (``state_dict`` /
    ``load_state_dict`` / iteration): its position is folded into every
    checkpoint and restored on resume/rollback automatically. Fetches
    (``fetch_list``) come back in ``result.losses``, one numpy row per
    step executed by THIS call. ``on_chunk(start_step, rows)`` fires after
    every *committed* fused chunk (never for a chunk a sentinel trip threw
    away) — the hook progress ledgers and checkpoint-external bookkeeping
    ride on. ``backoff_seed`` seeds the retry jitter (default: the active
    fault plan's seed, else 0), see :func:`backoff_schedule`.
    """
    from .. import io as _io
    from ..core.scope import global_scope
    from . import sentinel as _sent  # typed fatals in the rollback path

    res = SupervisorResult()
    reader_mode = _is_reader_source(feed_source)
    args = _io.load_checkpoint(exe, checkpoint_dir, program)
    if args is not None:
        res.resumed = True
        res.start_step = int(args.get("step", 0))
        _m_resume.inc()
    start = res.start_step
    if reader_mode:
        state = (args or {}).get("data_reader")
        if state is not None:
            feed_source.load_state_dict(state)
        elif start > 0:
            # checkpoint predates reader-state payloads: fast-forward by
            # consuming `start` batches so at least the position matches
            # the step (logged — exactly-once needs the state payload)
            from ..log import vlog

            vlog(0, "run_supervised: checkpoint at step %d carries no "
                    "data_reader state; fast-forwarding the reader by "
                    "consuming %d batches", start, start)
            for _ in range(start):
                try:
                    next(feed_source)
                except StopIteration:
                    break
        it = iter(feed_source)
    else:
        it = iter(feed_source(start))
    # Rewind the per-step RNG counter to the resume point: the compiled step
    # folds this counter into every stochastic op's key, so restoring it is
    # what makes the resumed trajectory bit-identical, dropout included.
    program._tpu_step_counter = start
    res.steps_done = start

    preempt_flag = threading.Event()
    installed = []
    if install_signal_handlers and \
            threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            preempt_flag.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            installed.append((sig, signal.signal(sig, _on_signal)))

    def _checkpoint(step: int) -> None:
        targs = {"step": step}
        if reader_mode:
            targs["data_reader"] = feed_source.state_dict()
        serial = _io.save_checkpoint(
            exe, checkpoint_dir, program, trainer_id=trainer_id,
            trainer_args=targs, max_num_checkpoints=max_num_checkpoints)
        res.last_serial = serial
        res.checkpoints_written += 1
        _m_ckpt.inc()

    k = max(1, int(fetch_every))
    last_ckpt_step = start
    last_ckpt_t = time.monotonic()
    fr = _dev.flight_recorder()
    if backoff_seed is None:
        plan = _faults.current_plan()
        backoff_seed = plan.seed if plan is not None else 0
    sleeps = backoff_schedule(backoff_s, max_retries, seed=backoff_seed) \
        if backoff_s else [0.0] * max_retries

    if sentinel is not None and args is None:
        # the rollback floor: a trip before the first periodic checkpoint
        # must still have a known-good serial to return to
        _checkpoint(start)
        last_ckpt_step = start

    def _sentinel_rollback(trip, chunk_len: int) -> None:
        """Roll back to the last good checkpoint: model + optimizer state,
        RNG counter, reader position — then quarantine the tripping data
        window so the replay (and every later epoch) skips it."""
        chunk_start = res.steps_done
        try:
            sentinel.register_trip(chunk_start, trip)  # may raise Fatal
        except Exception as fatal:
            res.trips = list(sentinel.trips)
            if fr is not None:
                fr.record_event(
                    "sentinel_fatal", step=chunk_start, trip=trip.to_doc(),
                    trips=[t.to_doc() for t in sentinel.trips])
                try:  # the post-mortem artifact, watchdog-named op included
                    fr.dump("sentinel_fatal", fatal)
                except Exception:
                    pass  # an unwritable dir never masks the fatal
            raise
        res.trips = list(sentinel.trips)
        window_ids: List[str] = []
        if reader_mode and hasattr(feed_source, "last_batch_ids"):
            batches = feed_source.last_batch_ids(chunk_len)
            if len(batches) < chunk_len:
                from ..log import vlog

                vlog(0, "sentinel: id history holds %d of the %d tripping "
                        "batches — quarantining the known suffix only",
                     len(batches), chunk_len)
            window_ids = [rid for b in batches for rid in b]
        # Quiesce before the restore overwrites live device state: the
        # tripping chunk's dispatch may still be in flight, and replacing
        # (then GC-ing) its carry mid-execution races the async runtime.
        _quiesce_scope(global_scope())
        rb_args = _io.load_checkpoint(exe, checkpoint_dir, program)
        if rb_args is None:
            raise _sent.SentinelFatal(
                "sentinel: trip at step %d but no checkpoint to roll back "
                "to in %r (%s)" % (chunk_start, checkpoint_dir, trip.reason),
                sentinel.trips)
        good_step = int(rb_args.get("step", 0))
        if reader_mode:
            state = rb_args.get("data_reader")
            if state is not None:
                feed_source.load_state_dict(state)
            else:
                # a legacy serial (pre-reader-payload) can't rewind the
                # stream: the replay will train LATER records at earlier
                # steps — say so loudly instead of silently skewing
                from ..log import vlog

                vlog(0, "sentinel rollback: checkpoint serial at step %d "
                        "carries no data_reader state — the reader cannot "
                        "rewind, model and data stream are now skewed "
                        "(re-checkpoint with this build to heal)",
                     good_step)
            if window_ids and hasattr(feed_source, "quarantine"):
                feed_source.quarantine(
                    window_ids, "sentinel %s trip at step %d: %s"
                    % (trip.rule, chunk_start, trip.reason))
        else:
            nonlocal it
            it = iter(feed_source(good_step))
        program._tpu_step_counter = good_step
        del res.losses[good_step - res.start_step:]
        res.steps_done = good_step
        res.rollbacks += 1
        res.records_quarantined += len(window_ids)
        sentinel.record_rollback(len(window_ids))
        sentinel.apply_lr_backoff(global_scope())
        if fr is not None:
            fr.record_event(
                "sentinel_trip", step=chunk_start, rolled_back_to=good_step,
                trip=trip.to_doc(), quarantined=len(window_ids))
        nonlocal last_ckpt_step, last_ckpt_t
        last_ckpt_step = good_step
        last_ckpt_t = time.monotonic()

    # continuous telemetry rides the supervised run's lifetime: the JSONL
    # ring streams while training, and the final release (in the finally
    # below) flushes the last PARTIAL interval so a preempted or failed
    # run still leaves a complete series (PADDLE_TPU_TELEMETRY_DIR unset
    # = one env read, telemetry_handle stays None).
    telemetry_handle = _telemetry.acquire()
    try:
        while res.steps_done < total_steps and not preempt_flag.is_set():
            want = min(k, total_steps - res.steps_done)
            chunk = []
            while len(chunk) < want:
                try:
                    chunk.append(next(it))
                except StopIteration:
                    break
            if not chunk:
                break  # feed source exhausted before total_steps

            counter0 = getattr(program, "_tpu_step_counter", res.steps_done)
            attempt = 0
            rows = None
            while True:
                try:
                    rows = exe.run_steps(
                        program, iter(chunk), steps=len(chunk),
                        fetch_list=fetch_list, fetch_every=len(chunk))
                    break
                except Exception as e:
                    trip = sentinel.check_exception(e) \
                        if sentinel is not None else None
                    if trip is not None:
                        _sentinel_rollback(trip, len(chunk))
                        break  # rows stays None: chunk discarded
                    kind = _faults.classify(e)
                    if kind == "transient" and attempt < max_retries:
                        attempt += 1
                        res.retries += 1
                        _m_retry.inc()
                        # rewind the RNG counter a partially-dispatched
                        # chunk may have advanced: the retry must replay
                        # the SAME per-step streams
                        program._tpu_step_counter = counter0
                        if sleeps[attempt - 1]:
                            time.sleep(sleeps[attempt - 1])
                        continue
                    if fr is None:
                        fr = _dev.flight_recorder()
                    if fr is not None:
                        fr.record_event(
                            "supervisor_fatal", step=res.steps_done,
                            classified=kind, attempts=attempt,
                            error="%s: %s" % (type(e).__name__, e))
                    raise
            if rows is None:
                continue  # sentinel rolled back on the exception path
            if sentinel is not None:
                # only the trailing rule window, not O(steps-so-far)
                tail = res.losses[-sentinel.history_window():]
                history = [sentinel._loss(r) for r in tail]
                trip = sentinel.check_rows(rows, history)
                if trip is not None:
                    _sentinel_rollback(trip, len(chunk))
                    continue
            res.losses.extend(rows)
            chunk_start = res.steps_done
            res.steps_done += len(chunk)
            if on_chunk is not None:
                on_chunk(chunk_start, rows)

            due = False
            if checkpoint_every_steps and \
                    res.steps_done - last_ckpt_step >= checkpoint_every_steps:
                due = True
            if checkpoint_every_s and \
                    time.monotonic() - last_ckpt_t >= checkpoint_every_s:
                due = True
            if due and res.steps_done < total_steps:
                _checkpoint(res.steps_done)
                last_ckpt_step = res.steps_done
                last_ckpt_t = time.monotonic()

        if preempt_flag.is_set() and res.steps_done < total_steps:
            res.preempted = True
            _m_preempt.inc()
            if res.steps_done != last_ckpt_step:
                # skip the write when the periodic checkpoint already
                # covered this exact step — no duplicate serial
                _checkpoint(res.steps_done)
            if fr is not None:
                fr.record_event("supervisor_preempted",
                                step=res.steps_done,
                                serial=res.last_serial)
    finally:
        _telemetry.release(telemetry_handle)
        for sig, prev in installed:
            signal.signal(sig, prev)

    if res.preempted and exit_on_preempt:
        sys.exit(EXIT_PREEMPTED)
    return res
