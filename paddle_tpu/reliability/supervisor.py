"""Preemption-aware training supervisor: checkpoint, resume, retry.

:func:`run_supervised` wraps ``Executor.run_steps`` with the production
lifecycle the bare driver lacks:

* **Preemption**: SIGTERM/SIGINT set a flag; the in-flight fused chunk
  finishes, a rotating checkpoint is written (``io.save_checkpoint``), and
  the process exits with :data:`EXIT_PREEMPTED` (or the call returns with
  ``result.preempted`` when ``exit_on_preempt=False``) — the contract a
  cloud scheduler's preemption notice expects.
* **Auto-checkpoint**: every ``checkpoint_every_steps`` steps and/or
  ``checkpoint_every_s`` seconds.
* **Auto-resume**: on entry the latest complete checkpoint is restored
  (``io.load_checkpoint``), the per-step RNG counter is rewound to the
  checkpointed step (so dropout masks and every other per-step stream
  continue bit-identically), and the step offset is handed back to the
  caller's ``feed_source`` so the data stream resumes in place — the
  kill/resume drill asserts the resumed loss trajectory is bit-identical
  to an uninterrupted run.
* **Retry**: a failed chunk is classified (:func:`~.faults.classify`);
  transient failures retry with exponential backoff up to ``max_retries``
  (the RNG step counter is rewound first, so a retried chunk replays the
  exact streams of the failed attempt); fatal failures record a
  supervisor event in the flight recorder and re-raise.

The feed contract: ``feed_source(start_step)`` returns an iterator yielding
one feed dict per step **starting at global step** ``start_step`` — the
supervisor materializes each fused chunk before dispatching it, so a
transient failure can replay the chunk without re-pulling data.
"""

from __future__ import annotations

import signal
import sys
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from ..monitor import device as _dev, metrics as _mx, telemetry as _telemetry
from . import faults as _faults

__all__ = ["EXIT_PREEMPTED", "SupervisorResult", "run_supervised"]

#: Marked exit code for a preemption-triggered checkpoint-and-exit — the
#: restart policy treats it as "resume me", unlike a crash code.
EXIT_PREEMPTED = 42

_m_preempt = _mx.counter("reliability/preemptions",
                         help="preemption notices honored (checkpoint+exit)")
_m_ckpt = _mx.counter("reliability/checkpoints_written",
                      help="rotating checkpoints written by the supervisor")
_m_resume = _mx.counter("reliability/resumes",
                        help="supervised runs that restored a checkpoint")
_m_retry = _mx.counter("reliability/retries",
                       help="transient chunk failures absorbed by retry")


class SupervisorResult:
    """Outcome of one :func:`run_supervised` invocation."""

    __slots__ = ("steps_done", "start_step", "resumed", "preempted",
                 "losses", "checkpoints_written", "retries", "last_serial")

    def __init__(self):
        self.steps_done = 0        # global step index reached
        self.start_step = 0        # where this invocation began (resume point)
        self.resumed = False
        self.preempted = False
        self.losses: List[Any] = []  # one fetch row per step run HERE
        self.checkpoints_written = 0
        self.retries = 0
        self.last_serial: Optional[int] = None

    def __repr__(self):
        return ("SupervisorResult(steps=%d from %d, resumed=%s, preempted=%s,"
                " ckpts=%d, retries=%d)"
                % (self.steps_done, self.start_step, self.resumed,
                   self.preempted, self.checkpoints_written, self.retries))


def run_supervised(
    exe,
    program,
    feed_source: Callable[[int], Any],
    total_steps: int,
    fetch_list: Optional[Sequence] = None,
    *,
    checkpoint_dir: str,
    fetch_every: int = 1,
    checkpoint_every_steps: int = 0,
    checkpoint_every_s: float = 0.0,
    max_retries: int = 3,
    backoff_s: float = 0.05,
    trainer_id: int = 0,
    max_num_checkpoints: int = 3,
    exit_on_preempt: bool = True,
    install_signal_handlers: bool = True,
) -> SupervisorResult:
    """Drive ``total_steps`` training steps with preemption handling,
    rotating checkpoints, auto-resume and bounded transient retry.

    ``feed_source(start_step)`` must return an iterator of per-step feed
    dicts beginning at ``start_step``. Fetches (``fetch_list``) come back
    in ``result.losses``, one numpy row per step executed by THIS call
    (resumed steps before ``start_step`` belong to the previous life).
    """
    from .. import io as _io

    res = SupervisorResult()
    args = _io.load_checkpoint(exe, checkpoint_dir, program)
    if args is not None:
        res.resumed = True
        res.start_step = int(args.get("step", 0))
        _m_resume.inc()
    start = res.start_step
    # Rewind the per-step RNG counter to the resume point: the compiled step
    # folds this counter into every stochastic op's key, so restoring it is
    # what makes the resumed trajectory bit-identical, dropout included.
    program._tpu_step_counter = start
    res.steps_done = start

    preempt_flag = threading.Event()
    installed = []
    if install_signal_handlers and \
            threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            preempt_flag.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            installed.append((sig, signal.signal(sig, _on_signal)))

    def _checkpoint(step: int) -> None:
        serial = _io.save_checkpoint(
            exe, checkpoint_dir, program, trainer_id=trainer_id,
            trainer_args={"step": step},
            max_num_checkpoints=max_num_checkpoints)
        res.last_serial = serial
        res.checkpoints_written += 1
        _m_ckpt.inc()

    it = iter(feed_source(start))
    k = max(1, int(fetch_every))
    last_ckpt_step = start
    last_ckpt_t = time.monotonic()
    fr = _dev.flight_recorder()
    # continuous telemetry rides the supervised run's lifetime: the JSONL
    # ring streams while training, and the final release (in the finally
    # below) flushes the last PARTIAL interval so a preempted or failed
    # run still leaves a complete series (PADDLE_TPU_TELEMETRY_DIR unset
    # = one env read, telemetry_handle stays None).
    telemetry_handle = _telemetry.acquire()
    try:
        while res.steps_done < total_steps and not preempt_flag.is_set():
            want = min(k, total_steps - res.steps_done)
            chunk = []
            while len(chunk) < want:
                try:
                    chunk.append(next(it))
                except StopIteration:
                    break
            if not chunk:
                break  # feed source exhausted before total_steps

            counter0 = getattr(program, "_tpu_step_counter", res.steps_done)
            attempt = 0
            while True:
                try:
                    rows = exe.run_steps(
                        program, iter(chunk), steps=len(chunk),
                        fetch_list=fetch_list, fetch_every=len(chunk))
                    break
                except Exception as e:
                    kind = _faults.classify(e)
                    if kind == "transient" and attempt < max_retries:
                        attempt += 1
                        res.retries += 1
                        _m_retry.inc()
                        # rewind the RNG counter a partially-dispatched
                        # chunk may have advanced: the retry must replay
                        # the SAME per-step streams
                        program._tpu_step_counter = counter0
                        if backoff_s:
                            time.sleep(backoff_s * (2 ** (attempt - 1)))
                        continue
                    if fr is None:
                        fr = _dev.flight_recorder()
                    if fr is not None:
                        fr.record_event(
                            "supervisor_fatal", step=res.steps_done,
                            classified=kind, attempts=attempt,
                            error="%s: %s" % (type(e).__name__, e))
                    raise
            res.losses.extend(rows)
            res.steps_done += len(chunk)

            due = False
            if checkpoint_every_steps and \
                    res.steps_done - last_ckpt_step >= checkpoint_every_steps:
                due = True
            if checkpoint_every_s and \
                    time.monotonic() - last_ckpt_t >= checkpoint_every_s:
                due = True
            if due and res.steps_done < total_steps:
                _checkpoint(res.steps_done)
                last_ckpt_step = res.steps_done
                last_ckpt_t = time.monotonic()

        if preempt_flag.is_set() and res.steps_done < total_steps:
            res.preempted = True
            _m_preempt.inc()
            if res.steps_done != last_ckpt_step:
                # skip the write when the periodic checkpoint already
                # covered this exact step — no duplicate serial
                _checkpoint(res.steps_done)
            if fr is not None:
                fr.record_event("supervisor_preempted",
                                step=res.steps_done,
                                serial=res.last_serial)
    finally:
        _telemetry.release(telemetry_handle)
        for sig, prev in installed:
            signal.signal(sig, prev)

    if res.preempted and exit_on_preempt:
        sys.exit(EXIT_PREEMPTED)
    return res
