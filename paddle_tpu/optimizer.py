"""Optimizers (reference: python/paddle/fluid/optimizer.py:44-1484).

Same structure as Fluid: ``minimize`` = ``backward`` (append_backward) +
``apply_gradients`` (regularization + clip + per-param optimize ops appended
to the program after the backward marker). The optimize ops are functional
JAX updates (paddle_tpu/ops/optimizer_ops.py) that XLA fuses into the step.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from . import initializer as init_mod
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .core import unique_name
from .core.framework import Parameter, Program, Variable, default_main_program, default_startup_program
from .layers.layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    "SGD",
    "Momentum",
    "Adagrad",
    "Adam",
    "Adamax",
    "AdamW",
    "DecayedAdagrad",
    "Adadelta",
    "RMSProp",
    "Ftrl",
    "Lamb",
    "LarsMomentum",
    "ProximalGD",
    "ProximalAdagrad",
    "SGDOptimizer",
    "MomentumOptimizer",
    "AdagradOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "DecayedAdagradOptimizer",
    "AdadeltaOptimizer",
    "RMSPropOptimizer",
    "FtrlOptimizer",
    "LambOptimizer",
    "LarsMomentumOptimizer",
    "ProximalGDOptimizer",
    "ProximalAdagradOptimizer",
    "ModelAverage",
    "Optimizer",
]


class _EagerSlot:
    """Mutable array holder for imperative-mode accumulators/LR (duck-typed
    like VarBase for EagerBlock's in-place output writes)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class Optimizer:
    """Base optimizer (reference: optimizer.py:44)."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_var: Optional[Variable] = None
        # accumulators: {acc_name: {param_name: var}}
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self.helper: Optional[LayerHelper] = None
        self.type = getattr(self, "type", "optimizer")

    # -- learning rate --------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            main = default_main_program()
            if main._lr_var_name is None:
                main._lr_var_name = self._learning_rate.name
            return
        if self._learning_rate_var is None:
            from .layers import tensor as tensor_layers

            self._learning_rate_var = tensor_layers.create_global_var(
                shape=[1],
                value=float(self._learning_rate),
                dtype="float32",
                persistable=True,
                name=unique_name.generate("learning_rate"),
            )
            default_main_program()._lr_var_name = self._learning_rate_var.name

    def _global_learning_rate(self) -> Variable:
        return self._learning_rate_var

    @property
    def learning_rate(self):
        return self._learning_rate

    # -- accumulators ---------------------------------------------------------
    def _add_accumulator(self, name: str, param: Parameter, dtype=None, fill_value=0.0, shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        if getattr(self, "_imperative", False):
            import jax.numpy as jnp

            from .core.dtypes import to_jnp_dtype

            shape = tuple(shape if shape is not None else param.shape)
            slot = _EagerSlot(jnp.full(shape, float(fill_value),
                                       to_jnp_dtype(dtype or "float32")))
            self._accumulators.setdefault(name, {})[param.name] = slot
            return slot
        acc_name = unique_name.generate("%s_%s_%s" % (param.name, self.type, name))
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or "float32"
        helper = self.helper
        var = helper.create_or_get_global_variable(
            shape, dtype, acc_name, persistable=True,
            initializer=init_mod.Constant(float(fill_value)))
        # marks the var as per-param optimizer state so BuildStrategy's
        # ReduceStrategy.Reduce (ZeRO-1) can shard it over the data axis
        var.is_optimizer_state = True
        # a sharded parameter's same-shape accumulators (Adam moments on a
        # row-sharded embedding table) inherit its mesh layout: each device
        # holds V/n rows of param AND moments, and the startup twin carries
        # the annotation too so its fill_constant materializes shard-by-shard
        spec = getattr(param, "sharding", None)
        if spec is not None and list(shape) == list(param.shape):
            var.sharding = tuple(spec)
            sb = helper.startup_program.global_block
            if sb.has_var(acc_name):
                sb.var(acc_name).sharding = tuple(spec)
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name: str, param: Parameter) -> Variable:
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, parameters_and_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- the Fluid pipeline ---------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None,
                 callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks or [error_clip_callback])

    def apply_gradients(self, params_grads: List[Tuple[Parameter, Variable]]):
        """reference: optimizer.py:318 — clip, regularize, then optimize ops."""
        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        from . import monitor as _monitor

        if _monitor.grad_norm_enabled():
            self._append_grad_norm_probe(params_grads)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        return self._create_optimization_pass(params_grads)

    @staticmethod
    def _append_grad_norm_probe(params_grads):
        """PADDLE_TPU_GRAD_NORM=1: append ops writing the pre-clip global
        gradient norm into ``monitor.GRAD_NORM_VAR``; the Executor fetches
        it as a hidden extra and mirrors it into the
        ``optimizer/grad_global_norm`` gauge after each step. Deliberately
        NOT persistable: it is a per-step probe, not model state — keeping
        it out of the persistable set keeps it out of
        save/load_persistables checkpoints and out of the program-cache
        state signature. XLA fuses the reduction into the step, so the only
        added cost is the Executor's scalar fetch."""
        from . import monitor as _monitor

        grads = [g for p, g in params_grads
                 if g is not None and not getattr(p, "is_sparse_param", False)]
        if not grads:
            return
        block = grads[0].block
        if block.has_var(_monitor.GRAD_NORM_VAR):
            return  # one probe per program
        helper = LayerHelper("grad_norm_probe")
        sqs = []
        for g in grads:
            sq = helper.create_variable_for_type_inference(g.dtype)
            block.append_op("squared_l2_norm", inputs={"X": g},
                            outputs={"Out": sq})
            sqs.append(sq)
        gsum = helper.create_variable_for_type_inference("float32")
        block.append_op("sum", inputs={"X": sqs}, outputs={"Out": gsum})
        out = block.create_var(name=_monitor.GRAD_NORM_VAR, dtype="float32",
                               persistable=False)
        block.append_op("sqrt", inputs={"X": gsum}, outputs={"Out": out})

    def _create_optimization_pass(self, parameters_and_grads):
        """reference: optimizer.py:198."""
        program = default_main_program()
        block = program.global_block
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(block, [p for p, g in parameters_and_grads if g is not None])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                optimize_ops.append(self._append_optimize_op(block, param_and_grad))
        self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        """reference: optimizer.py:357. Ops are appended to the *loss's*
        program, not whatever default program is active at call time.

        In imperative (dygraph) mode the same per-optimizer update ops run
        eagerly instead (reference: optimizer.py minimize under
        _in_imperative_mode)."""
        from .imperative import base as _imp

        if _imp.enabled():
            return self._imperative_minimize(loss, parameter_list, no_grad_set)
        if getattr(self, "_imperative", False):
            raise RuntimeError(
                "This optimizer instance was used in imperative mode; its "
                "accumulators are eager arrays and cannot drive a static "
                "program. Create a fresh optimizer per mode.")
        from .core.framework import program_guard

        with program_guard(loss.block.program, startup_program):
            params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def _imperative_minimize(self, loss, parameter_list=None, no_grad_set=None):
        """Dygraph optimize step: run `_append_optimize_op` with an
        EagerBlock so every subclass's update math is reused unchanged.
        Accumulators live as eager arrays; in-place ParamOut writes go
        straight to the VarBase values. Gradient *clipping* is not wired in
        dygraph v0 (reference 1.x dygraph had the same gap)."""
        import jax.numpy as jnp

        from .imperative.tracer import EagerBlock, current_tracer

        if isinstance(self._learning_rate, Variable):
            raise NotImplementedError(
                "LR-scheduler Variables are a static-graph feature; use a "
                "float learning rate (optionally updated between steps) in "
                "imperative mode.")
        self._imperative = True
        no_grad = {getattr(v, "name", v) for v in (no_grad_set or ())}
        params = parameter_list if parameter_list is not None else current_tracer().parameters()
        params = sorted((p for p in params
                         if p.trainable and p._grad is not None and p.name not in no_grad),
                        key=lambda p: p.name)
        block = EagerBlock()
        self._create_accumulators(block, params)
        params_grads, ops = [], []
        for p in params:
            g = p._grad
            reg = getattr(p, "regularizer", None) or self.regularization
            if reg is not None:
                from .regularizer import L1DecayRegularizer, L2DecayRegularizer

                if isinstance(reg, L2DecayRegularizer):
                    g = g + reg._coeff * p.value
                elif isinstance(reg, L1DecayRegularizer):
                    g = g + reg._coeff * jnp.sign(p.value)
                else:
                    raise NotImplementedError("unsupported regularizer in dygraph: %r" % reg)
            params_grads.append((p, g))
            ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        return ops, params_grads

    def _lr_input(self, param=None):
        if getattr(self, "_imperative", False):
            import jax.numpy as jnp

            plr = 1.0 if param is None else getattr(
                param, "optimize_attr", {"learning_rate": 1.0}).get("learning_rate", 1.0)
            return _EagerSlot(jnp.full((1,), float(self._learning_rate) * float(plr),
                                       jnp.float32))
        lr = self._global_learning_rate()
        plr = 1.0
        if param is not None:
            plr = getattr(param, "optimize_attr", {"learning_rate": 1.0}).get("learning_rate", 1.0)
        if plr == 1.0:
            return lr
        from .layers import tensor as tensor_layers

        return tensor_layers.scale(lr, scale=float(plr))


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": p, "Grad": g, "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p},
        )


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p, dtype=p.dtype)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": p, "Grad": g, "Velocity": velocity, "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p, "VelocityOut": velocity},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(MomentumOptimizer):
    type = "lars_momentum"

    def __init__(self, learning_rate, momentum, lars_coeff=0.001, lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": p, "Grad": g, "Velocity": velocity, "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p, "VelocityOut": velocity},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            inputs={"Param": p, "Grad": g, "Moment": moment, "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p, "MomentOut": moment},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
        if not parameters:
            return
        if getattr(self, "_imperative", False):
            for p in parameters:
                self._add_accumulator("beta1_pow_acc", p,
                                      fill_value=self._beta1, shape=[1])
                self._add_accumulator("beta2_pow_acc", p,
                                      fill_value=self._beta2, shape=[1])
            return
        # ONE shared beta-pow pair for the whole parameter set (optax keeps a
        # single step count the same way). The reference's per-param [1]
        # scalars are always numerically identical, but 2 extra scalar state
        # vars PER PARAM give every Adam fusion a distinct operand set, which
        # blocks XLA's horizontal fusion of the ~1-per-param update kernels —
        # measured 10.6 ms/step of pure launch latency on BERT-base (133
        # params, r4). The single bump happens once in _finish_update, after
        # every param op has read the step-t value.
        p0 = parameters[0]
        b1 = self._add_accumulator("beta1_pow_acc", p0,
                                   fill_value=self._beta1, shape=[1])
        b2 = self._add_accumulator("beta2_pow_acc", p0,
                                   fill_value=self._beta2, shape=[1])
        for p in parameters[1:]:
            self._accumulators["beta1_pow_acc"][p.name] = b1
            self._accumulators["beta2_pow_acc"][p.name] = b2

    def _finish_update(self, block, parameters_and_grads):
        if getattr(self, "_imperative", False):
            return
        pows = self._accumulators.get("beta1_pow_acc")
        if not pows:
            return
        for acc_name, beta in (("beta1_pow_acc", self._beta1),
                               ("beta2_pow_acc", self._beta2)):
            accs = {v.name: v for v in self._accumulators[acc_name].values()}
            for var in accs.values():  # one shared var normally
                block.append_op("scale", inputs={"X": var},
                                outputs={"Out": var},
                                attrs={"scale": beta, "bias": 0.0})

    def _extra_attrs(self):
        """Attrs beyond plain Adam's (AdamW/Lamb decay). Must be supplied
        before append_op: in imperative mode the op executes immediately."""
        return {}

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        attrs = {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}
        attrs.update(self._extra_attrs())
        if getattr(self, "_imperative", False):
            outputs = {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
                       "Beta1PowOut": b1p, "Beta2PowOut": b2p}
        else:
            # pows are SHARED read-only here; _finish_update bumps them once
            outputs = {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2}
        return block.append_op(
            self.type,
            inputs={"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": self._lr_input(p)},
            outputs=outputs,
            attrs=attrs,
        )


class AdamWOptimizer(AdamOptimizer):
    type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._weight_decay = weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "adamax",
            inputs={"Param": p, "Grad": g,
                    "Moment": self._get_accumulator("moment", p),
                    "InfNorm": self._get_accumulator("inf_norm", p),
                    "Beta1Pow": self._get_accumulator("beta1_pow_acc", p),
                    "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p,
                     "MomentOut": self._get_accumulator("moment", p),
                     "InfNormOut": self._get_accumulator("inf_norm", p),
                     "Beta1PowOut": self._get_accumulator("beta1_pow_acc", p)},
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": p, "Grad": g, "Moment": moment, "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p, "MomentOut": moment},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "adadelta",
            inputs={"Param": p, "Grad": g,
                    "AvgSquaredGrad": self._get_accumulator("avg_squared_grad", p),
                    "AvgSquaredUpdate": self._get_accumulator("avg_squared_update", p),
                    "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p,
                     "AvgSquaredGradOut": self._get_accumulator("avg_squared_grad", p),
                     "AvgSquaredUpdateOut": self._get_accumulator("avg_squared_update", p)},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs = {"Param": p, "Grad": g,
                  "MeanSquare": self._get_accumulator("mean_square", p),
                  "Moment": self._get_accumulator("momentum", p),
                  "LearningRate": self._lr_input(p)}
        outputs = {"ParamOut": p,
                   "MeanSquareOut": self._get_accumulator("mean_square", p),
                   "MomentOut": self._get_accumulator("momentum", p)}
        if self._centered:
            inputs["MeanGrad"] = self._get_accumulator("mean_grad", p)
            outputs["MeanGradOut"] = self._get_accumulator("mean_grad", p)
        return block.append_op(
            "rmsprop", inputs=inputs, outputs=outputs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "ftrl",
            inputs={"Param": p, "Grad": g,
                    "SquaredAccumulator": self._get_accumulator("squared", p),
                    "LinearAccumulator": self._get_accumulator("linear", p),
                    "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p,
                     "SquaredAccumOut": self._get_accumulator("squared", p),
                     "LinearAccumOut": self._get_accumulator("linear", p)},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class ProximalGDOptimizer(Optimizer):
    """Proximal gradient descent with L1/L2 shrinkage (reference op:
    operators/optimizers/proximal_gd_op.cc; the reference's v1.3 Python
    layer never exposed it — this class completes the surface the same way
    the C++ op intended)."""

    type = "proximal_gd"

    def __init__(self, learning_rate, l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1 = l1_regularization_strength
        self._l2 = l2_regularization_strength

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "proximal_gd",
            inputs={"Param": p, "Grad": g, "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p},
            attrs={"l1": self._l1, "l2": self._l2},
        )


class ProximalAdagradOptimizer(Optimizer):
    """reference op: operators/optimizers/proximal_adagrad_op.cc."""

    type = "proximal_adagrad"

    def __init__(self, learning_rate, initial_accumulator_value=0.1,
                 l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._initial = initial_accumulator_value
        self._l1 = l1_regularization_strength
        self._l2 = l2_regularization_strength

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "proximal_adagrad",
            inputs={"Param": p, "Grad": g, "Moment": moment,
                    "LearningRate": self._lr_input(p)},
            outputs={"ParamOut": p, "MomentOut": moment},
            attrs={"l1": self._l1, "l2": self._l2},
        )


class LambOptimizer(AdamOptimizer):
    type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}


class ModelAverage(Optimizer):
    """Averaged-weights evaluation (reference: optimizer.py ModelAverage +
    operators/average_accumulates_op.cc): appends running-sum accumulate ops
    after the optimize ops; ``apply()`` temporarily swaps params for their
    window average, ``restore()`` (or leaving the context) swaps back."""

    type = "model_average"

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self.params_grads = []
        self._backup = {}
        main = default_main_program()
        self.helper = LayerHelper(self.__class__.__name__)
        for param in main.global_block.all_parameters():
            if not param.trainable:
                continue
            self._append_average_accumulate_op(param)
            self.params_grads.append(param)

    def _append_average_accumulate_op(self, param):
        block = default_main_program().global_block
        sum1 = self._add_accumulator("sum_1", param)
        sum2 = self._add_accumulator("sum_2", param)
        sum3 = self._add_accumulator("sum_3", param)
        n_acc = self._add_accumulator("num_accumulates", param, dtype="int64", shape=[1])
        old_n = self._add_accumulator("old_num_accumulates", param, dtype="int64", shape=[1])
        n_upd = self._add_accumulator("num_updates", param, dtype="int64", shape=[1])
        block.append_op(
            "average_accumulates",
            inputs={"Param": param, "InSum1": sum1, "InSum2": sum2,
                    "InSum3": sum3, "InNumAccumulates": n_acc,
                    "InOldNumAccumulates": old_n, "InNumUpdates": n_upd},
            outputs={"OutSum1": sum1, "OutSum2": sum2, "OutSum3": sum3,
                     "OutNumAccumulates": n_acc, "OutOldNumAccumulates": old_n,
                     "OutNumUpdates": n_upd},
            attrs={"average_window": self.average_window,
                   "max_average_window": self.max_average_window,
                   "min_average_window": self.min_average_window})

    def _averaged(self, scope, param):
        import numpy as _np

        g = lambda acc: _np.asarray(scope.find_var(
            self._accumulators[acc][param.name].name), dtype=_np.float64)
        total = g("sum_1") + g("sum_2") + g("sum_3")
        n = float(g("num_accumulates").reshape(())) + float(
            g("old_num_accumulates").reshape(()))
        return (total / max(n, 1.0)).astype("float32")

    def apply(self, executor=None, need_restore=True):
        """Context manager: params ← window average (reference:
        optimizer.py ModelAverage.apply)."""
        import contextlib

        from .core.scope import global_scope

        @contextlib.contextmanager
        def _ctx():
            scope = global_scope()
            self._backup = {}
            import numpy as _np

            for p in self.params_grads:
                self._backup[p.name] = _np.asarray(scope.find_var(p.name))
                scope.set_var(p.name, self._averaged(scope, p))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return _ctx()

    def restore(self, executor=None):
        from .core.scope import global_scope

        scope = global_scope()
        for name, val in self._backup.items():
            scope.set_var(name, val)
        self._backup = {}


# Fluid-style short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
