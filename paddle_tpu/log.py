"""VLOG-style logging (reference: glog VLOG(n) used throughout
paddle/fluid C++; controlled by the GLOG_v env var).

``vlog(level, msg)`` emits when ``GLOG_v >= level`` (same env contract as
the reference); ``get_logger`` returns a stdlib logger under the
``paddle_tpu`` namespace for structured use.
"""

from __future__ import annotations

import logging
import os
import sys

__all__ = ["get_logger", "vlog", "vlog_level"]

_root = logging.getLogger("paddle_tpu")
if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s %(message)s", "%H:%M:%S"))
    _root.addHandler(h)
    _root.setLevel(logging.INFO)


def vlog_level() -> int:
    try:
        return int(os.environ.get("GLOG_v", "0"))
    except ValueError:
        return 0


def get_logger(name: str = "") -> logging.Logger:
    return _root.getChild(name) if name else _root


def vlog(level: int, msg: str, *args):
    """reference: VLOG(level) << ... — prints iff GLOG_v >= level."""
    if vlog_level() >= level:
        _root.info("[VLOG%d] " + msg, level, *args)
