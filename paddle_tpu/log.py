"""VLOG-style logging (reference: glog VLOG(n) used throughout
paddle/fluid C++; controlled by the GLOG_v env var).

``vlog(level, msg)`` emits when ``GLOG_v >= level`` (same env contract as
the reference); ``get_logger`` returns a stdlib logger under the
``paddle_tpu`` namespace for structured use.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

__all__ = ["get_logger", "vlog", "vlog_level", "set_vlog_level"]

_root = logging.getLogger("paddle_tpu")
if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s %(message)s", "%H:%M:%S"))
    _root.addHandler(h)
    _root.setLevel(logging.INFO)

_vlog_level: Optional[int] = None  # parsed once; vlog() sits on hot paths


def vlog_level() -> int:
    global _vlog_level
    if _vlog_level is None:
        try:
            _vlog_level = int(os.environ.get("GLOG_v", "0"))
        except ValueError:
            _vlog_level = 0
    return _vlog_level


def set_vlog_level(level: Optional[int]) -> None:
    """Override (or with ``None``, re-read from ``GLOG_v``) the cached
    verbosity — for tests and runtime toggling."""
    global _vlog_level
    _vlog_level = None if level is None else int(level)


def get_logger(name: str = "") -> logging.Logger:
    return _root.getChild(name) if name else _root


def vlog(level: int, msg: str, *args):
    """reference: VLOG(level) << ... — prints iff GLOG_v >= level.

    ``msg`` is %-formatted against ``args`` only when the level is active;
    a literal ``%`` in a no-args message is safe (the level prefix is a
    separate format field, never concatenated into user text)."""
    if vlog_level() >= level:
        _root.info("[VLOG%d] %s", level, (msg % args) if args else msg)
