"""Op implementation library — importing this package registers all ops."""

from . import (  # noqa: F401
    activation_ops,
    attention_ops,
    beam_search_ops,
    compare_ops,
    control_flow_ops,
    detection_ops,
    extra_ops,
    gradient_ops,
    loss_ops,
    math_ops,
    metric_ops,
    misc_ops,
    nn_ops,
    optimizer_ops,
    quantize_ops,
    reduce_ops,
    rnn_ops,
    sequence_ops,
    tensor_ops,
    vision_ops,
)
