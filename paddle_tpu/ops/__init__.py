"""Op implementation library — importing this package registers all ops."""

from . import (  # noqa: F401
    activation_ops,
    attention_ops,
    compare_ops,
    math_ops,
    nn_ops,
    optimizer_ops,
    reduce_ops,
    tensor_ops,
)
