"""Beam search + TensorArray ops.

Reference: ``operators/beam_search_op.cc``, ``operators/math/beam_search.cu``,
``operators/beam_search_decode_op.cc`` and the TensorArray read/write ops
(``operators/controlflow/tensor_array_read_write_op.cc``,
``operators/lod_array_length_op.cc``).

TPU-native redesign:
- Fluid's beam search walks LoD levels per source sentence on the host;
  here one step is a fully batched top-k over ``[B, K·V]`` on the MXU/VPU —
  no ragged structures, the number of live beams is static.
- LoDTensorArray (dynamically growing list of tensors) becomes a
  pre-allocated ``[capacity, ...]`` ring buffer plus a write count, carried
  functionally as a ``(buffer, count)`` pytree — the only representation that
  composes with ``lax.while_loop``'s fixed carry structure. Writes are
  ``dynamic_update_index``; growth beyond capacity is an error the layer
  guards against, not a silent wrap.
- beam_search_decode backtracks parent pointers with a reversed ``lax.scan``
  over the static capacity, masking steps beyond the true length.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op

# sentinel env value for a created-but-never-written array
EMPTY_ARRAY = ("__empty_tensor_array__",)


@register_op("create_array")
def create_array_op(ctx: OpContext):
    ctx.set_output("Out", EMPTY_ARRAY)


@register_op("write_to_array")
def write_to_array_op(ctx: OpContext):
    x = ctx.input("X")
    i = ctx.input("I").reshape(()).astype(jnp.int32)
    arr = ctx.input("Array")
    capacity = int(ctx.attr("capacity", 512))
    if arr is None or (isinstance(arr, tuple) and arr == EMPTY_ARRAY):
        buf = jnp.zeros((capacity,) + tuple(x.shape), x.dtype)
        count = jnp.zeros((), jnp.int32)
    else:
        buf, count = arr
    # i is traced, so capacity can't be asserted at build time; XLA drops
    # out-of-bounds scatters, and we saturate the count to match so
    # array_length never claims elements that were not stored.
    buf = buf.at[i].set(x)
    count = jnp.minimum(jnp.maximum(count, i + 1), buf.shape[0])
    ctx.set_output("Out", (buf, count))


@register_op("read_from_array")
def read_from_array_op(ctx: OpContext):
    buf, _count = ctx.input("Array")
    i = ctx.input("I").reshape(()).astype(jnp.int32)
    ctx.set_output("Out", buf[i])


@register_op("lod_array_length")
def lod_array_length_op(ctx: OpContext):
    _buf, count = ctx.input("Array")
    ctx.set_output("Out", count.reshape(1).astype(jnp.int32))


@register_op("array_to_tensor")
def array_to_tensor_op(ctx: OpContext):
    """Stack a TensorArray into one tensor [capacity, ...] (the
    array_to_lod_tensor analog — here padding past the write count simply
    stays zero; the count is emitted for masking)."""
    buf, count = ctx.input("Array")
    ctx.set_output("Out", buf)
    ctx.set_output("OutIndex", count.reshape(1).astype(jnp.int32))


@register_op("beam_search")
def beam_search_op(ctx: OpContext):
    """One beam-search step, fully batched (reference: beam_search_op.cc).

    PreIds/PreScores [B, K]; Scores = per-step log-probs [B, K, V].
    Finished beams (pre_id == end_id) survive with unchanged score and emit
    end_id again; everything else expands to K·V candidates and the top K
    per batch row win. ParentIdx records which source beam each winner came
    from, for beam_search_decode's backtrack.
    """
    pre_ids = ctx.input("PreIds")
    pre_scores = ctx.input("PreScores")
    scores = ctx.input("Scores")
    end_id = int(ctx.attr("end_id", 0))
    B, K, V = scores.shape

    finished = pre_ids == end_id  # [B, K]
    neg_inf = jnp.asarray(-1e9, scores.dtype)
    if ctx.attr("is_accumulated", False):
        total = scores  # caller already folded pre_scores in
    else:
        total = pre_scores[..., None] + scores  # [B, K, V]
    # finished beams: single candidate (end_id, pre_score)
    total = jnp.where(finished[..., None], neg_inf, total)
    keep_end = jnp.zeros((B, K, V), bool).at[:, :, end_id].set(finished)
    total = jnp.where(keep_end, pre_scores[..., None], total)

    flat = total.reshape(B, K * V)
    top_scores, top_idx = jax.lax.top_k(flat, K)  # [B, K]
    sel_ids = (top_idx % V).astype(pre_ids.dtype)
    parent = (top_idx // V).astype(jnp.int32)
    ctx.set_output("SelectedIds", sel_ids)
    ctx.set_output("SelectedScores", top_scores)
    ctx.set_output("ParentIdx", parent)


@register_op("beam_search_decode")
def beam_search_decode_op(ctx: OpContext):
    """Backtrack stacked (ids, parents) into full sequences
    (reference: beam_search_decode_op.cc).

    Ids/Parents: TensorArray values ((buffer [cap,B,K], count)) or plain
    stacked [T,B,K] tensors. Outputs SentenceIds [B,K,T_cap] padded with
    end_id past each sequence's length, plus SentenceScores [B,K].
    """
    ids_in = ctx.input("Ids")
    parents_in = ctx.input("Parents")
    scores = ctx.input("Scores")
    end_id = int(ctx.attr("end_id", 0))

    if isinstance(ids_in, tuple):
        ids_buf, count = ids_in
    else:
        ids_buf, count = ids_in, jnp.asarray(ids_in.shape[0], jnp.int32)
    parents_buf = parents_in[0] if isinstance(parents_in, tuple) else parents_in

    cap, B, K = ids_buf.shape
    binds = jnp.arange(B)[:, None]  # [B,1] broadcast over K

    def back(cur, t):
        valid = t < count
        id_t = ids_buf[t][binds, cur]  # [B,K] gather by beam
        par_t = parents_buf[t][binds, cur]
        out = jnp.where(valid, id_t, jnp.asarray(end_id, id_t.dtype))
        cur = jnp.where(valid, par_t, cur)
        return cur, out

    init = jnp.tile(jnp.arange(K)[None, :], (B, 1)).astype(jnp.int32)
    _, outs = jax.lax.scan(back, init, jnp.arange(cap - 1, -1, -1))
    # outs is [cap, B, K] in reverse time order → [B, K, cap] forward
    sent = jnp.flip(outs, axis=0).transpose(1, 2, 0)
    ctx.set_output("SentenceIds", sent)
    ctx.set_output("SentenceScores", scores)


@register_op("tensor_array_to_tensor")
def tensor_array_to_tensor_op(ctx: OpContext):
    """Concatenate TensorArray entries along ``axis`` (reference:
    operators/tensor_array_to_tensor_op.cc). Static-shape contract (the
    padded+Length convention): the concat spans the array's full capacity —
    slots past the write count hold zeros — and OutIndex carries each
    entry's extent along the axis, 0 for unwritten slots, so consumers mask
    exactly like every Length-carrying op here. Size the array's capacity
    to the real entry count to avoid padding (create_array/array_write)."""
    buf, count = ctx.input("X")
    axis = ctx.attr("axis", 1)
    k = buf.shape[0]
    out = jnp.concatenate([buf[i] for i in range(k)], axis=axis)
    ctx.set_output("Out", out)
    extent = buf.shape[1:][axis]
    ctx.set_output("OutIndex",
                   jnp.where(jnp.arange(k) < count, extent, 0).astype(jnp.int32))
