"""Structured-loss tail: CTC, linear-chain CRF, NCE, hierarchical sigmoid,
sampled logits (reference: operators/warpctc_op.cc, ctc_align_op.cc,
linear_chain_crf_op.cc/.h, crf_decoding_op.cc, nce_op.cc,
hierarchical_sigmoid_op.cc + math/matrix_bit_code.h, sample_logits_op.cc).

TPU-first notes:
- Variable-length sequences use the framework's padded+Length convention
  (Logits [B, T, C] + Length [B]) instead of the reference's LoD packing;
  time recursions (CTC alpha, CRF forward/Viterbi) are ``lax.scan`` over the
  padded time axis with mask carries — one compiled kernel, no host loops.
- Gradients come from JAX AD through the scans (log-space, numerically
  stable), replacing the reference's hand-written grad kernels
  (warp-ctc library, LinearChainCrfGradOpKernel).
- Sampling ops (nce, sample_logits) draw from the per-op PRNG stream
  (ctx.rng()), static sample counts for fixed shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import OpContext, register_op

_NEG = -1e30


def _log_matvec(alpha, log_mat):
    """logsumexp_i(alpha_i + M_ij) — one CRF/HMM forward step."""
    return jax.scipy.special.logsumexp(alpha[:, None] + log_mat, axis=0)


# -- CTC ----------------------------------------------------------------------


def ctc_loss_padded(log_probs, labels, logit_lens, label_lens, blank=0):
    """CTC negative log-likelihood via the standard alpha recursion.

    log_probs [B, T, C] (log-softmax'd), labels [B, L] int32,
    logit_lens [B], label_lens [B] → loss [B]. reference: warpctc_op.cc
    (the warp-ctc library's forward pass), re-derived in log space.
    """
    b, t, c = log_probs.shape
    l = labels.shape[1]
    s = 2 * l + 1
    labels = labels.astype(jnp.int32)

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((b, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    # can skip from s-2 to s when ext[s] != ext[s-2] and ext[s] != blank
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s]
    can_skip = (ext != blank) & (ext != ext_prev2)

    valid_s = jnp.arange(s)[None, :] < (2 * label_lens[:, None] + 1)

    def step(alpha, lp_t):
        # alpha [B, S] log; lp_t [B, C]
        a0 = alpha
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :s]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :s]
        a2 = jnp.where(can_skip, a2, _NEG)
        m = jnp.maximum(jnp.maximum(a0, a1), a2)
        summed = (jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
        new = m + jnp.log(summed)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)  # [B, S]
        new = jnp.where(valid_s, new + emit, _NEG)
        return new, new

    init = jnp.full((b, s), _NEG)
    emit0 = jnp.take_along_axis(log_probs[:, 0], ext, axis=1)
    init = init.at[:, 0].set(emit0[:, 0])
    has_label = label_lens > 0
    init = init.at[:, 1].set(jnp.where(has_label, emit0[:, 1], _NEG))

    _, alphas = jax.lax.scan(step, init, jnp.swapaxes(log_probs[:, 1:], 0, 1))
    alphas = jnp.concatenate([init[None], alphas], axis=0)  # [T, B, S]

    # gather alpha at each sequence's last frame, positions 2L and 2L-1
    t_idx = jnp.clip(logit_lens - 1, 0, t - 1)
    last = alphas[t_idx, jnp.arange(b)]                      # [B, S]
    p_end = jnp.take_along_axis(last, (2 * label_lens)[:, None], axis=1)[:, 0]
    p_end1 = jnp.take_along_axis(
        last, jnp.maximum(2 * label_lens - 1, 0)[:, None], axis=1)[:, 0]
    p_end1 = jnp.where(has_label, p_end1, _NEG)
    m = jnp.maximum(p_end, p_end1)
    ll = m + jnp.log(jnp.exp(p_end - m) + jnp.exp(p_end1 - m))
    return -ll


@register_op("warpctc")
def warpctc_op(ctx: OpContext):
    """Logits [B, T, C] (+ LogitsLength [B]), Label [B, L] (+ LabelLength [B])
    → Loss [B, 1]. Logits are raw activations (softmax applied here, as
    warp-ctc does)."""
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    lg_len = ctx.input("LogitsLength")
    lb_len = ctx.input("LabelLength")
    blank = int(ctx.attr("blank", 0))
    b, t, _ = logits.shape
    if lg_len is None:
        lg_len = jnp.full((b,), t, jnp.int32)
    if lb_len is None:
        lb_len = jnp.full((b,), label.shape[1], jnp.int32)
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = ctc_loss_padded(log_probs, label, lg_len.astype(jnp.int32),
                           lb_len.astype(jnp.int32), blank)
    if ctx.attr("norm_by_times", False):
        loss = loss / jnp.maximum(lg_len.astype(loss.dtype), 1.0)
    ctx.set_output("Loss", loss[:, None].astype(logits.dtype))


@register_op("ctc_align")
def ctc_align_op(ctx: OpContext):
    """Greedy CTC collapse (reference: ctc_align_op.cc): merge repeats, drop
    blanks. Input [B, T] int + Length [B] → Output [B, T] padded with -1 +
    OutputLength [B]."""
    ids = ctx.input("Input").astype(jnp.int32)
    lens = ctx.input("Length")
    blank = int(ctx.attr("blank", 0))
    b, t = ids.shape
    if lens is None:
        lens = jnp.full((b,), t, jnp.int32)
    in_range = jnp.arange(t)[None, :] < lens.astype(jnp.int32)[:, None]
    prev = jnp.pad(ids, ((0, 0), (1, 0)), constant_values=-1)[:, :t]
    keep = (ids != blank) & (ids != prev) & in_range

    def one(row_ids, row_keep):
        pos = jnp.cumsum(row_keep) - 1
        out = jnp.full((t,), -1, jnp.int32)
        idx = jnp.where(row_keep, pos, t)  # dump discarded into a shadow slot
        out = jnp.zeros((t + 1,), jnp.int32).at[idx].set(row_ids)[:t]
        n = jnp.sum(row_keep.astype(jnp.int32))
        out = jnp.where(jnp.arange(t) < n, out, -1)
        return out, n

    out, n = jax.vmap(one)(ids, keep)
    ctx.set_output("Output", out)
    ctx.set_output("OutputLength", n)


# -- linear-chain CRF ---------------------------------------------------------


def _crf_unpack(transition):
    """Transition [D+2, D]: row0 = start, row1 = stop, rest = [D, D]
    (reference: linear_chain_crf_op.h layout)."""
    return transition[0], transition[1], transition[2:]


@register_op("linear_chain_crf")
def linear_chain_crf_op(ctx: OpContext):
    """Emission [B, T, D] + Length [B], Transition [D+2, D], Label [B, T] →
    LogLikelihood [B, 1]. reference: linear_chain_crf_op.cc (there per-LoD
    sequence on CPU; here one lax.scan over the padded batch)."""
    emission = ctx.input("Emission").astype(jnp.float32)
    transition = ctx.input("Transition").astype(jnp.float32)
    label = ctx.input("Label").astype(jnp.int32)
    length = ctx.input("Length")
    b, t, d = emission.shape
    if label.ndim == 3:
        label = label[..., 0]
    if length is None:
        length = jnp.full((b,), t, jnp.int32)
    length = length.astype(jnp.int32)
    start, stop, trans = _crf_unpack(transition)

    # ---- partition function via forward recursion
    def fwd(carry, xs):
        alpha, step = carry
        em_t, = xs
        new = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None], axis=1) + em_t
        active = (step < length)[:, None]
        new = jnp.where(active, new, alpha)
        return (new, step + 1), None

    alpha0 = start[None, :] + emission[:, 0]
    (alpha_fin, _), _ = jax.lax.scan(
        fwd, (alpha0, jnp.ones((), jnp.int32)),
        (jnp.swapaxes(emission[:, 1:], 0, 1),))
    logz = jax.scipy.special.logsumexp(alpha_fin + stop[None, :], axis=1)

    # ---- gold path score
    lab0 = label[:, 0]
    score0 = start[lab0] + emission[jnp.arange(b), 0, lab0]

    def path_step(carry, xs):
        score, prev, step = carry
        em_t, lab_t = xs
        s_new = score + trans[prev, lab_t] + em_t[jnp.arange(b), lab_t]
        active = step < length
        score = jnp.where(active, s_new, score)
        prev = jnp.where(active, lab_t, prev)
        return (score, prev, step + 1), None

    (path_score, last_lab, _), _ = jax.lax.scan(
        path_step, (score0, lab0, jnp.ones((), jnp.int32)),
        (jnp.swapaxes(emission[:, 1:], 0, 1), jnp.swapaxes(label[:, 1:], 0, 1)))
    path_score = path_score + stop[last_lab]

    # reference ForwardOneSequence returns -(path_score - logZ): a COST
    ll = -(path_score - logz)
    ctx.set_output("LogLikelihood", ll[:, None])
    # aux outputs kept for reference parity (consumed by nothing under AD)
    ctx.set_output("Alpha", alpha_fin)
    ctx.set_output("EmissionExps", jnp.exp(emission))
    ctx.set_output("TransitionExps", jnp.exp(transition))


@register_op("crf_decoding")
def crf_decoding_op(ctx: OpContext):
    """Viterbi decode (reference: crf_decoding_op.cc). Emission [B, T, D] +
    Length, Transition → ViterbiPath [B, T] int64 (padding positions 0).
    With Label wired, outputs per-position mismatch mask instead (the
    reference's evaluation mode)."""
    emission = ctx.input("Emission").astype(jnp.float32)
    transition = ctx.input("Transition").astype(jnp.float32)
    label = ctx.input("Label")
    length = ctx.input("Length")
    b, t, d = emission.shape
    if length is None:
        length = jnp.full((b,), t, jnp.int32)
    length = length.astype(jnp.int32)
    start, stop, trans = _crf_unpack(transition)

    def step(carry, xs):
        delta, stepi = carry
        em_t, = xs
        cand = delta[:, :, None] + trans[None]              # [B, D, D]
        best = jnp.max(cand, axis=1) + em_t
        arg = jnp.argmax(cand, axis=1).astype(jnp.int32)
        active = (stepi < length)[:, None]
        new = jnp.where(active, best, delta)
        arg = jnp.where(active, arg, jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[None], (b, d)))
        return (new, stepi + 1), arg

    delta0 = start[None] + emission[:, 0]
    (delta_fin, _), args = jax.lax.scan(
        step, (delta0, jnp.ones((), jnp.int32)),
        (jnp.swapaxes(emission[:, 1:], 0, 1),))
    last = jnp.argmax(delta_fin + stop[None], axis=1).astype(jnp.int32)

    def backtrace(carry, arg_t):
        cur = carry
        prev = arg_t[jnp.arange(b), cur]
        return prev, cur

    # ys[t] = state at time t+1; final carry = state at time 0
    first, path_tail = jax.lax.scan(backtrace, last, args, reverse=True)
    path = jnp.concatenate([first[None], path_tail], axis=0)  # [T, B]
    path = jnp.swapaxes(path, 0, 1)
    mask = jnp.arange(t)[None] < length[:, None]
    path = jnp.where(mask, path, 0).astype(jnp.int32)
    if label is not None:
        lab = label.astype(jnp.int32)
        if lab.ndim == 3:
            lab = lab[..., 0]
        ctx.set_output("ViterbiPath", jnp.where(mask, (path != lab).astype(jnp.int32), 0))
    else:
        ctx.set_output("ViterbiPath", path)


# -- NCE ----------------------------------------------------------------------


@register_op("nce")
def nce_op(ctx: OpContext):
    """Noise-contrastive estimation (reference: nce_op.cc, uniform sampler).

    Input [B, D], Weight [C, D], Bias [C], Label [B, NT] →
    Cost [B, 1], SampleLogits, SampleLabels. Negatives drawn per batch from
    the uniform noise distribution (sampler attr 0; custom_dist folds in
    through attr probs)."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    label = ctx.input("Label").astype(jnp.int32)
    k = int(ctx.attr("num_neg_samples", 10))
    c = int(ctx.attr("num_total_classes", w.shape[0]))
    seed_rng = ctx.rng()
    b, nt = label.shape

    if ctx.is_test:
        neg = jnp.zeros((b, k), jnp.int32)  # deterministic eval: class 0s
    else:
        neg = jax.random.randint(seed_rng, (b, k), 0, c, jnp.int32)
    samples = jnp.concatenate([label, neg], axis=1)          # [B, NT+K]
    sw = w[samples]                                          # [B, NT+K, D]
    logits = jnp.einsum("bd,bsd->bs", x, sw)
    if bias is not None:
        logits = logits + bias[samples]
    p_noise = 1.0 / c                                        # uniform sampler
    # NCE: sigmoid classification of data vs noise with logit correction
    corrected = logits - jnp.log(k * p_noise)
    lab_true = jnp.concatenate([jnp.ones((b, nt)), jnp.zeros((b, k))], axis=1)
    bce = (jnp.maximum(corrected, 0) - corrected * lab_true
           + jnp.log1p(jnp.exp(-jnp.abs(corrected))))
    ctx.set_output("Cost", jnp.sum(bce, axis=1, keepdims=True))
    ctx.set_output("SampleLogits", logits)
    ctx.set_output("SampleLabels", samples)


# -- hierarchical sigmoid -----------------------------------------------------


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid_op(ctx: OpContext):
    """reference: hierarchical_sigmoid_op.cc + math/matrix_bit_code.h
    SimpleCode: class c encodes as c + C; internal node for bit i is
    (code >> (i+1)) - 1, branch target is bit i of the code. Loss [B, 1] =
    Σ_path BCE(x·w_node + b_node, bit). Static unrolled over the tree depth
    (bit_length(C-1)) with per-sample masks — no data-dependent shapes."""
    x = ctx.input("X")                       # [B, D]
    w = ctx.input("W")                       # [C-1, D] non-leaf weights
    bias = ctx.input("Bias")                 # [C-1] or None
    label = ctx.input("Label").astype(jnp.int32)
    c = int(ctx.attr("num_classes"))
    if label.ndim == 2:
        label = label[:, 0]
    code = label + c                         # [B]
    max_len = int(np.ceil(np.log2(max(c, 2)))) + 1
    # length = FindLastSet(code) - 1 = floor(log2(code))
    length = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)

    losses = jnp.zeros((x.shape[0],), jnp.float32)
    pre_out = []
    for bit in range(max_len):
        idx = (code >> (bit + 1)) - 1        # [B] node row
        tgt = ((code >> bit) & 1).astype(jnp.float32)
        valid = bit < length
        idx_safe = jnp.clip(idx, 0, w.shape[0] - 1)
        logit = jnp.einsum("bd,bd->b", x, w[idx_safe])
        if bias is not None:
            logit = logit + bias.reshape(-1)[idx_safe]
        bce = (jnp.maximum(logit, 0) - logit * tgt
               + jnp.log1p(jnp.exp(-jnp.abs(logit))))
        losses = losses + jnp.where(valid, bce, 0.0)
        pre_out.append(jnp.where(valid, logit, 0.0))
    ctx.set_output("Out", losses[:, None].astype(x.dtype))
    ctx.set_output("PreOut", jnp.stack(pre_out, axis=1))


# -- sample_logits ------------------------------------------------------------


@register_op("sample_logits")
def sample_logits_op(ctx: OpContext):
    """Sampled-softmax helper (reference: sample_logits_op.cc): draw S
    negative classes (log-uniform), gather their logits, subtract log-probs
    (sampled softmax correction), mask accidental hits.

    Logits [B, C], Labels [B, NT] → Samples [B, NT+S], Probabilities,
    SampledLogits [B, NT+S], SampledLabels [B, NT]."""
    logits = ctx.input("Logits")
    labels = ctx.input("Labels").astype(jnp.int32)
    s = int(ctx.attr("num_samples", 10))
    use_custom = ctx.input("CustomizedSamples") is not None
    b, c = logits.shape
    nt = labels.shape[1]
    if use_custom:
        samples = ctx.input("CustomizedSamples").astype(jnp.int32)
        probs = ctx.input("CustomizedProbabilities")
    else:
        rng = ctx.rng()
        # log-uniform (Zipfian) sampling via inverse CDF
        u = jax.random.uniform(rng, (b, s))
        neg = (jnp.exp(u * jnp.log(c + 1.0)) - 1.0).astype(jnp.int32)
        neg = jnp.clip(neg, 0, c - 1)
        samples = jnp.concatenate([labels, neg], axis=1)
        p = (jnp.log((samples + 2.0) / (samples + 1.0))) / jnp.log(c + 1.0)
        probs = p
    sampled = jnp.take_along_axis(logits, samples, axis=1)
    if ctx.attr("remove_accidental_hits", True):
        hit = samples[:, None, :nt] == samples[:, :, None]
        # a negative equal to any true label gets a -inf-ish logit
        acc = jnp.any(hit[:, nt:, :], axis=-1) if nt else jnp.zeros((b, s), bool)
        mask = jnp.concatenate([jnp.zeros((b, nt), bool), acc], axis=1)
        sampled = jnp.where(mask, sampled - 1e20, sampled)
    if ctx.attr("uniq", True) or True:
        sampled = sampled - jnp.log(jnp.maximum(probs, 1e-20))
    ctx.set_output("Samples", samples)
    ctx.set_output("Probabilities", probs)
    ctx.set_output("SampledLogits", sampled)
    ctx.set_output("SampledLabels",
                   jnp.broadcast_to(jnp.arange(nt, dtype=jnp.int32)[None], (b, nt)))
