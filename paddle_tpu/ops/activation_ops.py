"""Activation ops.

Fluid macro-registers ~30 activations (``operators/activation_op.cc:491-510``,
``activation_op.h:997``) with hand-written forward+grad functors. Here each is
one jax expression; grads come from JAX autodiff and XLA fuses them into
adjacent matmuls (the HBM-bandwidth win the reference needs fusion passes for).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op

_SIMPLE = {
    "sigmoid": jax.nn.sigmoid,
    "logsigmoid": jax.nn.log_sigmoid,
    "exp": jnp.exp,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "tanh_shrink": lambda x: x - jnp.tanh(x),
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "cos": jnp.cos,
    "sin": jnp.sin,
    "round": jnp.round,
    "reciprocal": jnp.reciprocal,
    "log": jnp.log,
    "square": jnp.square,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
}


def _make_simple(fn):
    def impl(ctx: OpContext):
        ctx.set_output("Out", fn(ctx.input("X")))

    return impl


for _name, _fn in _SIMPLE.items():
    register_op(_name)(_make_simple(_fn))


@register_op("gelu")
def gelu_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jax.nn.gelu(x, approximate=bool(ctx.attr("approximate", False))))


@register_op("leaky_relu")
def leaky_relu_op(ctx: OpContext):
    x = ctx.input("X")
    alpha = ctx.attr("alpha", 0.02)
    ctx.set_output("Out", jnp.where(x >= 0, x, x * jnp.asarray(alpha, x.dtype)))


@register_op("relu6")
def relu6_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.clip(x, 0.0, ctx.attr("threshold", 6.0)))


@register_op("pow")
def pow_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.power(x, jnp.asarray(ctx.attr("factor", 1.0), x.dtype)))


@register_op("stanh")
def stanh_op(ctx: OpContext):
    x = ctx.input("X")
    a = ctx.attr("scale_a", 0.67)
    b = ctx.attr("scale_b", 1.7159)
    ctx.set_output("Out", b * jnp.tanh(a * x))


@register_op("hard_sigmoid")
def hard_sigmoid_op(ctx: OpContext):
    x = ctx.input("X")
    slope = ctx.attr("slope", 0.2)
    offset = ctx.attr("offset", 0.5)
    ctx.set_output("Out", jnp.clip(slope * x + offset, 0.0, 1.0))


@register_op("swish")
def swish_op(ctx: OpContext):
    x = ctx.input("X")
    beta = ctx.attr("beta", 1.0)
    ctx.set_output("Out", x * jax.nn.sigmoid(beta * x))


@register_op("elu")
def elu_op(ctx: OpContext):
    x = ctx.input("X")
    alpha = ctx.attr("alpha", 1.0)
    ctx.set_output("Out", jax.nn.elu(x, alpha=alpha))


@register_op("selu")
def selu_op(ctx: OpContext):
    ctx.set_output("Out", jax.nn.selu(ctx.input("X")))


@register_op("brelu")
def brelu_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.clip(x, ctx.attr("t_min", 0.0), ctx.attr("t_max", 24.0)))


@register_op("soft_relu")
def soft_relu_op(ctx: OpContext):
    x = ctx.input("X")
    t = ctx.attr("threshold", 40.0)
    ctx.set_output("Out", jnp.log1p(jnp.exp(jnp.clip(x, -t, t))))


@register_op("hard_shrink")
def hard_shrink_op(ctx: OpContext):
    x = ctx.input("X")
    t = ctx.attr("threshold", 0.5)
    ctx.set_output("Out", jnp.where(jnp.abs(x) > t, x, jnp.zeros_like(x)))


@register_op("soft_shrink", "softshrink")
def soft_shrink_op(ctx: OpContext):
    x = ctx.input("X")
    lam = ctx.attr("lambda", 0.5)
    ctx.set_output("Out", jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, jnp.zeros_like(x))))


@register_op("thresholded_relu")
def thresholded_relu_op(ctx: OpContext):
    x = ctx.input("X")
    t = ctx.attr("threshold", 1.0)
    ctx.set_output("Out", jnp.where(x > t, x, jnp.zeros_like(x)))


@register_op("prelu")
def prelu_op(ctx: OpContext):
    x = ctx.input("X")
    alpha = ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "channel" and alpha.size > 1:
        alpha = alpha.reshape(1, -1, *([1] * (x.ndim - 2)))
    elif mode == "all":
        alpha = alpha.reshape(())
    ctx.set_output("Out", jnp.where(x >= 0, x, alpha * x))


@register_op("maxout")
def maxout_op(ctx: OpContext):
    x = ctx.input("X")  # NCHW
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    ctx.set_output("Out", x.reshape(n, c // groups, groups, h, w).max(axis=2))


@register_op("log1p")
def log1p_op(ctx):
    ctx.set_output("Out", jnp.log1p(ctx.input("X")))


@register_op("erf")
def erf_op(ctx):
    ctx.set_output("Out", jax.lax.erf(ctx.input("X")))
