"""Vision-tail ops (reference: operators/spp_op.cc, unpool_op.cc,
pool_with_index_op.cc (max_pool2d_with_index), grid_sampler_op.cc,
psroi_pool_op.cc).

All static-shape, gather/scatter-vectorized; adaptive bin boundaries use
the floor(i·H/k)/ceil((i+1)·H/k) rule like the reference's adaptive pools.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import OpContext, register_op


def _adaptive_bins(total: int, k: int):
    starts = [int(np.floor(i * total / k)) for i in range(k)]
    ends = [int(np.ceil((i + 1) * total / k)) for i in range(k)]
    return starts, ends


def _adaptive_pool2d(x, k: int, ptype: str):
    """[N, C, H, W] → [N, C, k, k] with reference adaptive bin boundaries."""
    n, c, h, w = x.shape
    hs, he = _adaptive_bins(h, k)
    ws, we = _adaptive_bins(w, k)
    red = jnp.max if ptype == "max" else jnp.mean
    rows = []
    for i in range(k):
        cols = [red(x[:, :, hs[i]:he[i], ws[j]:we[j]], axis=(2, 3)) for j in range(k)]
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


@register_op("spp")
def spp_op(ctx: OpContext):
    """Spatial pyramid pooling (reference: spp_op.cc): levels 2^0..2^(L-1)
    bins, flattened + concatenated → [N, C·Σ4^l]."""
    x = ctx.input("X")
    levels = int(ctx.attr("pyramid_height", 1))
    ptype = ctx.attr("pooling_type", "max")
    n = x.shape[0]
    outs = []
    for l in range(levels):
        k = 2 ** l
        outs.append(_adaptive_pool2d(x, k, ptype).reshape(n, -1))
    ctx.set_output("Out", jnp.concatenate(outs, axis=1))


@register_op("max_pool2d_with_index")
def max_pool2d_with_index_op(ctx: OpContext):
    """reference: pool_with_index_op.cc — Out + Mask of flat H*W argmax
    indices (what unpool consumes)."""
    x = ctx.input("X")
    ksize = list(ctx.attr("ksize", [2, 2]))
    strides = list(ctx.attr("strides", ksize))
    paddings = list(ctx.attr("paddings", [0, 0]))
    n, c, h, w = x.shape
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    # window gather: build [oh, ow, kh, kw] index grids into padded input
    iy = (jnp.arange(oh) * sh)[:, None, None, None] + jnp.arange(kh)[None, None, :, None] - ph
    ix = (jnp.arange(ow) * sw)[None, :, None, None] + jnp.arange(kw)[None, None, None, :] - pw
    iy = jnp.broadcast_to(iy, (oh, ow, kh, kw))
    ix = jnp.broadcast_to(ix, (oh, ow, kh, kw))
    inb = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
    iyc = jnp.clip(iy, 0, h - 1)
    ixc = jnp.clip(ix, 0, w - 1)
    vals = x[:, :, iyc, ixc]                                   # [N, C, oh, ow, kh, kw]
    vals = jnp.where(inb[None, None], vals, -jnp.inf)
    vflat = vals.reshape(n, c, oh, ow, kh * kw)
    arg = jnp.argmax(vflat, axis=-1)
    out = jnp.max(vflat, axis=-1)
    ky, kx = arg // kw, arg % kw
    gy = (jnp.arange(oh) * sh - ph)[None, None, :, None] + ky
    gx = (jnp.arange(ow) * sw - pw)[None, None, None, :] + kx
    mask = gy * w + gx
    ctx.set_output("Out", out)
    ctx.set_output("Mask", mask.astype(jnp.int32))


@register_op("unpool")
def unpool_op(ctx: OpContext):
    """Max unpooling (reference: unpool_op.cc): scatter X back to the flat
    positions recorded in Indices; unpooled size from attrs."""
    x = ctx.input("X")                       # [N, C, oh, ow]
    indices = ctx.input("Indices").astype(jnp.int32)
    ksize = list(ctx.attr("ksize", [2, 2]))
    strides = list(ctx.attr("strides", ksize))
    unpooled = ctx.attr("unpooled_size", None)
    n, c, oh, ow = x.shape
    if unpooled:
        uh, uw = int(unpooled[0]), int(unpooled[1])
    else:
        uh = (oh - 1) * strides[0] + ksize[0]
        uw = (ow - 1) * strides[1] + ksize[1]

    flat_idx = indices.reshape(n, c, -1)
    vals = x.reshape(n, c, -1)
    out = jnp.zeros((n, c, uh * uw), x.dtype)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(out, flat_idx, vals)
    ctx.set_output("Out", out.reshape(n, c, uh, uw))


@register_op("grid_sampler")
def grid_sampler_op(ctx: OpContext):
    """Bilinear sampling at normalized [-1, 1] grid coords (reference:
    grid_sampler_op.cc). X [N, C, H, W], Grid [N, Ho, Wo, 2] → [N, C, Ho, Wo]."""
    x = ctx.input("X")
    grid = ctx.input("Grid")
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0   # [N, Ho, Wo]
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    lx = gx - x0
    ly = gy - y0

    def gather(yy, xx):
        inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        v = jax.vmap(lambda img, yi, xi: img[:, yi, xi])(x, yc, xc)  # [N, C, Ho, Wo]
        return jnp.where(inb[:, None], v, 0.0)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    lx = lx[:, None]
    ly = ly[:, None]
    out = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
           + v10 * ly * (1 - lx) + v11 * ly * lx)
    ctx.set_output("Output", out)


@register_op("psroi_pool")
def psroi_pool_op(ctx: OpContext):
    """Position-sensitive RoI pooling (reference: psroi_pool_op.cc):
    input channels C = output_channels · ph · pw; bin (i, j) averages its own
    channel group. ROIs [R, 4] + BatchId [R]."""
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    batch_id = ctx.input("BatchId")
    if batch_id is None:
        batch_id = jnp.zeros((rois.shape[0],), jnp.int32)
    oc = int(ctx.attr("output_channels"))
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    n, c, h, w = x.shape
    ygrid = jnp.arange(h, dtype=jnp.float32)
    xgrid = jnp.arange(w, dtype=jnp.float32)

    def one(roi, bid):
        feat = x[bid].reshape(oc, ph, pw, h, w)
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = jnp.round(roi[2] + 1.0) * scale
        y2 = jnp.round(roi[3] + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph

        def bin_val(i, j):
            ys, ye = y1 + i * bh, y1 + (i + 1) * bh
            xs, xe = x1 + j * bw, x1 + (j + 1) * bw
            m = ((ygrid[:, None] >= jnp.floor(ys)) & (ygrid[:, None] < jnp.ceil(ye))
                 & (xgrid[None, :] >= jnp.floor(xs)) & (xgrid[None, :] < jnp.ceil(xe)))
            cnt = jnp.maximum(jnp.sum(m.astype(jnp.float32)), 1.0)
            return jnp.sum(jnp.where(m[None], feat[:, i, j], 0.0), axis=(1, 2)) / cnt

        rows = [jnp.stack([bin_val(i, j) for j in range(pw)], axis=-1) for i in range(ph)]
        return jnp.stack(rows, axis=-2)  # [oc, ph, pw]

    ctx.set_output("Out", jax.vmap(one)(rois, batch_id.astype(jnp.int32)))
