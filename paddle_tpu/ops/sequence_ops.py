"""Sequence ops over padded batches + lengths.

The reference's signature feature is LoD (level-of-detail) tensors — ragged
sequences stored concatenated with offset tables, consumed by 45
``sequence_ops/`` kernels (reference: ``framework/lod_tensor.h:58-110``,
``operators/sequence_ops/``). LoD's data-dependent shapes are fundamentally
at odds with XLA's static-shape compilation, so the TPU-native representation
is **padded [B, T, ...] tensors + an int Length vector [B]** (equivalently a
mask), the standard XLA idiom (segment ids for the packed case — see
attention_ops). Each op takes X + Length and matches the reference op's
per-sequence semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op


def _mask(length, maxlen, dtype=jnp.float32):
    """[B, T] 1.0 where t < length_b."""
    t = jnp.arange(maxlen)
    return (t[None, :] < length.reshape(-1, 1)).astype(dtype)


@register_op("sequence_mask")
def sequence_mask_op(ctx: OpContext):
    """reference: operators/sequence_ops/sequence_mask_op.cc."""
    length = ctx.input("X").reshape(-1)
    maxlen = ctx.attr("maxlen", -1)
    if maxlen is None or maxlen <= 0:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen attr (XLA static shapes)")
    from ..core.dtypes import to_jnp_dtype

    dtype = to_jnp_dtype(ctx.attr("out_dtype", "int64"))
    ctx.set_output("Y", _mask(length, maxlen, dtype))


@register_op("sequence_pool")
def sequence_pool_op(ctx: OpContext):
    """reference: sequence_pool_op.cc — pooltype in {sum, average, sqrt, max,
    last, first}. X: [B, T, ...], Length: [B]."""
    x = ctx.input("X")
    length = ctx.input("Length")
    ptype = ctx.attr("pooltype", "average").lower()
    B, T = x.shape[0], x.shape[1]
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.reshape(-1)
    m = _mask(length, T).reshape(B, T, *([1] * (x.ndim - 2)))
    denom = jnp.maximum(length.astype(x.dtype), 1).reshape(B, *([1] * (x.ndim - 2)))
    if ptype == "sum":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "average":
        out = jnp.sum(x * m, axis=1) / denom
    elif ptype == "sqrt":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(denom)
    elif ptype == "max":
        neg = jnp.where(m > 0, x, jnp.full_like(x, -3.4e38))
        out = jnp.max(neg, axis=1)
    elif ptype == "last":
        idx = jnp.maximum(length - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape(B, 1, *([1] * (x.ndim - 2))), axis=1
        ).squeeze(1)
    elif ptype == "first":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    ctx.set_output("Out", out)


@register_op("sequence_softmax")
def sequence_softmax_op(ctx: OpContext):
    """reference: sequence_softmax_op.cc — softmax within each sequence."""
    x = ctx.input("X")
    length = ctx.input("Length")
    B, T = x.shape[0], x.shape[1]
    if length is None:
        probs = jax.nn.softmax(x, axis=1)
    else:
        m = _mask(length.reshape(-1), T, jnp.bool_)
        m = m.reshape(B, T, *([1] * (x.ndim - 2)))
        scores = jnp.where(m, x, jnp.full_like(x, -1e9))
        probs = jax.nn.softmax(scores, axis=1)
        probs = jnp.where(m, probs, jnp.zeros_like(probs))
    ctx.set_output("Out", probs)


@register_op("sequence_reverse")
def sequence_reverse_op(ctx: OpContext):
    """reference: sequence_reverse_op.h — reverse each sequence's valid
    prefix, padding stays in place."""
    x = ctx.input("X")
    length = ctx.input("Length")
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)
    if length is None:
        ctx.set_output("Y", jnp.flip(x, axis=1))
        return
    L = length.reshape(-1, 1)
    idx = jnp.where(t[None, :] < L, L - 1 - t[None, :], t[None, :])
    ctx.set_output("Y", jnp.take_along_axis(
        x, idx.reshape(B, T, *([1] * (x.ndim - 2))), axis=1))


@register_op("sequence_expand")
def sequence_expand_op(ctx: OpContext):
    """reference: sequence_expand_op.cc with ref_level semantics reduced to
    the padded world: tile X rows per target length pattern. X: [B, D] →
    [B, T, D] broadcast against Y's time dim."""
    x = ctx.input("X")
    y = ctx.input("Y")
    T = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    ctx.set_output("Out", out)


@register_op("sequence_concat")
def sequence_concat_op(ctx: OpContext):
    """Concatenate along time (padded): [B,T1,D]+[B,T2,D] → [B,T1+T2,D].
    With Lengths given, compacts each pair's valid prefixes together."""
    xs = ctx.inputs("X")
    lengths = ctx.inputs("Length") if ctx.has_input("Length") else None
    if not lengths:
        ctx.set_output("Out", jnp.concatenate(xs, axis=1))
        return
    B = xs[0].shape[0]
    T_out = sum(x.shape[1] for x in xs)
    D = xs[0].shape[2:]
    out = jnp.zeros((B, T_out) + D, xs[0].dtype)
    t_total = jnp.zeros((B,), jnp.int32)
    pos = jnp.arange(T_out)
    for x, l in zip(xs, lengths):
        l = l.reshape(-1)
        T = x.shape[1]
        src_t = jnp.arange(T)
        # scatter each sequence's prefix at offset t_total
        tgt = t_total[:, None] + src_t[None, :]
        valid = src_t[None, :] < l[:, None]
        b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
        tgt_c = jnp.where(valid, tgt, T_out - 1)
        contrib = jnp.zeros_like(out).at[b_idx.reshape(-1), tgt_c.reshape(-1)].add(
            jnp.where(valid.reshape(B, T, *([1] * len(D))), x, 0).reshape((B * T,) + D))
        out = out + contrib
        t_total = t_total + l.astype(jnp.int32)
    ctx.set_output("Out", out)
    ctx.set_output("LengthOut", t_total)


@register_op("sequence_pad")
def sequence_pad_op(ctx: OpContext):
    """reference: sequence_pad_op.cc — here X is already padded [B,T,...];
    re-pads to padded_length with pad_value and emits Length."""
    x = ctx.input("X")
    length = ctx.input("Length")
    pad_value = ctx.input("PadValue")
    target = ctx.attr("padded_length", -1)
    B, T = x.shape[0], x.shape[1]
    if target is None or target <= 0:
        target = T
    pv = pad_value.reshape(()) if pad_value is not None else jnp.asarray(0.0, x.dtype)
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    m = _mask(length.reshape(-1), T, jnp.bool_).reshape(B, T, *([1] * (x.ndim - 2)))
    x = jnp.where(m, x, pv.astype(x.dtype))
    if target > T:
        pad = [(0, 0), (0, target - T)] + [(0, 0)] * (x.ndim - 2)
        x = jnp.pad(x, pad, constant_values=pv)
    else:
        x = x[:, :target]
    ctx.set_output("Out", x)
    ctx.set_output("Length", length.reshape(-1))


@register_op("sequence_unpad")
def sequence_unpad_op(ctx: OpContext):
    """reference: sequence_unpad_op.cc — zeroes padding (stays padded-shape;
    true ragged output is not expressible under XLA)."""
    x = ctx.input("X")
    length = ctx.input("Length").reshape(-1)
    T = x.shape[1]
    m = _mask(length, T, jnp.bool_).reshape(x.shape[0], T, *([1] * (x.ndim - 2)))
    ctx.set_output("Out", jnp.where(m, x, jnp.zeros_like(x)))


@register_op("sequence_erase")
def sequence_erase_op(ctx: OpContext):
    """reference: sequence_erase_op.cc — replace listed tokens with pad (0)
    and compact left. X: [B, T] int."""
    x = ctx.input("X")
    tokens = jnp.asarray(ctx.attr("tokens", []))
    B, T = x.shape
    keep = jnp.ones_like(x, jnp.bool_)
    for tok in ctx.attr("tokens", []):
        keep = keep & (x != tok)
    # stable compaction: argsort on (not keep) puts kept items first in order
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1)
    m = _mask(new_len, T, jnp.bool_)
    ctx.set_output("Out", jnp.where(m, compacted, jnp.zeros_like(compacted)))
    ctx.set_output("Length", new_len)


@register_op("sequence_enumerate")
def sequence_enumerate_op(ctx: OpContext):
    """reference: sequence_enumerate_op.cc — sliding windows of win_size."""
    x = ctx.input("X")  # [B, T]
    win = ctx.attr("win_size")
    pad = ctx.attr("pad_value", 0)
    B, T = x.shape
    padded = jnp.pad(x, [(0, 0), (0, win - 1)], constant_values=pad)
    out = jnp.stack([padded[:, i : i + T] for i in range(win)], axis=-1)
    ctx.set_output("Out", out)


@register_op("sequence_slice")
def sequence_slice_op(ctx: OpContext):
    """reference: sequence_slice_op.cc — per-sequence [offset, offset+length)
    gather (output padded to max length attr)."""
    x = ctx.input("X")
    offset = ctx.input("Offset").reshape(-1)
    length = ctx.input("Length").reshape(-1)
    B, T = x.shape[0], x.shape[1]
    out_T = ctx.attr("out_maxlen", 0) or T
    t = jnp.arange(out_T)
    idx = jnp.clip(offset[:, None] + t[None, :], 0, T - 1)
    g = jnp.take_along_axis(x, idx.reshape(B, out_T, *([1] * (x.ndim - 2))), axis=1)
    m = _mask(length, out_T, jnp.bool_).reshape(B, out_T, *([1] * (x.ndim - 2)))
    ctx.set_output("Out", jnp.where(m, g, jnp.zeros_like(g)))


@register_op("sequence_scatter")
def sequence_scatter_op(ctx: OpContext):
    """reference: sequence_scatter_op.cc — per-row scatter-add of Updates at
    Ids positions."""
    x = ctx.input("X")  # [B, T]
    ids = ctx.input("Ids")  # [B, K]
    upd = ctx.input("Updates")  # [B, K]
    B = x.shape[0]
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], ids.shape)
    out = x.at[b_idx.reshape(-1), ids.reshape(-1)].add(upd.reshape(-1))
    ctx.set_output("Out", out)


@register_op("sequence_expand_as")
def sequence_expand_as_op(ctx: OpContext):
    x = ctx.input("X")
    y = ctx.input("Y")
    ctx.set_output("Out", jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:]))


@register_op("im2sequence")
def im2sequence_op(ctx: OpContext):
    """reference: im2sequence_op.cc — image patches to sequence [B, L, khkw*C]."""
    x = ctx.input("X")  # NCHW
    kh, kw = ctx.attr("kernels")
    sh, sw = ctx.attr("strides", [1, 1])
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(x[:, :, i : i + sh * oh : sh, j : j + sw * ow : sw])
    stacked = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
    out = stacked.reshape(n, c * kh * kw, oh * ow).transpose(0, 2, 1)
    ctx.set_output("Out", out)


@register_op("row_conv")
def row_conv_op(ctx: OpContext):
    """reference: row_conv_op.cc — lookahead row convolution over [B, T, D]."""
    x = ctx.input("X")
    w = ctx.input("Filter")  # [future_ctx, D]
    ctxlen = w.shape[0]
    B, T, D = x.shape
    out = jnp.zeros_like(x)
    for k in range(ctxlen):
        shifted = jnp.pad(x, [(0, 0), (0, k), (0, 0)])[:, k : k + T]
        out = out + shifted * w[k][None, None, :]
    ctx.set_output("Out", out)
