"""calc_gradient op: d(targets)/d(inputs) inside the traced step.

Reference: ``python/paddle/fluid/backward.py:613`` (``calc_gradient``) — Fluid
walks the forward ops in reverse appending grad ops between targets and
inputs. The TPU-native design instead re-interprets the op prefix that leads
up to the marker as a pure function of the requested inputs and applies
``jax.vjp`` to it at trace time, so the backward is XLA-fused like everything
else. Because the marker is an ordinary op, ``fluid.gradients`` may be called
several times in one program (GAN two-loss style), and a later marker whose
prefix contains an earlier one differentiates *through* it — the double-grad
idiom — via JAX's nested AD.

Semantics notes (vs the reference):
- each requested input is treated as an independent leaf: the graph is cut at
  that variable, so gradients do not flow through it to upstream producers
  (matching Fluid, which seeds ``input@GRAD`` directly);
- inputs with no path to any target get zero gradients (Fluid returns None
  for them; a traced program cannot distinguish structurally-zero at trace
  time, so zeros are the faithful equivalent);
- ``no_grad_set`` variables are wrapped in ``stop_gradient`` as soon as they
  are produced.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.interpreter import SKIP_OPS
from ..core.registry import OpContext, get_op_impl, register_op


@register_op("calc_gradient")
def _calc_gradient(ctx: OpContext):
    op = ctx.op
    block = op.block
    my_idx = next(i for i, o in enumerate(block.ops) if o is op)
    prefix = block.ops[:my_idx]
    target_names = list(op.attrs["targets"])
    input_names = list(op.attrs["inputs"])
    tg_names = list(op.attrs.get("target_gradients") or [None] * len(target_names))
    no_grad = frozenset(op.attrs.get("no_grad_set") or ())

    # Backward slice: keep only the ops that transitively produce the targets,
    # cutting at the requested inputs. A reverse walk keeps the LAST producer
    # of each needed name (program-order semantics: the op reads the latest
    # value), and naturally excludes training-tail ops — backward_marker,
    # optimizer/clip updates — so gradients() works on a program that already
    # called minimize()/append_backward.
    input_set = set(input_names)
    needed = set(target_names) - input_set
    sliced = []  # (original prefix index, op), reverse order
    for i in range(len(prefix) - 1, -1, -1):
        o = prefix[i]
        if o.type in SKIP_OPS:
            continue
        outs = {n for ns in o.outputs.values() for n in ns}
        if outs & needed:
            sliced.append((i, o))
            needed -= outs
            for ns in o.inputs.values():
                needed.update(ns)
            needed -= input_set  # leaves: don't pull their producers
    sliced.reverse()

    # Per-op re-assert lists: a leaf/no_grad wrap is only needed when the op
    # actually (re)wrote that name — unconditional re-wrapping would grow the
    # jaxpr O(ops × vars) in identity equations.
    slice_plan = []
    for i, o in sliced:
        outs = {n for ns in o.outputs.values() for n in ns}
        leaf_hits = [j for j, n in enumerate(input_names) if n in outs]
        ng_hits = [n for n in no_grad if n in outs]
        slice_plan.append((i, o, leaf_hits, ng_hits))

    written = {n for _, o in sliced for ns in o.outputs.values() for n in ns}
    # Base env: everything the slice does NOT recompute (feeds, params —
    # including values training-tail ops already rewrote — startup state).
    # The slice re-runs from here inside the vjp'd function; XLA CSE merges
    # the recomputation with the original forward at compile time.
    base = {k: v for k, v in ctx.env.items() if k not in written}
    leaves = [ctx._lookup(n) for n in input_names]
    trace = ctx.trace

    def fwd(leaf_vals):
        env = dict(base)
        env.update(zip(input_names, leaf_vals))
        from ..core.enforce import EnforceNotMet, wrap_op_error

        for i, o, leaf_hits, ng_hits in slice_plan:
            trace.current_op_idx = i
            try:
                get_op_impl(o.type)(OpContext(o, env, trace))
            except (EnforceNotMet, NotImplementedError):
                raise
            except Exception as e:
                raise wrap_op_error(e, o, i, env) from e
            # Re-assert leaves: if this op (re)produced a requested input, the
            # leaf value wins — that is what cuts the graph at the input.
            for j in leaf_hits:
                env[input_names[j]] = leaf_vals[j]
            for n in ng_hits:
                env[n] = jax.lax.stop_gradient(env[n])
        return [env[t] for t in target_names]

    targets_out, vjp_fn = jax.vjp(fwd, leaves)
    seeds = []
    for t_out, tg in zip(targets_out, tg_names):
        if tg:
            seeds.append(ctx._lookup(tg).astype(t_out.dtype))
        else:
            seeds.append(jnp.ones_like(t_out))
    (grads,) = vjp_fn(seeds)
    trace.current_op_idx = my_idx
    ctx.set_outputs("InputGrads", grads)
