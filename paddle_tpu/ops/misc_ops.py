"""Host-interaction ops: print, py_func (reference: operators/print_op.cc,
py_func_op.cc).

Under XLA these are host callbacks: ``print`` uses jax.debug.print /
debug.callback (works inside jit, tapped out at run time), ``py_func``
uses pure_callback with an optional user backward function wired through
custom_vjp — the reference's RegisterPyFunc machinery without the global
function table.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import OpContext, register_op

# py_func registry: attr stores an integer handle (program-serializable),
# matching the reference's global PyFuncRegistry ids (py_func_op.cc).
_PY_FUNCS: Dict[int, Callable] = {}


def register_py_func(fn: Callable) -> int:
    handle = len(_PY_FUNCS)
    _PY_FUNCS[handle] = fn
    return handle


@register_op("print")
def print_op(ctx: OpContext):
    """reference: print_op.cc — tensor tap-out; pass-through output."""
    x = ctx.input("In" if ctx.has_input("In") else "X")
    message = ctx.attr("message", "") or ""
    first_n = ctx.attr("first_n", -1)  # accepted; XLA prints every call
    summarize = int(ctx.attr("summarize", -1))
    if summarize and summarize > 0:
        flat = x.reshape(-1)[:summarize]
        jax.debug.print(message + " {}", flat)
    else:
        jax.debug.print(message + " {}", x)
    ctx.set_output("Out", x)


@register_op("py_func")
def py_func_op(ctx: OpContext):
    """reference: py_func_op.cc. Runs a registered host function over the
    inputs; output shapes/dtypes come from the declared output vars."""
    xs = ctx.inputs("X")
    handle = int(ctx.attr("forward_callable_id"))
    bwd_handle = ctx.attr("backward_callable_id", -1)
    fwd = _PY_FUNCS[handle]
    out_vars = [ctx.op.block.var(n) for n in ctx.op.outputs.get("Out", [])]
    result_shapes = [
        jax.ShapeDtypeStruct(tuple(v.shape), np.dtype(v.dtype)) for v in out_vars
    ]

    def host_fwd(*arrays):
        out = fwd(*[np.asarray(a) for a in arrays])
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(np.asarray(o, dtype=s.dtype).reshape(s.shape)
                     for o, s in zip(outs, result_shapes))

    def call_fwd(*args):
        return jax.pure_callback(host_fwd, tuple(result_shapes), *args)

    if bwd_handle is not None and int(bwd_handle) >= 0:
        bwd = _PY_FUNCS[int(bwd_handle)]

        @jax.custom_vjp
        def f(*args):
            return call_fwd(*args)

        def f_fwd(*args):
            return call_fwd(*args), args

        def f_bwd(res, gs):
            shapes = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in res)

            def host_bwd(*all_args):
                n = len(res)
                xs_np = [np.asarray(a) for a in all_args[:n]]
                gs_np = [np.asarray(a) for a in all_args[n:]]
                grads = bwd(*xs_np, *gs_np)
                grads = grads if isinstance(grads, (list, tuple)) else [grads]
                return tuple(np.asarray(g, dtype=s.dtype).reshape(s.shape)
                             for g, s in zip(grads, shapes))

            return jax.pure_callback(host_bwd, shapes, *res, *gs)

        f.defvjp(f_fwd, f_bwd)
        outs = f(*xs)
    else:
        outs = call_fwd(*xs)
    ctx.set_outputs("Out", outs)


@register_op("delete_var")
def delete_var_op(ctx: OpContext):
    """reference: operators/controlflow/... delete_var frees scope tensors;
    XLA buffer liveness already reclaims dead values inside the compiled
    step, so this drops the env entries (symbolic no-op kept for program
    parity)."""
    for names in ctx.op.inputs.values():
        for n in names:
            ctx.env.pop(n, None)


@register_op("merge_selected_rows")
def merge_selected_rows_op(ctx: OpContext):
    """reference: operators/merge_selected_rows_op.cc — sum rows with
    duplicate ids in a SelectedRows. Static-shape sort+segment-sum
    (core/sparse.py merge_rows); padded tail ids become out-of-range so a
    downstream scatter drops them."""
    from ..core.sparse import SparseGrad, merge_rows

    x = ctx.input("X")
    if not isinstance(x, SparseGrad):
        raise TypeError("merge_selected_rows expects a SelectedRows "
                        "(SparseGrad) value, got %r" % type(x).__name__)
    uniq, merged = merge_rows(x.ids, x.rows, invalid_index=2**31 - 1)
    ctx.set_output("Out", SparseGrad(uniq, merged))


@register_op("get_tensor_from_selected_rows")
def get_tensor_from_selected_rows_op(ctx: OpContext):
    """reference: operators/get_tensor_from_selected_rows_op.cc — expose a
    SelectedRows' value block as a dense [N, D] tensor (row i holds the
    contribution of table row ids[i])."""
    from ..core.sparse import SparseGrad

    x = ctx.input("X")
    if not isinstance(x, SparseGrad):
        raise TypeError("get_tensor_from_selected_rows expects a "
                        "SelectedRows (SparseGrad) value, got %r"
                        % type(x).__name__)
    ctx.set_output("Out", x.rows)
