"""Detection ops (reference: paddle/fluid/operators/detection/ — 43 files,
11.7k LoC: prior_box_op.cc, box_coder_op.cc, iou_similarity_op.cc,
bipartite_match_op.cc, target_assign_op.cc, multiclass_nms_op.cc,
anchor_generator_op.cc, box_clip_op.cc, density_prior_box_op.cc,
yolov3_loss_op.cc, generate_proposals_op.cc, mine_hard_examples_op.cc,
polygon_box_transform_op.cc; roi_align_op.cc / roi_pool_op.cc in
operators/).

TPU-first redesign notes:
- Variable-length results (NMS keeps, proposals) use the framework's
  padded+Length convention (ops/sequence_ops.py) instead of LoD: fixed
  [B, K, ...] outputs padded with -1 plus a Length [B] count — static
  shapes for XLA, same information.
- Batched ops take dense [B, ...] inputs where the reference used LoD
  concatenation ([sum_i N_i, ...]); per-image ragged sizes are expressed by
  sentinel rows (boxes with w<=0 are padding), matching how the reference's
  CTR/SSD pipelines pad anyway.
- Greedy sequential algorithms (NMS suppression, bipartite matching) run as
  ``lax.fori_loop`` over a precomputed dense IoU/distance matrix: O(K) tiny
  steps over VPU-friendly [K,K] tiles instead of pointer-chasing.
- ``vmap`` lifts single-image kernels over the batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import OpContext, register_op

# -- box utilities ------------------------------------------------------------


def box_area(boxes, normalized: bool = True):
    """[..., 4] xyxy → area. Un-normalized (pixel) boxes count the +1 edge
    pixel, matching the reference's BBoxArea (bbox_util.h)."""
    off = 0.0 if normalized else 1.0
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0] + off, 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1] + off, 0.0)
    return w * h


def pairwise_iou(a, b, normalized: bool = True):
    """a [N,4], b [M,4] xyxy → IoU [N,M] (reference: iou_similarity_op.h)."""
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a, normalized)[:, None] + box_area(b, normalized)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


# -- iou_similarity -----------------------------------------------------------


@register_op("iou_similarity")
def iou_similarity_op(ctx: OpContext):
    """reference: detection/iou_similarity_op.cc — X [N,4], Y [M,4] → [N,M]."""
    x, y = ctx.input("X"), ctx.input("Y")
    norm = ctx.attr("box_normalized", True)
    if x.ndim == 3:  # batched extension [B,N,4] × ([B,M,4] or shared [M,4])
        ctx.set_output("Out", jax.vmap(
            lambda a, b: pairwise_iou(a, b, norm),
            in_axes=(0, 0 if y.ndim == 3 else None))(x, y))
    else:
        ctx.set_output("Out", pairwise_iou(x, y, norm))


# -- box_coder ----------------------------------------------------------------


@register_op("box_coder")
def box_coder_op(ctx: OpContext):
    """reference: detection/box_coder_op.cc.

    encode_center_size: TargetBox [N,4] vs PriorBox [M,4] → [N,M,4]
    decode_center_size: TargetBox [N,M,4] + PriorBox → [N,M,4]
    (axis=1 swaps which dim the priors broadcast over in decode).
    """
    prior = ctx.input("PriorBox")          # [M, 4] xyxy
    prior_var = ctx.input("PriorBoxVar")   # [M, 4] or None
    target = ctx.input("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    normalized = ctx.attr("box_normalized", True)
    axis = ctx.attr("axis", 0)
    attr_var = ctx.attr("variance", [])

    off = 0.0 if normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5

    if prior_var is not None:
        var = prior_var  # [M, 4]
    elif attr_var:
        var = jnp.broadcast_to(jnp.asarray(attr_var, prior.dtype), prior.shape)
    else:
        var = jnp.ones_like(prior)

    if code_type.lower() in ("encode_center_size", "encodecentersize"):
        def enc(t2d):
            tw = t2d[:, 2] - t2d[:, 0] + off
            th = t2d[:, 3] - t2d[:, 1] + off
            tcx = t2d[:, 0] + tw * 0.5
            tcy = t2d[:, 1] + th * 0.5
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            dh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            return jnp.stack([dx, dy, dw, dh], axis=-1) / var[None, :, :]

        # batched [B, N, 4] extension for dense SSD pipelines
        out = jax.vmap(enc)(target) if target.ndim == 3 else enc(target)
    else:  # decode_center_size
        if target.ndim == 2:
            target = target[:, None, :]
        if axis == 0:  # priors along dim 1
            pw_, ph_, pcx_, pcy_, var_ = (a[None, :] for a in (pw, ph, pcx, pcy, var))
        else:          # priors along dim 0
            pw_, ph_, pcx_, pcy_, var_ = (a[:, None] for a in (pw, ph, pcx, pcy, var))
        t = target * var_ if var_.ndim == target.ndim else target * var_[..., None]
        cx = t[..., 0] * pw_ + pcx_
        cy = t[..., 1] * ph_ + pcy_
        w = jnp.exp(t[..., 2]) * pw_
        h = jnp.exp(t[..., 3]) * ph_
        out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                         cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)
    ctx.set_output("OutputBox", out)


# -- prior_box / density_prior_box / anchor_generator -------------------------


@register_op("prior_box")
def prior_box_op(ctx: OpContext):
    """reference: detection/prior_box_op.cc. Boxes [H,W,P,4] normalized."""
    feat = ctx.input("Input")   # [N, C, H, W]
    image = ctx.input("Image")  # [N, C, IH, IW]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = [1.0]
    for r in ctx.attr("aspect_ratios", []) or []:
        r = float(r)
        if not any(abs(r - e) < 1e-6 for e in ars):
            ars.append(r)
            if ctx.attr("flip", False):
                ars.append(1.0 / r)
    variances = [float(v) for v in ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = ctx.attr("clip", False)
    offset = float(ctx.attr("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(ctx.attr("step_w", 0.0)) or iw / w
    step_h = float(ctx.attr("step_h", 0.0)) or ih / h
    mmorder = ctx.attr("min_max_aspect_ratios_order", False)

    whs = []
    for k, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        if not mmorder:
            for r in ars:
                if abs(r - 1.0) < 1e-6:
                    continue
                sr = np.sqrt(r)
                whs.append((ms * sr, ms / sr))
        if max_sizes:
            bs = np.sqrt(ms * max_sizes[k])
            whs.append((bs, bs))
        if mmorder:
            for r in ars:
                if abs(r - 1.0) < 1e-6:
                    continue
                sr = np.sqrt(r)
                whs.append((ms * sr, ms / sr))
    whs = jnp.asarray(whs, feat.dtype)  # [P, 2]

    cx = (jnp.arange(w, dtype=feat.dtype) + offset) * step_w
    cy = (jnp.arange(h, dtype=feat.dtype) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                     # [H, W]
    bw = whs[:, 0] / 2.0 / iw
    bh = whs[:, 1] / 2.0 / ih
    boxes = jnp.stack([
        cxg[..., None] / iw - bw, cyg[..., None] / ih - bh,
        cxg[..., None] / iw + bw, cyg[..., None] / ih + bh,
    ], axis=-1)                                          # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    ctx.set_output("Boxes", boxes)
    ctx.set_output("Variances", jnp.broadcast_to(
        jnp.asarray(variances, feat.dtype), boxes.shape))


@register_op("density_prior_box")
def density_prior_box_op(ctx: OpContext):
    """reference: detection/density_prior_box_op.cc — dense sampling grid per
    fixed_size/density pair."""
    feat = ctx.input("Input")
    image = ctx.input("Image")
    fixed_sizes = [float(s) for s in ctx.attr("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in ctx.attr("fixed_ratios", [])]
    densities = [int(d) for d in ctx.attr("densities", [])]
    variances = [float(v) for v in ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = ctx.attr("clip", False)
    offset = float(ctx.attr("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(ctx.attr("step_w", 0.0)) or iw / w
    step_h = float(ctx.attr("step_h", 0.0)) or ih / h

    # per-cell local offsets and sizes (static python loop — tiny)
    locs = []  # (shift_x, shift_y, half_w, half_h)
    for size, density in zip(fixed_sizes, densities):
        shift = size / density
        for r in fixed_ratios:
            sr = np.sqrt(r)
            bw2, bh2 = size * sr / 2.0, size / sr / 2.0
            for di in range(density):
                for dj in range(density):
                    locs.append((-size / 2.0 + shift / 2.0 + dj * shift,
                                 -size / 2.0 + shift / 2.0 + di * shift,
                                 bw2, bh2))
    locs = jnp.asarray(locs, feat.dtype)  # [P, 4]

    cx = (jnp.arange(w, dtype=feat.dtype) + offset) * step_w
    cy = (jnp.arange(h, dtype=feat.dtype) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    ccx = cxg[..., None] + locs[None, None, :, 0]
    ccy = cyg[..., None] + locs[None, None, :, 1]
    boxes = jnp.stack([
        (ccx - locs[None, None, :, 2]) / iw,
        (ccy - locs[None, None, :, 3]) / ih,
        (ccx + locs[None, None, :, 2]) / iw,
        (ccy + locs[None, None, :, 3]) / ih,
    ], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    ctx.set_output("Boxes", boxes)
    ctx.set_output("Variances", jnp.broadcast_to(
        jnp.asarray(variances, feat.dtype), boxes.shape))


@register_op("anchor_generator")
def anchor_generator_op(ctx: OpContext):
    """reference: detection/anchor_generator_op.cc — RPN anchors in input
    (pixel) coordinates, Anchors [H,W,A,4]."""
    feat = ctx.input("Input")
    sizes = [float(s) for s in ctx.attr("anchor_sizes", [])]
    ratios = [float(r) for r in ctx.attr("aspect_ratios", [])]
    stride = [float(s) for s in ctx.attr("stride", [])]
    variances = [float(v) for v in ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = float(ctx.attr("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]

    whs = []
    for r in ratios:
        for s in sizes:
            area = s * s
            wa = np.sqrt(area / r)
            whs.append((wa, wa * r))
    whs = jnp.asarray(whs, feat.dtype)  # [A, 2]
    cx = (jnp.arange(w, dtype=feat.dtype) + offset) * stride[0]
    cy = (jnp.arange(h, dtype=feat.dtype) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    bw, bh = whs[:, 0] / 2.0, whs[:, 1] / 2.0
    anchors = jnp.stack([
        cxg[..., None] - bw, cyg[..., None] - bh,
        cxg[..., None] + bw, cyg[..., None] + bh,
    ], axis=-1)
    ctx.set_output("Anchors", anchors)
    ctx.set_output("Variances", jnp.broadcast_to(
        jnp.asarray(variances, feat.dtype), anchors.shape))


# -- box_clip -----------------------------------------------------------------


@register_op("box_clip")
def box_clip_op(ctx: OpContext):
    """reference: detection/box_clip_op.cc — clip to im_info [B,3] (h,w,scale);
    boxes [B,N,4] (batched dense replacing the reference's LoD)."""
    boxes = ctx.input("Input")
    im_info = ctx.input("ImInfo")
    h = im_info[:, 0] / im_info[:, 2] - 1.0
    w = im_info[:, 1] / im_info[:, 2] - 1.0
    if boxes.ndim == 2:
        hm, wm = h[0], w[0]
        out = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, wm), jnp.clip(boxes[:, 1], 0.0, hm),
            jnp.clip(boxes[:, 2], 0.0, wm), jnp.clip(boxes[:, 3], 0.0, hm)], axis=-1)
    else:
        hm, wm = h[:, None], w[:, None]
        out = jnp.stack([
            jnp.clip(boxes[..., 0], 0.0, wm), jnp.clip(boxes[..., 1], 0.0, hm),
            jnp.clip(boxes[..., 2], 0.0, wm), jnp.clip(boxes[..., 3], 0.0, hm)], axis=-1)
    ctx.set_output("Output", out)


# -- bipartite_match ----------------------------------------------------------


def _bipartite_match_single(dist, valid_rows):
    """Greedy max bipartite matching (reference: bipartite_match_op.cc
    BipartiteMatchFunctor, match_type='bipartite').

    dist [N, M] (rows = gt entities, cols = priors). Returns
    (col_to_row [M] int32, col_dist [M] f32): each column's matched row or
    -1. Sequential argmax loop → fori_loop over min(N, M) steps.
    """
    n, m = dist.shape
    NEG = jnp.asarray(-1.0, dist.dtype)
    dist = jnp.where(valid_rows[:, None], dist, NEG)

    def body(_, carry):
        d, c2r, cdist = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        take = best > 0.0
        c2r = jnp.where(take, c2r.at[j].set(i.astype(jnp.int32)), c2r)
        cdist = jnp.where(take, cdist.at[j].set(best), cdist)
        d = jnp.where(take, d.at[i, :].set(NEG).at[:, j].set(NEG), d)
        return d, c2r, cdist

    _, c2r, cdist = jax.lax.fori_loop(
        0, min(n, m), body,
        (dist, jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), dist.dtype)))
    return c2r, cdist


def _match_extra(dist, c2r, cdist, valid_rows):
    """per_prediction phase 2: unmatched cols take their argmax row if
    dist >= overlap_threshold (handled by caller)."""
    best_row = jnp.argmax(jnp.where(valid_rows[:, None], dist, -1.0), axis=0)
    best_val = jnp.max(jnp.where(valid_rows[:, None], dist, -1.0), axis=0)
    un = c2r < 0
    return (jnp.where(un, best_row.astype(jnp.int32), c2r),
            jnp.where(un, best_val, cdist))


@register_op("bipartite_match")
def bipartite_match_op(ctx: OpContext):
    """DistMat [B,N,M] (or [N,M]) → ColToRowMatchIndices [B,M],
    ColToRowMatchDist [B,M]. Rows whose distances are all <= 0 are padding.
    """
    dist = ctx.input("DistMat")
    match_type = ctx.attr("match_type", "bipartite")
    thresh = float(ctx.attr("dist_threshold", 0.5))
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]

    def one(d):
        valid = jnp.any(d > 0.0, axis=1)
        c2r, cd = _bipartite_match_single(d, valid)
        if match_type == "per_prediction":
            er, ed = _match_extra(d, c2r, cd, valid)
            ok = ed >= thresh
            c2r = jnp.where((c2r < 0) & ok, er, c2r)
            cd = jnp.where((cd == 0) & ok, ed, cd)
        return c2r, cd

    c2r, cd = jax.vmap(one)(dist)
    if squeeze:
        c2r, cd = c2r[0], cd[0]
    ctx.set_output("ColToRowMatchIndices", c2r)
    ctx.set_output("ColToRowMatchDist", cd)


# -- target_assign ------------------------------------------------------------


@register_op("target_assign")
def target_assign_op(ctx: OpContext):
    """reference: detection/target_assign_op.cc (TargetAssignFunctor).

    X [B, Ng, P, K] (or [B, Ng, K] ≡ P=1), MatchIndices [B, M] →
    Out [B, M, K] with out[b, m] = X[b, match[b, m], m % P]; mismatched
    entries (index<0) get ``mismatch_value`` / weight 0. Optional NegMask
    [B, M] (the static-shape stand-in for the reference's NegIndices LoD):
    masked entries get mismatch_value with weight **1** — the hard-negative
    conf target."""
    x = ctx.input("X")
    match = ctx.input("MatchIndices")
    neg_mask = ctx.input("NegMask")
    mismatch = ctx.attr("mismatch_value", 0)
    if x.ndim == 3:
        x = x[:, :, None, :]
    p = x.shape[2]

    def one(xb, mb):
        cols = jnp.arange(mb.shape[0], dtype=jnp.int32) % p
        safe = jnp.maximum(mb, 0).astype(jnp.int32)
        out = xb[safe, cols]                       # [M, K]
        ok = (mb >= 0)[:, None]
        out = jnp.where(ok, out, jnp.asarray(mismatch, x.dtype))
        return out, ok.astype(jnp.float32)

    out, w = jax.vmap(one)(x, match)
    if neg_mask is not None:
        neg = (neg_mask > 0)[..., None]
        out = jnp.where(neg, jnp.asarray(mismatch, x.dtype), out)
        w = jnp.where(neg, 1.0, w)
    ctx.set_output("Out", out)
    ctx.set_output("OutWeight", w)


# -- NMS ----------------------------------------------------------------------


def nms_keep_mask(boxes, scores, iou_threshold, eta=1.0, normalized=True):
    """Greedy NMS over score-descending order without reordering the output:
    returns a bool keep mask. boxes [K,4], scores [K] (−inf = invalid)."""
    k = boxes.shape[0]
    order = jnp.argsort(-scores)
    b_sorted = boxes[order]
    s_sorted = scores[order]
    iou = pairwise_iou(b_sorted, b_sorted, normalized)

    def body(i, carry):
        keep, thresh = carry
        sup = jnp.any(keep & (iou[:, i] > thresh))
        valid = s_sorted[i] > -jnp.inf
        keep = keep.at[i].set(valid & ~sup)
        thresh = jnp.where(keep[i] & (eta < 1.0) & (thresh > 0.5), thresh * eta, thresh)
        return keep, thresh

    keep_sorted, _ = jax.lax.fori_loop(
        0, k, body, (jnp.zeros((k,), bool), jnp.asarray(iou_threshold, jnp.float32)))
    # scatter back to original index order
    return jnp.zeros((k,), bool).at[order].set(keep_sorted)


@register_op("multiclass_nms")
def multiclass_nms_op(ctx: OpContext):
    """reference: detection/multiclass_nms_op.cc.

    BBoxes [B, M, 4] + Scores [B, C, M] → Out [B, keep_top_k, 6]
    (label, score, x1, y1, x2, y2; padded with -1) + Length [B] — the
    padded+Length replacement for the reference's variable-size LoD output.
    """
    bboxes = ctx.input("BBoxes")
    scores = ctx.input("Scores")
    bg = ctx.attr("background_label", 0)
    score_thresh = float(ctx.attr("score_threshold", 0.0))
    nms_top_k = int(ctx.attr("nms_top_k", -1))
    nms_thresh = float(ctx.attr("nms_threshold", 0.3))
    eta = float(ctx.attr("nms_eta", 1.0))
    keep_top_k = int(ctx.attr("keep_top_k", -1))
    normalized = ctx.attr("normalized", True)

    b, c, m = scores.shape
    k1 = min(nms_top_k, m) if nms_top_k > 0 else m
    ktot = keep_top_k if keep_top_k > 0 else c * k1

    def per_class(boxes_img, s_c):
        s = jnp.where(s_c > score_thresh, s_c, -jnp.inf)
        top_s, top_i = jax.lax.top_k(s, k1)
        top_b = boxes_img[top_i]
        keep = nms_keep_mask(top_b, top_s, nms_thresh, eta, normalized)
        s_out = jnp.where(keep, top_s, -jnp.inf)
        return top_b, s_out

    def one(boxes_img, scores_img):
        cls_ids = [i for i in range(c) if i != bg]
        bs, ss = jax.vmap(lambda s_c: per_class(boxes_img, s_c))(scores_img[jnp.asarray(cls_ids)])
        labels = jnp.repeat(jnp.asarray(cls_ids, jnp.float32), k1)
        flat_b = bs.reshape(-1, 4)
        flat_s = ss.reshape(-1)
        kk = min(ktot, flat_s.shape[0])
        sel_s, sel_i = jax.lax.top_k(flat_s, kk)
        sel_b = flat_b[sel_i]
        sel_l = labels[sel_i]
        valid = sel_s > -jnp.inf
        out = jnp.concatenate([sel_l[:, None], sel_s[:, None], sel_b], axis=1)
        out = jnp.where(valid[:, None], out, -1.0)
        n_pad = ktot - kk
        if n_pad:
            out = jnp.concatenate([out, jnp.full((n_pad, 6), -1.0, out.dtype)], axis=0)
        return out, jnp.sum(valid.astype(jnp.int32))

    out, length = jax.vmap(one)(bboxes, scores)
    ctx.set_output("Out", out)
    ctx.set_output("Length", length)
    ctx.set_output("Index", length)  # alias slot some callers wire


# -- RoI pooling --------------------------------------------------------------


def _roi_align_single(feat, roi, pooled_h, pooled_w, scale, sampling, off):
    """feat [C,H,W], roi [4] xyxy (input coords) → [C, ph, pw].
    reference: operators/roi_align_op.cc (sampling_ratio<=0 → 2 samples,
    a documented static-shape deviation from the adaptive ceil)."""
    c, h, w = feat.shape
    x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, roi[2] * scale, roi[3] * scale
    rw = jnp.maximum(x2 - x1, 1.0 if off else 1e-6)
    rh = jnp.maximum(y2 - y1, 1.0 if off else 1e-6)
    bin_w = rw / pooled_w
    bin_h = rh / pooled_h
    s = sampling if sampling > 0 else 2
    # sample grid: [ph, pw, s, s]
    iy = jnp.arange(s, dtype=feat.dtype) + 0.5
    ix = jnp.arange(s, dtype=feat.dtype) + 0.5
    py = jnp.arange(pooled_h, dtype=feat.dtype)
    px = jnp.arange(pooled_w, dtype=feat.dtype)
    ys = y1 + py[:, None] * bin_h + iy[None, :] * bin_h / s  # [ph, s]
    xs = x1 + px[:, None] * bin_w + ix[None, :] * bin_w / s  # [pw, s]

    def bilinear(yy, xx):
        yy = jnp.clip(yy, 0.0, h - 1.0)
        xx = jnp.clip(xx, 0.0, w - 1.0)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        ly = yy - y0
        lx = xx - x0
        v00 = feat[:, y0, x0]
        v01 = feat[:, y0, x1i]
        v10 = feat[:, y1i, x0]
        v11 = feat[:, y1i, x1i]
        return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
                + v10 * ly * (1 - lx) + v11 * ly * lx)

    # all sample points [ph, pw, s, s]
    yy = jnp.broadcast_to(ys[:, None, :, None], (pooled_h, pooled_w, s, s))
    xx = jnp.broadcast_to(xs[None, :, None, :], (pooled_h, pooled_w, s, s))
    vals = bilinear(yy.reshape(-1), xx.reshape(-1))      # [C, ph*pw*s*s]
    vals = vals.reshape(c, pooled_h, pooled_w, s, s)
    return jnp.mean(vals, axis=(3, 4))


@register_op("roi_align")
def roi_align_op(ctx: OpContext):
    """X [N,C,H,W], ROIs [R,4] + BatchId [R] (dense replacement for the
    reference's LoD roi batching) → [R, C, ph, pw]."""
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    batch_id = ctx.input("BatchId")
    if batch_id is None:
        batch_id = jnp.zeros((rois.shape[0],), jnp.int32)
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    sampling = int(ctx.attr("sampling_ratio", -1))

    def one(roi, bid):
        return _roi_align_single(x[bid], roi, ph, pw, scale, sampling, off=False)

    ctx.set_output("Out", jax.vmap(one)(rois, batch_id.astype(jnp.int32)))


@register_op("roi_pool")
def roi_pool_op(ctx: OpContext):
    """Max-pool RoI (reference: operators/roi_pool_op.cc). Integer bin
    boundaries like the reference (rounded roi coords)."""
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    batch_id = ctx.input("BatchId")
    if batch_id is None:
        batch_id = jnp.zeros((rois.shape[0],), jnp.int32)
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    n, c, h, w = x.shape

    ygrid = jnp.arange(h, dtype=jnp.float32)
    xgrid = jnp.arange(w, dtype=jnp.float32)

    def one(roi, bid):
        feat = x[bid]
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bw = rw / pw
        bh = rh / ph

        def bin_val(i, j):
            ys = jnp.clip(jnp.floor(y1 + i * bh), 0, h)
            ye = jnp.clip(jnp.ceil(y1 + (i + 1) * bh), 0, h)
            xs = jnp.clip(jnp.floor(x1 + j * bw), 0, w)
            xe = jnp.clip(jnp.ceil(x1 + (j + 1) * bw), 0, w)
            mask = ((ygrid[:, None] >= ys) & (ygrid[:, None] < ye)
                    & (xgrid[None, :] >= xs) & (xgrid[None, :] < xe))
            empty = ~jnp.any(mask)
            v = jnp.max(jnp.where(mask[None], feat, -jnp.inf), axis=(1, 2))
            return jnp.where(empty, 0.0, v)

        rows = [jnp.stack([bin_val(i, j) for j in range(pw)], axis=-1) for i in range(ph)]
        return jnp.stack(rows, axis=-2)  # [C, ph, pw]

    ctx.set_output("Out", jax.vmap(one)(rois, batch_id.astype(jnp.int32)))


# -- mine_hard_examples -------------------------------------------------------


@register_op("mine_hard_examples")
def mine_hard_examples_op(ctx: OpContext):
    """reference: detection/mine_hard_examples_op.cc (max_negative mining).

    ClsLoss [B, P], MatchIndices [B, P] → UpdatedMatchIndices [B, P] (hard
    negatives stay -1... positives kept; easy negatives set to -1) and
    NegMask [B, P] (our static-shape replacement for the reference's LoD
    NegIndices: a 0/1 mask of selected hard negatives).
    """
    cls_loss = ctx.input("ClsLoss")
    match = ctx.input("MatchIndices")
    neg_pos_ratio = float(ctx.attr("neg_pos_ratio", 1.0))
    neg_overlap = float(ctx.attr("neg_dist_threshold", 0.5))
    match_dist = ctx.input("MatchDist")

    def one(loss_b, m_b, d_b):
        pos = m_b >= 0
        n_pos = jnp.sum(pos.astype(jnp.int32))
        n_neg = jnp.minimum((n_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32),
                            m_b.shape[0])
        cand = (~pos) & (d_b < neg_overlap) if d_b is not None else ~pos
        neg_loss = jnp.where(cand, loss_b, -jnp.inf)
        order = jnp.argsort(-neg_loss)
        rank = jnp.zeros_like(m_b).at[order].set(jnp.arange(m_b.shape[0], dtype=m_b.dtype))
        neg_mask = cand & (rank < n_neg) & jnp.isfinite(neg_loss)
        return neg_mask.astype(jnp.int32)

    if match_dist is None:
        match_dist = jnp.ones_like(cls_loss)
    neg_mask = jax.vmap(one)(cls_loss, match, match_dist)
    ctx.set_output("NegMask", neg_mask)
    ctx.set_output("UpdatedMatchIndices", match)


# -- polygon_box_transform ----------------------------------------------------


@register_op("polygon_box_transform")
def polygon_box_transform_op(ctx: OpContext):
    """reference: detection/polygon_box_transform_op.cc — offsets→absolute
    quad coords: out[c] = 4*(idx) + in[c] per axis pair."""
    x = ctx.input("Input")  # [N, geo(8), H, W]
    n, g, h, w = x.shape
    ix = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, None, None, :], x.shape)
    iy = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[None, None, :, None], x.shape)
    is_x = (jnp.arange(g) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, ix, iy) * 4.0
    ctx.set_output("Output", base - x)


# -- yolov3_loss --------------------------------------------------------------


@register_op("yolov3_loss")
def yolov3_loss_op(ctx: OpContext):
    """reference: detection/yolov3_loss_op.cc (v1.3 semantics).

    X [N, A*(5+C), H, W]; GTBox [N, B, 4] (cx, cy, w, h normalized to [0,1],
    rows with w*h<=0 are padding); GTLabel [N, B] int. Loss [N]:
    BCE(x,y)+L1(w,h) weighted (2 - w*h) for matched cells, objectness BCE
    with ignore_thresh masking, class BCE.
    """
    x = ctx.input("X")
    gtbox = ctx.input("GTBox").astype(jnp.float32)
    gtlabel = ctx.input("GTLabel").astype(jnp.int32)
    anchors = [float(a) for a in ctx.attr("anchors", [])]
    mask = [int(i) for i in ctx.attr("anchor_mask", []) or list(range(len(anchors) // 2))]
    class_num = int(ctx.attr("class_num"))
    ignore = float(ctx.attr("ignore_thresh", 0.7))
    down = int(ctx.attr("downsample_ratio", 32))

    n, _, h, w = x.shape
    na = len(mask)
    all_anchors = np.asarray(anchors, np.float32).reshape(-1, 2)  # [A_all, 2]
    m_anchors = all_anchors[mask]                                  # [na, 2]
    in_h, in_w = h * down, w * down

    x5 = x.reshape(n, na, 5 + class_num, h, w).astype(jnp.float32)
    tx, ty = x5[:, :, 0], x5[:, :, 1]
    tw, th = x5[:, :, 2], x5[:, :, 3]
    tobj = x5[:, :, 4]
    tcls = x5[:, :, 5:]                                            # [N,na,C,H,W]

    # predicted boxes (normalized cxcywh) for the ignore-mask IoU test
    gx = (jax.nn.sigmoid(tx) + jnp.arange(w, dtype=jnp.float32)[None, None, None, :]) / w
    gy = (jax.nn.sigmoid(ty) + jnp.arange(h, dtype=jnp.float32)[None, None, :, None]) / h
    gw = jnp.exp(tw) * m_anchors[None, :, 0, None, None] / in_w
    gh = jnp.exp(th) * m_anchors[None, :, 1, None, None] / in_h
    pred = jnp.stack([gx, gy, gw, gh], axis=-1)                    # [N,na,H,W,4]

    gt_valid = (gtbox[..., 2] > 0) & (gtbox[..., 3] > 0)           # [N, B]

    def cxcywh_iou(a, b):
        # a [..., 4], b [..., 4] normalized cxcywh
        ax1, ay1 = a[..., 0] - a[..., 2] / 2, a[..., 1] - a[..., 3] / 2
        ax2, ay2 = a[..., 0] + a[..., 2] / 2, a[..., 1] + a[..., 3] / 2
        bx1, by1 = b[..., 0] - b[..., 2] / 2, b[..., 1] - b[..., 3] / 2
        bx2, by2 = b[..., 0] + b[..., 2] / 2, b[..., 1] + b[..., 3] / 2
        iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
        ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
        inter = iw * ih
        union = a[..., 2] * a[..., 3] + b[..., 2] * b[..., 3] - inter
        return jnp.where(union > 0, inter / union, 0.0)

    # ignore mask: max IoU of each prediction vs any gt > thresh → no noobj loss
    iou_pg = cxcywh_iou(pred[:, :, :, :, None, :],
                        gtbox[:, None, None, None, :, :])          # [N,na,H,W,B]
    iou_pg = jnp.where(gt_valid[:, None, None, None, :], iou_pg, 0.0)
    ignore_mask = jnp.max(iou_pg, axis=-1) > ignore                # [N,na,H,W]

    # gt → (anchor, cell) assignment: best anchor over ALL anchors by wh-IoU
    gtw = gtbox[..., 2] * in_w
    gth = gtbox[..., 3] * in_h
    inter = (jnp.minimum(gtw[..., None], all_anchors[None, None, :, 0])
             * jnp.minimum(gth[..., None], all_anchors[None, None, :, 1]))
    union = (gtw * gth)[..., None] + all_anchors[None, None, :, 0] * all_anchors[None, None, :, 1] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N, B]
    # which of *our* mask slots that is (-1 if not in mask)
    slot = jnp.full_like(best_anchor, -1)
    for s_i, a_i in enumerate(mask):
        slot = jnp.where(best_anchor == a_i, s_i, slot)

    gi = jnp.clip((gtbox[..., 0] * w).astype(jnp.int32), 0, w - 1)  # [N, B]
    gj = jnp.clip((gtbox[..., 1] * h).astype(jnp.int32), 0, h - 1)
    assigned = gt_valid & (slot >= 0)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def per_image(tx_i, ty_i, tw_i, th_i, tobj_i, tcls_i, box_i, lab_i,
                  slot_i, gi_i, gj_i, ok_i, ignore_i):
        # scatter gt targets onto the [na, H, W] lattice
        obj_t = jnp.zeros((na, h, w), jnp.float32)
        sl = jnp.where(ok_i, slot_i, 0)
        gii = jnp.where(ok_i, gi_i, 0)
        gjj = jnp.where(ok_i, gj_i, 0)
        obj_t = obj_t.at[sl, gjj, gii].max(ok_i.astype(jnp.float32))

        # per-gt losses gathered at the assigned cell
        sx = box_i[:, 0] * w - gii
        sy = box_i[:, 1] * h - gjj
        aw = jnp.asarray(m_anchors[:, 0])[sl]
        ah = jnp.asarray(m_anchors[:, 1])[sl]
        swt = jnp.log(jnp.maximum(box_i[:, 2] * in_w / aw, 1e-9))
        sht = jnp.log(jnp.maximum(box_i[:, 3] * in_h / ah, 1e-9))
        wgt = 2.0 - box_i[:, 2] * box_i[:, 3]

        px = tx_i[sl, gjj, gii]
        py = ty_i[sl, gjj, gii]
        pw_ = tw_i[sl, gjj, gii]
        ph_ = th_i[sl, gjj, gii]
        loc = (bce(px, sx) + bce(py, sy)) * wgt + (jnp.abs(pw_ - swt) + jnp.abs(ph_ - sht)) * wgt
        cls_logits = tcls_i[sl, :, gjj, gii]                       # [B, C]
        cls_t = jax.nn.one_hot(lab_i, class_num)
        cls_l = jnp.sum(bce(cls_logits, cls_t), axis=-1)
        per_gt = jnp.where(ok_i, loc + cls_l, 0.0)

        obj_l = jnp.where(obj_t > 0, bce(tobj_i, 1.0), 0.0)
        noobj_l = jnp.where((obj_t == 0) & ~ignore_i, bce(tobj_i, 0.0), 0.0)
        return jnp.sum(per_gt) + jnp.sum(obj_l) + jnp.sum(noobj_l)

    loss = jax.vmap(per_image)(tx, ty, tw, th, tobj, tcls, gtbox, gtlabel,
                               slot, gi, gj, assigned, ignore_mask)
    ctx.set_output("Loss", loss)


# -- generate_proposals -------------------------------------------------------


@register_op("generate_proposals")
def generate_proposals_op(ctx: OpContext):
    """RPN proposal generation (reference: detection/generate_proposals_op.cc).

    Scores [B, A, H, W], BboxDeltas [B, 4A, H, W], ImInfo [B, 3],
    Anchors [H, W, A, 4], Variances like Anchors →
    RpnRois [B, post_nms_topN, 4] (padded -1) + Length [B].
    """
    scores = ctx.input("Scores")
    deltas = ctx.input("BboxDeltas")
    im_info = ctx.input("ImInfo")
    anchors = ctx.input("Anchors").reshape(-1, 4)
    variances = ctx.input("Variances").reshape(-1, 4)
    pre_n = int(ctx.attr("pre_nms_topN", 6000))
    post_n = int(ctx.attr("post_nms_topN", 1000))
    nms_thresh = float(ctx.attr("nms_thresh", 0.7))
    min_size = float(ctx.attr("min_size", 0.1))

    b, a, h, w = scores.shape
    total = a * h * w
    pre_n = min(pre_n, total)
    # [B, A, H, W] → [B, H*W*A] matching anchor layout [H, W, A]
    sc = scores.transpose(0, 2, 3, 1).reshape(b, -1)
    dl = deltas.reshape(b, a, 4, h, w).transpose(0, 3, 4, 1, 2).reshape(b, -1, 4)

    def one(s, d, info):
        top_s, top_i = jax.lax.top_k(s, pre_n)
        anc = anchors[top_i]
        var = variances[top_i]
        # decode (unnormalized center-size with variance scaling)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        t = d[top_i] * var
        cx = t[:, 0] * aw + acx
        cy = t[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(t[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(t[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                           cx + bw * 0.5 - 1.0, cy + bh * 0.5 - 1.0], axis=1)
        # clip to image
        hh, ww = info[0] - 1.0, info[1] - 1.0
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, ww), jnp.clip(boxes[:, 1], 0, hh),
            jnp.clip(boxes[:, 2], 0, ww), jnp.clip(boxes[:, 3], 0, hh)], axis=1)
        # filter tiny boxes (scale-adjusted min_size)
        ms = min_size * info[2]
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1.0 >= ms)
                   & (boxes[:, 3] - boxes[:, 1] + 1.0 >= ms))
        s_f = jnp.where(keep_sz, top_s, -jnp.inf)
        keep = nms_keep_mask(boxes, s_f, nms_thresh, normalized=False)
        s_k = jnp.where(keep, s_f, -jnp.inf)
        kk = min(post_n, pre_n)
        fin_s, fin_i = jax.lax.top_k(s_k, kk)
        rois = boxes[fin_i]
        valid = fin_s > -jnp.inf
        rois = jnp.where(valid[:, None], rois, -1.0)
        probs = jnp.where(valid, fin_s, -1.0)[:, None]
        if post_n > kk:
            rois = jnp.concatenate(
                [rois, jnp.full((post_n - kk, 4), -1.0, rois.dtype)], axis=0)
            probs = jnp.concatenate(
                [probs, jnp.full((post_n - kk, 1), -1.0, probs.dtype)], axis=0)
        return rois, probs, jnp.sum(valid.astype(jnp.int32))

    rois, probs, length = jax.vmap(one)(sc, dl, im_info)
    ctx.set_output("RpnRois", rois)
    ctx.set_output("RpnRoiProbs", probs)
    ctx.set_output("Length", length)


# -- two-stage detector training samplers -------------------------------------


def _subsample_mask(key, eligible, k):
    """Pick ≤k True positions from ``eligible`` uniformly at random →
    bool mask (the reference's ReservoirSampling, made shape-static: rank
    eligible rows by random scores, keep the first min(k, #eligible))."""
    n = eligible.shape[0]
    scores = jnp.where(eligible, jax.random.uniform(key, (n,)), -1.0)
    n_elig = jnp.sum(eligible.astype(jnp.int32))
    take = jnp.minimum(n_elig, k)
    order = jnp.argsort(-scores)
    rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    return eligible & (rank < take)


@register_op("rpn_target_assign")
def rpn_target_assign_op(ctx: OpContext):
    """RPN anchor sampling (reference: detection/rpn_target_assign_op.cc).

    Anchor [A, 4]; GtBoxes [B, Ng, 4] dense (zero-area rows pad); ImInfo
    [B, 3]. The reference emits variable-length index lists (LocationIndex/
    ScoreIndex); the static redesign emits per-anchor masks and targets:
    ScoreMask [B, A] ∈ {-1: ignore, 0: bg sample, 1: fg sample},
    TargetLabel [B, A], TargetBBox [B, A, 4] (encoded deltas),
    BBoxInsideWeight [B, A, 4]. Sampling honors rpn_fg_fraction /
    rpn_batch_size_per_im with use_random.
    """
    anchors = ctx.input("Anchor").reshape(-1, 4)
    gt = ctx.input("GtBoxes")
    im_info = ctx.input("ImInfo")
    bs_per_im = int(ctx.attr("rpn_batch_size_per_im", 256))
    straddle = float(ctx.attr("rpn_straddle_thresh", 0.0))
    fg_frac = float(ctx.attr("rpn_fg_fraction", 0.5))
    pos_ov = float(ctx.attr("rpn_positive_overlap", 0.7))
    neg_ov = float(ctx.attr("rpn_negative_overlap", 0.3))
    use_random = ctx.attr("use_random", True)
    base_key = ctx.rng()
    a = anchors.shape[0]
    fg_target = int(bs_per_im * fg_frac)

    def one(gt_b, info, key):
        valid_gt = (gt_b[:, 2] > gt_b[:, 0]) & (gt_b[:, 3] > gt_b[:, 1])
        h, w = info[0], info[1]
        inside = ((anchors[:, 0] >= -straddle) & (anchors[:, 1] >= -straddle)
                  & (anchors[:, 2] < w + straddle) & (anchors[:, 3] < h + straddle)) \
            if straddle >= 0 else jnp.ones((a,), bool)
        iou = pairwise_iou(anchors, gt_b, normalized=False)   # [A, Ng]
        iou = jnp.where(valid_gt[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        # fg: (a) argmax anchor per gt, (b) iou > pos_ov
        per_gt_best = jnp.max(jnp.where(inside[:, None], iou, -1.0), axis=0)
        is_gt_best = jnp.any(
            (iou == per_gt_best[None, :]) & valid_gt[None, :] & (per_gt_best[None, :] > 0),
            axis=1)
        fg_elig = inside & (is_gt_best | (best_iou >= pos_ov))
        bg_elig = inside & (best_iou < neg_ov) & ~fg_elig
        k1, k2 = jax.random.split(key)
        if use_random:
            fg = _subsample_mask(k1, fg_elig, jnp.asarray(fg_target))
        else:
            rank = jnp.cumsum(fg_elig.astype(jnp.int32)) - 1
            fg = fg_elig & (rank < fg_target)
        n_fg = jnp.sum(fg.astype(jnp.int32))
        n_bg = bs_per_im - n_fg
        if use_random:
            bg = _subsample_mask(k2, bg_elig, n_bg)
        else:
            rank = jnp.cumsum(bg_elig.astype(jnp.int32)) - 1
            bg = bg_elig & (rank < n_bg)
        score_mask = jnp.where(fg, 1, jnp.where(bg, 0, -1)).astype(jnp.int32)
        # encoded regression targets vs matched gt (variance-free, like the
        # reference's BoxToDelta with weights=1)
        g = gt_b[best_gt]
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw * 0.5
        acy = anchors[:, 1] + ah * 0.5
        gw = g[:, 2] - g[:, 0] + 1.0
        gh = g[:, 3] - g[:, 1] + 1.0
        gcx = g[:, 0] + gw * 0.5
        gcy = g[:, 1] + gh * 0.5
        tgt = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                         jnp.log(jnp.maximum(gw / aw, 1e-6)),
                         jnp.log(jnp.maximum(gh / ah, 1e-6))], axis=1)
        tgt = jnp.where(fg[:, None], tgt, 0.0)
        inw = jnp.where(fg[:, None], 1.0, 0.0) * jnp.ones((1, 4))
        return score_mask, fg.astype(jnp.int32), tgt, inw

    b = gt.shape[0]
    keys = jax.random.split(base_key, b)
    score_mask, lbl, tgt, inw = jax.vmap(one)(gt, im_info, keys)
    ctx.set_output("ScoreMask", score_mask)
    ctx.set_output("TargetLabel", lbl)
    ctx.set_output("TargetBBox", tgt)
    ctx.set_output("BBoxInsideWeight", inw)


@register_op("generate_proposal_labels")
def generate_proposal_labels_op(ctx: OpContext):
    """Second-stage RoI sampling (reference:
    detection/generate_proposal_labels_op.cc). RpnRois [B, R, 4] (padded
    -1), GtClasses [B, Ng], GtBoxes [B, Ng, 4] →
    Rois [B, batch_size_per_im, 4], LabelsInt32 [B, S] (−1 pads),
    BboxTargets [B, S, 4·C], BboxInsideWeights / BboxOutsideWeights same
    shape, RoiWeights [B, S] (1 for sampled rows).
    """
    rois = ctx.input("RpnRois")
    gt_classes = ctx.input("GtClasses").astype(jnp.int32)
    gt_boxes = ctx.input("GtBoxes")
    bs = int(ctx.attr("batch_size_per_im", 256))
    fg_frac = float(ctx.attr("fg_fraction", 0.25))
    fg_thresh = float(ctx.attr("fg_thresh", 0.5))
    bg_hi = float(ctx.attr("bg_thresh_hi", 0.5))
    bg_lo = float(ctx.attr("bg_thresh_lo", 0.0))
    weights = [float(v) for v in ctx.attr("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2])]
    class_nums = int(ctx.attr("class_nums"))
    use_random = ctx.attr("use_random", True)
    base_key = ctx.rng()
    fg_target = int(bs * fg_frac)

    def one(rois_b, cls_b, gt_b, key):
        valid_gt = (gt_b[:, 2] > gt_b[:, 0]) & (gt_b[:, 3] > gt_b[:, 1])
        # candidate set = proposals + gt boxes (the reference concatenates)
        cand = jnp.concatenate([rois_b, gt_b], axis=0)
        cand_valid = jnp.concatenate([
            rois_b[:, 2] > rois_b[:, 0], valid_gt], axis=0)
        iou = pairwise_iou(cand, gt_b, normalized=False)
        iou = jnp.where(valid_gt[None, :], iou, 0.0)
        best = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        fg_elig = cand_valid & (best_iou >= fg_thresh)
        bg_elig = cand_valid & (best_iou < bg_hi) & (best_iou >= bg_lo)
        k1, k2 = jax.random.split(key)
        if use_random:
            fg = _subsample_mask(k1, fg_elig, jnp.asarray(fg_target))
        else:
            fg = fg_elig & (jnp.cumsum(fg_elig.astype(jnp.int32)) - 1 < fg_target)
        n_fg = jnp.sum(fg.astype(jnp.int32))
        n_bg = bs - n_fg
        if use_random:
            bg = _subsample_mask(k2, bg_elig, n_bg)
        else:
            bg = bg_elig & (jnp.cumsum(bg_elig.astype(jnp.int32)) - 1 < n_bg)
        chosen = fg | bg
        # pack chosen rows to the front (stable) → fixed S = bs rows
        order = jnp.argsort(~chosen)          # False<True: chosen first
        take = order[:bs]
        sel = chosen[take]
        out_rois = jnp.where(sel[:, None], cand[take], -1.0)
        labels = jnp.where(fg[take], cls_b[best[take]], 0)
        labels = jnp.where(sel, labels, -1).astype(jnp.int32)
        # encoded targets against matched gt, one-hot per class
        g = gt_b[best[take]]
        r = cand[take]
        rw = r[:, 2] - r[:, 0] + 1.0
        rh = r[:, 3] - r[:, 1] + 1.0
        rcx = r[:, 0] + rw * 0.5
        rcy = r[:, 1] + rh * 0.5
        gw = jnp.maximum(g[:, 2] - g[:, 0] + 1.0, 1e-6)
        gh = jnp.maximum(g[:, 3] - g[:, 1] + 1.0, 1e-6)
        gcx = g[:, 0] + gw * 0.5
        gcy = g[:, 1] + gh * 0.5
        wv = jnp.asarray(weights)
        t = jnp.stack([(gcx - rcx) / rw / wv[0], (gcy - rcy) / rh / wv[1],
                       jnp.log(gw / rw) / wv[2], jnp.log(gh / rh) / wv[3]], axis=1)
        is_fg = fg[take] & sel
        onehot = jax.nn.one_hot(jnp.maximum(labels, 0), class_nums)  # [S, C]
        t_full = (onehot[:, :, None] * t[:, None, :]).reshape(bs, 4 * class_nums)
        t_full = jnp.where(is_fg[:, None], t_full, 0.0)
        iw = jnp.broadcast_to(
            (onehot * is_fg[:, None])[:, :, None], (bs, class_nums, 4)
        ).reshape(bs, 4 * class_nums)
        return out_rois, labels, t_full, iw, sel.astype(jnp.float32)

    b = rois.shape[0]
    keys = jax.random.split(base_key, b)
    out_rois, labels, tgts, iw, roiw = jax.vmap(one)(rois, gt_classes, gt_boxes, keys)
    ctx.set_output("Rois", out_rois)
    ctx.set_output("LabelsInt32", labels)
    ctx.set_output("BboxTargets", tgts)
    ctx.set_output("BboxInsideWeights", iw)
    ctx.set_output("BboxOutsideWeights", iw)
    ctx.set_output("RoiWeights", roiw)


# -- mask targets + perspective RoIs ------------------------------------------


def _point_in_polygon(px, py, verts, n_verts):
    """Even-odd rule, vectorized: px/py [...], verts [L, 2], n_verts scalar →
    bool [...]. Padded vertices beyond n_verts are ignored."""
    l = verts.shape[0]
    idx = jnp.arange(l)
    nxt = jnp.where(idx + 1 < n_verts, idx + 1, 0)
    x1, y1 = verts[:, 0], verts[:, 1]
    x2, y2 = verts[nxt, 0], verts[nxt, 1]
    valid = idx < n_verts
    pxe = px[..., None]
    pye = py[..., None]
    cond = (y1 > pye) != (y2 > pye)
    slope_x = x1 + (pye - y1) * (x2 - x1) / jnp.where(y2 == y1, 1e-9, y2 - y1)
    crossing = cond & (pxe < slope_x) & valid
    return jnp.sum(crossing.astype(jnp.int32), axis=-1) % 2 == 1


@register_op("generate_mask_labels")
def generate_mask_labels_op(ctx: OpContext):
    """Mask R-CNN mask targets (reference:
    detection/generate_mask_labels_op.cc + mask_util.cc poly rasterization).

    Dense redesign: GtSegms [B, Ng, L, 2] padded polygon vertices (one
    polygon per gt) + GtPolyLength [B, Ng] vertex counts replace the 3-level
    LoD; Rois [B, S, 4] with LabelsInt32 [B, S] from
    generate_proposal_labels. Outputs MaskInt32 [B, S, num_classes·R·R]
    (−1 everywhere except the matched class's R×R block for fg rois) and
    RoiHasMaskInt32 [B, S].
    """
    rois = ctx.input("Rois")
    labels = ctx.input("LabelsInt32").astype(jnp.int32)
    segms = ctx.input("GtSegms").astype(jnp.float32)
    poly_len = ctx.input("GtPolyLength")
    gt_classes = ctx.input("GtClasses").astype(jnp.int32)
    num_classes = int(ctx.attr("num_classes"))
    r = int(ctx.attr("resolution", 14))
    b, s, _ = rois.shape
    ng, l = segms.shape[1], segms.shape[2]
    if poly_len is None:
        poly_len = jnp.full((b, ng), l, jnp.int32)

    def one(rois_b, lab_b, segms_b, plen_b, cls_b):
        # gt boxes from polygons (for roi↔gt matching)
        vmask = (jnp.arange(l)[None, :] < plen_b[:, None])[..., None]
        big = jnp.where(vmask, segms_b, jnp.inf)
        small = jnp.where(vmask, segms_b, -jnp.inf)
        gt_boxes = jnp.concatenate([jnp.min(big, axis=1), jnp.max(small, axis=1)], 1)
        valid_gt = plen_b >= 3
        iou = pairwise_iou(rois_b, gt_boxes, normalized=False)
        iou = jnp.where(valid_gt[None, :], iou, -1.0)
        best = jnp.argmax(iou, axis=1)                       # [S]
        is_fg = lab_b > 0

        ys = (jnp.arange(r, dtype=jnp.float32) + 0.5) / r
        xs = (jnp.arange(r, dtype=jnp.float32) + 0.5) / r
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")         # [R, R]

        def mask_for(roi, gt_i):
            px = roi[0] + gx * jnp.maximum(roi[2] - roi[0], 1e-6)
            py = roi[1] + gy * jnp.maximum(roi[3] - roi[1], 1e-6)
            return _point_in_polygon(px, py, segms_b[gt_i],
                                     plen_b[gt_i]).astype(jnp.int32)

        masks = jax.vmap(mask_for)(rois_b, best)             # [S, R, R]
        cls_of = cls_b[best]                                 # [S]
        onehot = jax.nn.one_hot(cls_of, num_classes, dtype=jnp.int32)
        full = onehot[:, :, None, None] * masks[:, None, :, :]  # [S, C, R, R]
        # reference packs non-target entries as -1 (tgt_blk already excludes
        # bg rois, so no separate is_fg zeroing is needed)
        tgt_blk = (onehot[:, :, None, None] == 1) & is_fg[:, None, None, None]
        packed = jnp.where(tgt_blk, full, -1)
        return packed.reshape(s, num_classes * r * r), is_fg.astype(jnp.int32)

    mask, has = jax.vmap(one)(rois, labels, segms, poly_len.astype(jnp.int32),
                              gt_classes)
    ctx.set_output("MaskInt32", mask)
    ctx.set_output("RoiHasMaskInt32", has)


@register_op("roi_perspective_transform")
def roi_perspective_transform_op(ctx: OpContext):
    """Perspective-warp quadrilateral RoIs to a fixed rectangle (reference:
    detection/roi_perspective_transform_op.cc — OCR text RoIs). ROIs
    [R, 8] quad corners (x1..y4, clockwise from top-left) + BatchId [R];
    bilinear sampling of the warped grid → [R, C, H, W]."""
    x = ctx.input("X")
    rois = ctx.input("ROIs").astype(jnp.float32)
    batch_id = ctx.input("BatchId")
    if batch_id is None:
        batch_id = jnp.zeros((rois.shape[0],), jnp.int32)
    oh = int(ctx.attr("transformed_height"))
    ow = int(ctx.attr("transformed_width"))
    scale = float(ctx.attr("spatial_scale", 1.0))
    n, c, h, w = x.shape

    # normalized output grid
    gy, gx = jnp.meshgrid((jnp.arange(oh) + 0.5) / oh,
                          (jnp.arange(ow) + 0.5) / ow, indexing="ij")

    def one(quad, bid):
        q = quad.reshape(4, 2) * scale  # tl, tr, br, bl
        x0, y0 = q[0, 0], q[0, 1]
        x1, y1 = q[1, 0], q[1, 1]
        x2, y2 = q[2, 0], q[2, 1]
        x3, y3 = q[3, 0], q[3, 1]
        # full projective transform unit square → quad (the reference's
        # get_transform_matrix, closed form): (u,v) ↦
        # ((a·u + b·v + c) / w, (d·u + e·v + f) / w), w = g·u + h·v + 1
        sx = x0 - x1 + x2 - x3
        sy = y0 - y1 + y2 - y3
        dx1 = x1 - x2
        dx2 = x3 - x2
        dy1 = y1 - y2
        dy2 = y3 - y2
        den = dx1 * dy2 - dy1 * dx2
        den = jnp.where(jnp.abs(den) < 1e-12, 1e-12, den)
        g = (sx * dy2 - sy * dx2) / den
        h_ = (dx1 * sy - dy1 * sx) / den
        a = x1 - x0 + g * x1
        b_ = x3 - x0 + h_ * x3
        c = x0
        d_ = y1 - y0 + g * y1
        e = y3 - y0 + h_ * y3
        f = y0
        wgt = g * gx + h_ * gy + 1.0
        wgt = jnp.where(jnp.abs(wgt) < 1e-12, 1e-12, wgt)
        px = (a * gx + b_ * gy + c) / wgt
        py = (d_ * gx + e * gy + f) / wgt
        # distinct names from the homography coefficients/corners above —
        # do not rename back to g/x0/y0 (shadowing trap)
        ix0 = jnp.floor(px)
        iy0 = jnp.floor(py)
        lx = px - ix0
        ly = py - iy0

        def gather(yy, xx):
            inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            v = x[bid][:, yc, xc]
            return jnp.where(inb[None], v, 0.0)

        out = (gather(iy0, ix0) * (1 - ly) * (1 - lx)
               + gather(iy0, ix0 + 1) * (1 - ly) * lx
               + gather(iy0 + 1, ix0) * ly * (1 - lx)
               + gather(iy0 + 1, ix0 + 1) * ly * lx)
        return out

    ctx.set_output("Out", jax.vmap(one)(rois, batch_id.astype(jnp.int32)))


@register_op("detection_map")
def detection_map_op(ctx: OpContext):
    """mAP over padded detections (reference: operators/detection_map_op.cc).

    DetectRes [B, K, 6] (label, score, x1, y1, x2, y2; -1 pad rows),
    Label [B, Ng, 5] (label, x1, y1, x2, y2; zero-area pad rows), optional
    DetLength [B]. Matching/AP run on host via pure_callback (branchy
    per-box logic, negligible next to the detector itself); per-batch mAP
    only — cross-batch accumulation lives in metrics.DetectionMAP.
    """
    import jax

    det = ctx.input("DetectRes")
    gt = ctx.input("Label")
    det_len = ctx.input("DetLength")
    if det_len is None:
        det_len = jnp.full((det.shape[0],), det.shape[1], jnp.int32)
    overlap = ctx.attr("overlap_threshold", 0.5)
    ap_version = ctx.attr("ap_type", "integral")
    background = int(ctx.attr("background_label", 0))
    if not ctx.attr("evaluate_difficult", True):
        # the padded 5-col gt rows carry no difficult flag to exclude
        raise NotImplementedError(
            "detection_map: evaluate_difficult=False needs per-gt difficult "
            "flags, which the padded [label,x1,y1,x2,y2] convention does not "
            "carry — filter difficult gts out of the feed instead")

    def host_map(det_h, len_h, gt_h):
        import numpy as np

        from ..metrics import DetectionMAP

        det_h = np.array(det_h, copy=True)
        gt_h = np.array(gt_h, copy=True)
        if background >= 0:
            # background rows don't score: void matched det rows and
            # zero-area the background gts (the metric skips both)
            det_h[det_h[..., 0] == background] = -1.0
            gt_h[gt_h[..., 0] == background] = 0.0
        m = DetectionMAP(overlap_threshold=overlap, ap_version=ap_version)
        m.update(det_h, len_h, gt_h)
        return np.float32(m.eval())

    out = jax.pure_callback(
        host_map, jax.ShapeDtypeStruct((), jnp.float32), det, det_len, gt)
    ctx.set_output("MAP", out)
