"""Compare + logical ops (reference: operators/controlflow/compare_op.cc,
logical_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import OpContext, register_op

_CMP = {
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
}


def _make_cmp(fn):
    def impl(ctx: OpContext):
        x, y = ctx.input("X"), ctx.input("Y")
        if x.dtype != y.dtype:
            y = y.astype(x.dtype)
        ctx.set_output("Out", fn(x, y))

    return impl


for _name, _fn in _CMP.items():
    register_op(_name)(_make_cmp(_fn))


@register_op("logical_and")
def logical_and_op(ctx):
    ctx.set_output("Out", jnp.logical_and(ctx.input("X"), ctx.input("Y")))


@register_op("logical_or")
def logical_or_op(ctx):
    ctx.set_output("Out", jnp.logical_or(ctx.input("X"), ctx.input("Y")))


@register_op("logical_xor")
def logical_xor_op(ctx):
    ctx.set_output("Out", jnp.logical_xor(ctx.input("X"), ctx.input("Y")))


@register_op("logical_not")
def logical_not_op(ctx):
    ctx.set_output("Out", jnp.logical_not(ctx.input("X")))
