"""Recurrent ops: dynamic_lstm / dynamic_gru / stacked lstm / unit steps.

Reference: ``operators/lstm_op.cc``, ``operators/gru_op.cc``,
``operators/lstm_unit_op.cc``, ``operators/gru_unit_op.cc``,
``operators/cudnn_lstm_op.cu.cc`` and the shared compute kernels in
``operators/math/lstm_compute.cc`` / ``gru_compute.cc``.

TPU-native redesign: Fluid's LoD-packed sequences + per-timestep CPU/CUDA
kernels become one ``lax.scan`` over a padded batch-major tensor with a
``Length`` vector (the repo-wide padded+Length replacement for LoD, see
ops/sequence_ops.py). Each scan step is a fused matmul+gates block that XLA
maps onto the MXU; masking freezes carried state past each row's length and
zeroes padded outputs, which reproduces the variable-length semantics
bit-for-bit without ragged tensors or host loops.

Gate layout convention (documented, self-consistent with the layer API and
tests): the 4H projection splits as [i, f, c̃, o]; GRU's 3H splits as
[u, r, c̃] (update, reset, candidate).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "softsign": jax.nn.soft_sign,
}


def _act(name, default):
    return _ACT[name or default]


def _length_mask(length, batch, maxlen, dtype):
    """[B, T] 1.0 where t < length_b (all-ones when Length is absent)."""
    from .sequence_ops import _mask

    if length is None:
        return jnp.ones((batch, maxlen), dtype)
    return _mask(length, maxlen, dtype)


def _masked_scan(step, inits, xs_tm, mask_tm):
    """scan ``step`` over time-major xs; freeze carries and zero outputs on
    masked-out steps. step: (carries, x_t) -> (new_carries, outs_t)."""

    def body(carries, inp):
        x_t, m_t = inp
        new_carries, outs = step(carries, x_t)
        m = m_t[:, None]
        new_carries = tuple(
            m * nc + (1.0 - m) * c for nc, c in zip(new_carries, carries))
        outs = tuple(m * o for o in outs)
        return new_carries, outs

    return jax.lax.scan(body, inits, (xs_tm, mask_tm))


@register_op("dynamic_lstm")
def dynamic_lstm_op(ctx: OpContext):
    """Input [B,T,4H] (x-projection precomputed by an fc, as in the
    reference), Weight [H,4H] recurrent weights, Bias [1,4H] (or [1,7H] with
    peepholes: extra W_ic, W_fc, W_oc diagonals). Outputs Hidden/Cell [B,T,H].
    Reference: operators/lstm_op.cc, math/lstm_compute.cc."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    length = ctx.input("Length")
    hidden = w.shape[0]
    use_peepholes = bool(ctx.attr("use_peepholes", False))
    is_reverse = bool(ctx.attr("is_reverse", False))
    gate_act = _act(ctx.attr("gate_activation"), "sigmoid")
    cell_act = _act(ctx.attr("cell_activation"), "tanh")
    cand_act = _act(ctx.attr("candidate_activation"), "tanh")

    batch, maxlen = x.shape[0], x.shape[1]
    dt = x.dtype
    if bias is not None:
        b_gate = bias.reshape(-1)[: 4 * hidden]
        x = x + b_gate
        if use_peepholes:
            peep = bias.reshape(-1)[4 * hidden : 7 * hidden]
            w_ic, w_fc, w_oc = jnp.split(peep, 3)
        else:
            w_ic = w_fc = w_oc = None
    else:
        w_ic = w_fc = w_oc = None

    mask = _length_mask(length, batch, maxlen, dt)
    if is_reverse:
        x = jnp.flip(x, axis=1)
        mask = jnp.flip(mask, axis=1)

    xs_tm = jnp.swapaxes(x, 0, 1)  # [T,B,4H]
    mask_tm = jnp.swapaxes(mask, 0, 1)  # [T,B]
    h_init = h0 if h0 is not None else jnp.zeros((batch, hidden), dt)
    c_init = c0 if c0 is not None else jnp.zeros((batch, hidden), dt)

    def step(carries, x_t):
        h_prev, c_prev = carries
        gates = x_t + h_prev @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=1)
        if use_peepholes:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c = f * c_prev + i * cand_act(gc)
        if use_peepholes:
            go = go + c * w_oc
        o = gate_act(go)
        h = o * cell_act(c)
        return (h, c), (h, c)

    (_, _), (hs, cs) = _masked_scan(step, (h_init, c_init), xs_tm, mask_tm)
    hs = jnp.swapaxes(hs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, axis=1)
        cs = jnp.flip(cs, axis=1)
    ctx.set_output("Hidden", hs)
    ctx.set_output("Cell", cs)


@register_op("dynamic_lstmp")
def dynamic_lstmp_op(ctx: OpContext):
    """LSTM with a recurrent projection layer (reference: lstmp_op.cc):
    Weight is [P,4H] over the projected state r = proj_act(h @ ProjWeight),
    ProjWeight [H,P]. Outputs Projection [B,T,P] and Cell [B,T,H]."""
    x = ctx.input("Input")
    w = ctx.input("Weight")  # [P, 4H]
    w_proj = ctx.input("ProjWeight")  # [H, P]
    bias = ctx.input("Bias")
    length = ctx.input("Length")
    hidden = w_proj.shape[0]
    proj = w_proj.shape[1]
    is_reverse = bool(ctx.attr("is_reverse", False))
    gate_act = _act(ctx.attr("gate_activation"), "sigmoid")
    cell_act = _act(ctx.attr("cell_activation"), "tanh")
    cand_act = _act(ctx.attr("candidate_activation"), "tanh")
    proj_act = _act(ctx.attr("proj_activation"), "tanh")

    batch, maxlen = x.shape[0], x.shape[1]
    dt = x.dtype
    if bias is not None:
        x = x + bias.reshape(-1)[: 4 * hidden]
    mask = _length_mask(length, batch, maxlen, dt)
    if is_reverse:
        x = jnp.flip(x, axis=1)
        mask = jnp.flip(mask, axis=1)
    xs_tm = jnp.swapaxes(x, 0, 1)
    mask_tm = jnp.swapaxes(mask, 0, 1)
    r_init = jnp.zeros((batch, proj), dt)
    c_init = jnp.zeros((batch, hidden), dt)

    def step(carries, x_t):
        r_prev, c_prev = carries
        gates = x_t + r_prev @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=1)
        i, f, o = gate_act(gi), gate_act(gf), gate_act(go)
        c = f * c_prev + i * cand_act(gc)
        h = o * cell_act(c)
        r = proj_act(h @ w_proj)
        return (r, c), (r, c)

    (_, _), (rs, cs) = _masked_scan(step, (r_init, c_init), xs_tm, mask_tm)
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        rs = jnp.flip(rs, axis=1)
        cs = jnp.flip(cs, axis=1)
    ctx.set_output("Projection", rs)
    ctx.set_output("Cell", cs)


def _gru_step(w, hidden, gate_act, cand_act, origin_mode):
    w_ur = w[:, : 2 * hidden]  # [H, 2H] update+reset
    w_c = w[:, 2 * hidden :]  # [H, H] candidate

    def step(carries, x_t):
        (h_prev,) = carries
        xur = x_t[:, : 2 * hidden]
        xc = x_t[:, 2 * hidden :]
        ur = gate_act(xur + h_prev @ w_ur)
        u, r = jnp.split(ur, 2, axis=1)
        c = cand_act(xc + (r * h_prev) @ w_c)
        if origin_mode:
            h = (1.0 - u) * c + u * h_prev
        else:
            h = u * c + (1.0 - u) * h_prev
        return (h,), (h,)

    return step


@register_op("dynamic_gru")
def dynamic_gru_op(ctx: OpContext):
    """Input [B,T,3H] (x-projection precomputed), Weight [H,3H] split
    [u|r|c̃], Bias [1,3H]. Output Hidden [B,T,H].
    Reference: operators/gru_op.cc, math/gru_compute.cc."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    h0 = ctx.input("H0")
    length = ctx.input("Length")
    hidden = w.shape[0]
    is_reverse = bool(ctx.attr("is_reverse", False))
    origin_mode = bool(ctx.attr("origin_mode", False))
    gate_act = _act(ctx.attr("gate_activation"), "sigmoid")
    cand_act = _act(ctx.attr("candidate_activation"), "tanh")

    batch, maxlen = x.shape[0], x.shape[1]
    dt = x.dtype
    if bias is not None:
        x = x + bias.reshape(-1)
    mask = _length_mask(length, batch, maxlen, dt)
    if is_reverse:
        x = jnp.flip(x, axis=1)
        mask = jnp.flip(mask, axis=1)
    xs_tm = jnp.swapaxes(x, 0, 1)
    mask_tm = jnp.swapaxes(mask, 0, 1)
    h_init = h0 if h0 is not None else jnp.zeros((batch, hidden), dt)

    step = _gru_step(w, hidden, gate_act, cand_act, origin_mode)
    (_,), (hs,) = _masked_scan(step, (h_init,), xs_tm, mask_tm)
    hs = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, axis=1)
    ctx.set_output("Hidden", hs)


@register_op("lstm")
def lstm_op(ctx: OpContext):
    """Stacked (optionally bidirectional) LSTM over raw features — the
    cudnn_lstm analog (reference: operators/cudnn_lstm_op.cu.cc). Inputs:
    Input [B,T,D], InitH/InitC [L*dirs,B,H], WeightX (per layer*dir,
    [D_l,4H]), WeightH ([H,4H]), Bias ([4H]). Outputs Out [B,T,H*dirs],
    LastH/LastC [L*dirs,B,H]."""
    x = ctx.input("Input")
    init_h = ctx.input("InitH")
    init_c = ctx.input("InitC")
    length = ctx.input("Length")
    wx_list = ctx.inputs("WeightX")
    wh_list = ctx.inputs("WeightH")
    b_list = ctx.inputs("Bias")
    num_layers = int(ctx.attr("num_layers", 1))
    is_bidirec = bool(ctx.attr("is_bidirec", False))
    dropout_prob = float(ctx.attr("dropout_prob", 0.0) or 0.0)
    dirs = 2 if is_bidirec else 1
    hidden = wh_list[0].shape[0]
    batch, maxlen = x.shape[0], x.shape[1]
    dt = x.dtype

    mask = _length_mask(length, batch, maxlen, dt)
    mask_tm = jnp.swapaxes(mask, 0, 1)

    def run_dir(inp, wx, wh, b, h0, c0, reverse):
        seq = jnp.flip(inp, axis=1) if reverse else inp
        m_tm = jnp.flip(mask_tm, axis=0) if reverse else mask_tm
        xs = jnp.swapaxes(seq @ wx + b, 0, 1)

        def step(carries, x_t):
            h_prev, c_prev = carries
            gates = x_t + h_prev @ wh
            gi, gf, gc, go = jnp.split(gates, 4, axis=1)
            i, f, o = jax.nn.sigmoid(gi), jax.nn.sigmoid(gf), jax.nn.sigmoid(go)
            c = f * c_prev + i * jnp.tanh(gc)
            h = o * jnp.tanh(c)
            return (h, c), (h,)

        (h_last, c_last), (hs,) = _masked_scan(step, (h0, c0), xs, m_tm)
        hs = jnp.swapaxes(hs, 0, 1)
        if reverse:
            hs = jnp.flip(hs, axis=1)
        return hs, h_last, c_last

    out = x
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        layer_outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            h0 = init_h[idx] if init_h is not None else jnp.zeros((batch, hidden), dt)
            c0 = init_c[idx] if init_c is not None else jnp.zeros((batch, hidden), dt)
            hs, h_last, c_last = run_dir(
                out, wx_list[idx], wh_list[idx], b_list[idx], h0, c0, d == 1)
            layer_outs.append(hs)
            last_hs.append(h_last)
            last_cs.append(c_last)
        out = jnp.concatenate(layer_outs, axis=-1) if dirs > 1 else layer_outs[0]
        if dropout_prob and not ctx.is_test and layer < num_layers - 1:
            key = jax.random.fold_in(ctx.rng(), layer)  # distinct mask per layer
            keep = jax.random.bernoulli(key, 1.0 - dropout_prob, out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_prob), 0).astype(out.dtype)
    ctx.set_output("Out", out * mask[:, :, None])
    ctx.set_output("LastH", jnp.stack(last_hs))
    ctx.set_output("LastC", jnp.stack(last_cs))


@register_op("gru_unit")
def gru_unit_op(ctx: OpContext):
    """One GRU step (reference: operators/gru_unit_op.cc): Input [B,3H]
    (x-projection), HiddenPrev [B,H], Weight [H,3H], Bias [1,3H]."""
    x = ctx.input("Input")
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    hidden = w.shape[0]
    origin_mode = bool(ctx.attr("origin_mode", False))
    gate_act = _act(ctx.attr("gate_activation"), "sigmoid")
    cand_act = _act(ctx.attr("candidate_activation"), "tanh")
    if bias is not None:
        x = x + bias.reshape(-1)
    step = _gru_step(w, hidden, gate_act, cand_act, origin_mode)
    (h,), (_,) = step((h_prev,), x)
    ctx.set_output("Hidden", h)


@register_op("lstm_unit")
def lstm_unit_op(ctx: OpContext):
    """One LSTM step on pre-projected gates (reference:
    operators/lstm_unit_op.cc): X [B,4H] = [i|f|c̃|o], C_prev [B,H];
    forget_bias added to f before the sigmoid."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    forget_bias = float(ctx.attr("forget_bias", 0.0) or 0.0)
    gi, gf, gc, go = jnp.split(x, 4, axis=1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    ctx.set_output("C", c)
    ctx.set_output("H", h)


@register_op("dynamic_rnn")
def dynamic_rnn_op(ctx: OpContext):
    """DynamicRNN execution (reference: the LoD-bucketed DynamicRNN,
    layers/control_flow.py:1394 + lod_rank_table/shrink_rnn_memory ops).

    Fluid sorts sequences by length and shrinks the batch as sequences end;
    on TPU that dynamic re-batching would defeat XLA's static shapes, so the
    redesign scans the full padded batch and masks: carried memories freeze
    and outputs are zeroed once t ≥ length_b — same results, constant shape.

    attrs: sub_block, step_inputs [(outer,inner)], static_inputs
    [(outer,inner)], memories [(prev,updated,init_outer)], step_outputs;
    inputs: X (outer step inputs, batch-major [B,T,...]), Length [B],
    Boot (memory inits); outputs Out (stacked [B,T,...]).
    """
    block = ctx.trace.program.blocks[ctx.attr("sub_block")]
    step_inputs = ctx.attr("step_inputs")
    static_inputs = ctx.attr("static_inputs", []) or []
    memories = ctx.attr("memories")
    step_outputs = ctx.attr("step_outputs")
    env = ctx.env
    length = ctx.input("Length")

    first = env[step_inputs[0][0]]
    batch, maxlen = first.shape[0], first.shape[1]
    mask_tm = jnp.swapaxes(
        _length_mask(length, batch, maxlen, jnp.float32), 0, 1)

    xs = {inner: jnp.swapaxes(env[outer], 0, 1) for outer, inner in step_inputs}
    statics = {inner: env[outer] for outer, inner in static_inputs}
    init = {prev: env[init_name] for prev, _, init_name in memories}

    def body(carry, inp):
        x_t, m_t, t_idx = inp
        local = dict(env)
        local.update(statics)
        local.update(x_t)
        local.update(carry)
        from ..core.interpreter import PerStepTrace

        run_block_ops_ref(block.ops, local, PerStepTrace(ctx.trace, t_idx),
                          offset=10_000 * block.idx)
        new_carry = {}
        for prev, updated, _ in memories:
            m = m_t.reshape((-1,) + (1,) * (local[updated].ndim - 1))
            new_carry[prev] = (m * local[updated]
                               + (1.0 - m) * carry[prev]).astype(carry[prev].dtype)
        ys = tuple(
            (local[n] * m_t.reshape((-1,) + (1,) * (local[n].ndim - 1))
             ).astype(local[n].dtype)
            for n in step_outputs)
        return new_carry, ys

    final_carry, ys = jax.lax.scan(
        body, init, (xs, mask_tm, jnp.arange(maxlen)))
    outs = [jnp.swapaxes(y, 0, 1) for y in ys]
    for n, v in zip(ctx.output_names("Out"), outs):
        env[n] = v
    for (prev, updated, _), name in zip(memories, ctx.output_names("FinalStates")):
        env[name] = final_carry[prev]


def run_block_ops_ref(*args, **kw):
    from ..core.interpreter import run_block_ops

    return run_block_ops(*args, **kw)


# Reference op-name aliases: the reference's layers emit op types "gru" /
# "lstmp" (gru_op.cc, lstmp_op.cc) for what this framework registers as
# dynamic_gru / dynamic_lstmp — same math over padded+Length batches.
@register_op("gru")
def gru_alias_op(ctx: OpContext):
    dynamic_gru_op(ctx)


@register_op("lstmp")
def lstmp_alias_op(ctx: OpContext):
    dynamic_lstmp_op(ctx)
