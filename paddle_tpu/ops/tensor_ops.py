"""Tensor manipulation, fill, and random ops.

Fluid equivalents live across ``operators/reshape_op.cc``, ``concat_op.cc``,
``fill_constant_op.cc``, ``uniform_random_op.cc`` etc. Random ops use
counter-based JAX PRNG keys (deterministic, replay-safe under jit) instead of
the reference's per-device curand generators.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import to_jnp_dtype
from ..core.registry import OpContext, register_op


def _resolve_shape(shape, x=None):
    """Resolve a Fluid shape attr (may contain -1 and 0) against input x."""
    shape = list(shape)
    if x is not None:
        for i, s in enumerate(shape):
            if s == 0 and i < x.ndim:  # 0 means "copy from input" in fluid reshape
                shape[i] = x.shape[i]
    return shape


@register_op("reshape", "reshape2")
def reshape_op(ctx: OpContext):
    x = ctx.input("X")
    shape_tensor = ctx.input("Shape") if ctx.has_input("Shape") else None
    if shape_tensor is not None:
        shape = [int(s) for s in np.asarray(shape_tensor)]
    else:
        shape = _resolve_shape(ctx.attr("shape"), x)
    out = x.reshape(shape)
    ctx.set_output("Out", out)
    if ctx.has_output("XShape"):
        ctx.set_output("XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op("squeeze", "squeeze2")
def squeeze_op(ctx: OpContext):
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    ctx.set_output("Out", out)
    if ctx.has_output("XShape"):
        ctx.set_output("XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op("unsqueeze", "unsqueeze2")
def unsqueeze_op(ctx: OpContext):
    x = ctx.input("X")
    out = x
    for a in sorted(ctx.attr("axes")):
        out = jnp.expand_dims(out, a)
    ctx.set_output("Out", out)
    if ctx.has_output("XShape"):
        ctx.set_output("XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op("flatten", "flatten2")
def flatten_op(ctx: OpContext):
    from .math_ops import _dim_prod

    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    lead = _dim_prod(x.shape[:axis]) if axis > 0 else 1
    ctx.set_output("Out", x.reshape(lead, -1))
    if ctx.has_output("XShape"):
        ctx.set_output("XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op("transpose", "transpose2")
def transpose_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.transpose(x, ctx.attr("axis")))
    if ctx.has_output("XShape"):
        ctx.set_output("XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register_op("concat")
def concat_op(ctx: OpContext):
    xs = ctx.inputs("X")
    ctx.set_output("Out", jnp.concatenate(xs, axis=ctx.attr("axis", 0)))


@register_op("split")
def split_op(ctx: OpContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    ctx.set_outputs("Out", outs)


@register_op("stack")
def stack_op(ctx: OpContext):
    ctx.set_output("Y", jnp.stack(ctx.inputs("X"), axis=ctx.attr("axis", 0)))


@register_op("unstack")
def unstack_op(ctx: OpContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    outs = [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis=axis)]
    ctx.set_outputs("Y", outs)


@register_op("slice")
def slice_op(ctx: OpContext):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    ctx.set_output("Out", x[tuple(idx)])


@register_op("strided_slice")
def strided_slice_op(ctx: OpContext):
    x = ctx.input("Input")
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(ctx.attr("axes"), ctx.attr("starts"), ctx.attr("ends"), ctx.attr("strides")):
        idx[a] = slice(s, e, st)
    ctx.set_output("Out", x[tuple(idx)])


@register_op("gather")
def gather_op(ctx: OpContext):
    x, index = ctx.input("X"), ctx.input("Index")
    ctx.set_output("Out", jnp.take(x, index.reshape(-1), axis=0))


@register_op("gather_nd")
def gather_nd_op(ctx: OpContext):
    x, index = ctx.input("X"), ctx.input("Index")
    ctx.set_output("Out", x[tuple(jnp.moveaxis(index, -1, 0))])


@register_op("scatter")
def scatter_op(ctx: OpContext):
    x, ids, updates = ctx.input("X"), ctx.input("Ids"), ctx.input("Updates")
    ids = ids.reshape(-1)
    if ctx.attr("overwrite", True):
        out = x.at[ids].set(updates)
    else:
        out = x.at[ids].add(updates)
    ctx.set_output("Out", out)


@register_op("expand")
def expand_op(ctx: OpContext):
    x = ctx.input("X")
    times = ctx.attr("expand_times")
    ctx.set_output("Out", jnp.tile(x, times))


@register_op("expand_as")
def expand_as_op(ctx: OpContext):
    x, target = ctx.input("X"), ctx.input("target_tensor")
    times = [t // s for s, t in zip(x.shape, target.shape)]
    ctx.set_output("Out", jnp.tile(x, times))


@register_op("tile")
def tile_op(ctx: OpContext):
    ctx.set_output("Out", jnp.tile(ctx.input("X"), ctx.attr("repeat_times")))


@register_op("pad")
def pad_op(ctx: OpContext):
    x = ctx.input("X")
    paddings = ctx.attr("paddings")
    pad_value = ctx.attr("pad_value", 0.0)
    pairs = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output("Out", jnp.pad(x, pairs, constant_values=pad_value))


@register_op("pad2d")
def pad2d_op(ctx: OpContext):
    x = ctx.input("X")  # NCHW
    p = ctx.attr("paddings", [0, 0, 0, 0])  # top,bottom,left,right
    mode = ctx.attr("mode", "constant")
    value = ctx.attr("pad_value", 0.0)
    pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        out = jnp.pad(x, pairs, constant_values=value)
    elif mode == "reflect":
        out = jnp.pad(x, pairs, mode="reflect")
    else:
        out = jnp.pad(x, pairs, mode="edge")
    ctx.set_output("Out", out)


@register_op("pad_constant_like")
def pad_constant_like_op(ctx: OpContext):
    x, y = ctx.input("X"), ctx.input("Y")
    pairs = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    ctx.set_output("Out", jnp.pad(y, pairs, constant_values=ctx.attr("pad_value", 0.0)))


@register_op("crop")
def crop_op(ctx: OpContext):
    x = ctx.input("X")
    offsets = ctx.attr("offsets")
    shape = ctx.attr("shape")
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set_output("Out", x[idx])


@register_op("reverse")
def reverse_op(ctx: OpContext):
    x = ctx.input("X")
    out = x
    for a in ctx.attr("axis"):
        out = jnp.flip(out, a)
    ctx.set_output("Out", out)


@register_op("one_hot")
def one_hot_op(ctx: OpContext):
    ids = ctx.input("X")
    depth = ctx.attr("depth")
    out = jax.nn.one_hot(ids.reshape(ids.shape[:-1]) if ids.shape[-1] == 1 else ids, depth, dtype=jnp.float32)
    ctx.set_output("Out", out)


@register_op("shape")
def shape_op(ctx: OpContext):
    x = ctx.input("Input")
    ctx.set_output("Out", jnp.asarray(x.shape, dtype=jnp.int32))


@register_op("top_k")
def top_k_op(ctx: OpContext):
    x = ctx.input("X")
    k = ctx.attr("k", 1)
    values, indices = jax.lax.top_k(x, k)
    ctx.set_output("Out", values)
    ctx.set_output("Indices", indices)


@register_op("argsort")
def argsort_op(ctx: OpContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    indices = jnp.argsort(x, axis=axis)
    ctx.set_output("Indices", indices)
    ctx.set_output("Out", jnp.sort(x, axis=axis))


@register_op("arg_max")
def arg_max_op(ctx: OpContext):
    ctx.set_output("Out", jnp.argmax(ctx.input("X"), axis=ctx.attr("axis", -1)))


@register_op("arg_min")
def arg_min_op(ctx: OpContext):
    ctx.set_output("Out", jnp.argmin(ctx.input("X"), axis=ctx.attr("axis", -1)))


@register_op("where")
def where_op(ctx: OpContext):
    ctx.set_output("Out", jnp.where(ctx.input("Condition"), ctx.input("X"), ctx.input("Y")))


@register_op("multiplex")
def multiplex_op(ctx: OpContext):
    ids = ctx.input("Ids").reshape(-1)
    xs = jnp.stack(ctx.inputs("X"), axis=0)  # [k, n, d]
    ctx.set_output("Out", xs[ids, jnp.arange(xs.shape[1])])


@register_op("is_empty")
def is_empty_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.asarray(x.size == 0))


# -- fill / init ops ----------------------------------------------------------


def _init_out_sharding(ctx: OpContext):
    """NamedSharding for an init op whose output var carries a mesh-axis
    annotation (parallel.sharded_embedding / propagated Adam moments) while
    a mesh is active — trace mesh first, then the global ``mesh_guard``
    mesh (startup programs run eagerly, before any CompiledProgram mesh
    exists). Returns None when the init should stay single-device."""
    name = ctx.output_name("Out")
    if name is None:
        return None
    try:
        var = ctx.var(name)
    except Exception:
        return None
    spec = getattr(var, "sharding", None)
    if not spec or all(a is None for a in spec):
        return None
    mesh = getattr(ctx.trace, "mesh", None)
    if mesh is None:
        from ..parallel.mesh import get_mesh

        mesh = get_mesh()
    if mesh is None:
        return None
    from ..executor import _valid_sharding

    if not _valid_sharding(spec, mesh):
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def _run_init(ctx: OpContext, thunk):
    """Run an init thunk, shard-by-shard when the output is annotated: the
    thunk jits with sharded ``out_shardings`` so XLA partitions the
    fill/RNG and each device materializes only its [V/n, D] shard —
    numerics identical to the unsharded init (same program, partitioned),
    peak memory V/n rows per device. This is what lets a V=1e8 CTR table
    (p+m+v ≈ 13 GB) instantiate on a mesh where the single-device
    fill_constant hits RESOURCE_EXHAUSTED at trace time (BENCH_r05)."""
    sh = _init_out_sharding(ctx)
    if sh is None:
        return thunk()
    import jax as _jax

    return _jax.jit(thunk, out_shardings=sh)()


@register_op("fill_constant")
def fill_constant_op(ctx: OpContext):
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    shape = ctx.attr("shape", [])
    value = ctx.attr("value", 0.0)
    ctx.set_output("Out", _run_init(
        ctx, lambda: jnp.full(shape, value, dtype=dtype)))


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like_op(ctx: OpContext):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jnp.full(shape, ctx.attr("value", 0.0), dtype=dtype))


@register_op("fill_zeros_like")
def fill_zeros_like_op(ctx: OpContext):
    ctx.set_output("Out", jnp.zeros_like(ctx.input("X")))


@register_op("assign")
def assign_op(ctx: OpContext):
    ctx.set_output("Out", ctx.input("X"))


@register_op("assign_value")
def assign_value_op(ctx: OpContext):
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    shape = ctx.attr("shape")
    values = ctx.attr("values")
    ctx.set_output("Out", jnp.asarray(values, dtype=dtype).reshape(shape))


@register_op("range")
def range_op(ctx: OpContext):
    start, end, step = ctx.input("Start"), ctx.input("End"), ctx.input("Step")
    ctx.set_output("Out", jnp.arange(float(start), float(end), float(step)))


@register_op("linspace")
def linspace_op(ctx: OpContext):
    s, e, n = ctx.input("Start"), ctx.input("Stop"), ctx.input("Num")
    ctx.set_output("Out", jnp.linspace(float(s), float(e), int(n)))


# -- random ops ---------------------------------------------------------------


@register_op("uniform_random", "uniform_random_batch_size_like")
def uniform_random_op(ctx: OpContext):
    shape = list(ctx.attr("shape"))
    if ctx.has_input("Input"):
        x = ctx.input("Input")
        shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    key = ctx.rng()
    ctx.set_output("Out", _run_init(ctx, lambda: jax.random.uniform(
        key, shape, dtype=jnp.float32, minval=lo, maxval=hi).astype(dtype)))


@register_op("gaussian_random", "gaussian_random_batch_size_like")
def gaussian_random_op(ctx: OpContext):
    shape = list(ctx.attr("shape"))
    if ctx.has_input("Input"):
        x = ctx.input("Input")
        shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    key = ctx.rng()
    ctx.set_output("Out", _run_init(ctx, lambda: (
        mean + std * jax.random.normal(key, shape, dtype=jnp.float32)
    ).astype(dtype)))


@register_op("truncated_gaussian_random")
def truncated_gaussian_random_op(ctx: OpContext):
    shape = ctx.attr("shape")
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    key = ctx.rng()
    ctx.set_output("Out", _run_init(ctx, lambda: (
        mean + std * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype=jnp.float32)
    ).astype(dtype)))


@register_op("randint")
def randint_op(ctx: OpContext):
    shape = ctx.attr("shape")
    out = jax.random.randint(ctx.rng(), shape, ctx.attr("low", 0), ctx.attr("high"))
    ctx.set_output("Out", out)


@register_op("dropout")
def dropout_op(ctx: OpContext):
    """Reference: operators/dropout_op.cc. Two impl modes:
    downgrade_in_infer (default): train out = x*mask, infer out = x*(1-p);
    upscale_in_train: train out = x*mask/(1-p), infer out = x.
    """
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if ctx.is_test:
        if impl == "upscale_in_train":
            ctx.set_output("Out", x)
        else:
            ctx.set_output("Out", x * jnp.asarray(1.0 - p, x.dtype))
        return
    if p == 0.0:
        ctx.set_output("Out", x)
        ctx.set_output("Mask", jnp.ones_like(x))
        return
    # keep the mask as PRED through the where: the backward residual is then
    # the 1-byte bool, not an x.dtype mask — one byte/element less HBM
    # traffic per dropout site (matters at [B,H,S,S] attention sites)
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x * jnp.asarray(1.0 / (1.0 - p), x.dtype),
                        jnp.zeros((), x.dtype))
    else:
        out = jnp.where(keep, x, jnp.zeros((), x.dtype))
    ctx.set_output("Out", out)
    ctx.set_output("Mask", keep.astype(x.dtype))


@register_op("shuffle_channel")
def shuffle_channel_op(ctx: OpContext):
    x = ctx.input("X")
    group = ctx.attr("group")
    n, c, h, w = x.shape
    ctx.set_output("Out", x.reshape(n, group, c // group, h, w).swapaxes(1, 2).reshape(n, c, h, w))


@register_op("label_smooth")
def label_smooth_op(ctx: OpContext):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 0.1)
    k = x.shape[-1]
    prior = ctx.input("PriorDist")
    if prior is None:
        prior = jnp.full((k,), 1.0 / k, x.dtype)
    ctx.set_output("Out", (1.0 - eps) * x + eps * prior)


@register_op("pixel_shuffle")
def pixel_shuffle_op(ctx: OpContext):
    x = ctx.input("X")  # NCHW
    r = ctx.attr("upscale_factor")
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w).transpose(0, 1, 4, 2, 5, 3).reshape(
        n, c // (r * r), h * r, w * r
    )
    ctx.set_output("Out", out)


@register_op("space_to_depth")
def space_to_depth_op(ctx: OpContext):
    x = ctx.input("X")
    b = ctx.attr("blocksize")
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // b, b, w // b, b).transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b, h // b, w // b)
    ctx.set_output("Out", out)


@register_op("load")
def load_op(ctx: OpContext):
    """Reference: operators/load_op.cc. The file is read at trace time (the
    trace-once analog of the per-run load; re-tracing reloads) from the
    .npy/.npz format written by paddle_tpu.io.save_vars. For a combined
    .npz archive the entry matching the output variable's name is loaded."""
    import numpy as np

    path = ctx.attr("file_path")
    data = np.load(path, allow_pickle=False)
    if isinstance(data, np.lib.npyio.NpzFile):
        key = ctx.op.outputs["Out"][0]
        if key not in data:
            raise KeyError(
                "load: %r has no entry %r (archive keys: %s)"
                % (path, key, sorted(data.files)))
        arr = data[key]
    else:
        arr = data
    out = jnp.asarray(arr)
    if ctx.attr("load_as_fp16", False):
        out = out.astype(jnp.float16)
    ctx.set_output("Out", out)
