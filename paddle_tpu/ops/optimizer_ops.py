"""Optimizer update ops.

Fluid's optimizers are per-parameter device kernels mutating params in place
(reference: ``operators/optimizers/`` — sgd_op.cc, momentum_op.cc,
adam_op.cc, ...). Here each is a functional update; the Executor donates the
state buffers to the jitted step so XLA updates params in place in HBM —
the same zero-copy effect without mutation semantics.

Every op reads Param/Grad/LearningRate (+ accumulators) and writes
ParamOut (+ accumulator outs), exactly mirroring the reference op signatures
so the Python Optimizer layer stays Fluid-shaped.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op


def _lr(ctx):
    lr = ctx.input("LearningRate")
    return lr.reshape(()) if hasattr(lr, "reshape") else jnp.asarray(lr)


def _sparse(g):
    from ..core.sparse import SparseGrad

    return g if isinstance(g, SparseGrad) else None


def _sparse_kernel_mode():
    """Resolve ``FLAGS_sparse_update_kernel`` for this trace: None = XLA
    scatter path, "compiled"/"interpret" = the row-DMA Pallas kernel
    (pallas_kernels/sparse_adam.py). "auto" compiles on TPU and keeps the
    scatter path elsewhere — the interpreter is a correctness tool, not a
    fast CPU path."""
    from ..flags import flags

    mode = str(flags.sparse_update_kernel).lower()
    if mode in ("0", "off", "false", "no"):
        return None
    if mode == "interpret":
        return "interpret"
    on_tpu = jax.default_backend() == "tpu"
    if mode in ("1", "on", "true", "yes"):
        return "compiled" if on_tpu else "interpret"
    return "compiled" if on_tpu else None  # auto


def _table_mesh_sharding(ctx, param):
    """(mesh, axis) when this op's Param table is row-sharded over a live
    mesh axis (parallel.sharded_embedding annotation) — the signal to route
    the update through core.sparse.sharded_rows_update instead of a global
    scatter (which would gather the table)."""
    mesh = getattr(ctx.trace, "mesh", None)
    if mesh is None:
        return None
    names = ctx.op.inputs.get("Param")
    if not names:
        return None
    try:
        var = ctx.var(names[0])
    except Exception:
        return None
    spec = getattr(var, "sharding", None)
    from ..executor import _valid_sharding

    if not spec or spec[0] is None or not _valid_sharding(spec, mesh):
        return None
    axis = spec[0]
    n = mesh.shape[axis]
    if n <= 1:
        return None
    if param.shape[0] % n:
        # uneven rows can't take the shard-local path; the global-scatter
        # fallback re-materializes the full table per step — loud, because
        # at the V this feature exists for that IS the OOM being avoided
        import warnings

        warnings.warn(
            "sparse table %r: V=%d not divisible by mesh axis %r (n=%d); "
            "falling back to the full-table scatter update. Pad the vocab "
            "to a multiple of the axis size to keep updates shard-local."
            % (names[0], param.shape[0], axis, n))
        return None
    return mesh, axis


def _use_alltoall(n_ids, n_shards):
    from ..flags import flags

    return bool(flags.ctr_alltoall_update) and n_ids % n_shards == 0


def _kernel_for(param, *moments):
    """(kmode, interpret) when the row-DMA kernel should carry this update
    — FLAGS gate resolved AND sparse_rows_supported (pltpu importable, f32
    tables); (None, False) means the scatter formulation."""
    from .pallas_kernels.sparse_adam import sparse_rows_supported

    kmode = _sparse_kernel_mode()
    if kmode is None:
        return None, False
    if not sparse_rows_supported(param.shape[0], param.shape[1], param.dtype):
        return None, False
    if any(t.dtype != jnp.float32 for t in moments):
        return None, False
    return kmode, kmode == "interpret"


@register_op("sgd")
def sgd_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    sg = _sparse(g)
    if sg is not None:
        # SelectedRows branch (reference: sgd_op.h sparse path): touch only
        # the looked-up rows; duplicate ids accumulate in the scatter-add.
        lr = _lr(ctx).astype(p.dtype)
        sharded = _table_mesh_sharding(ctx, p)
        if sharded is not None:
            from ..core.sparse import merge_rows, sharded_rows_update

            mesh, axis = sharded
            uniq, merged = merge_rows(sg.ids, sg.rows.astype(p.dtype),
                                      p.shape[0])
            kmode, interp = _kernel_for(p)

            def _upd(tabs, lid, rows_l, lr_s):
                (p_l,) = tabs
                if kmode is not None:
                    # the row-DMA kernel runs per shard on the local
                    # [V/n, D] slice; foreign/pad ids arrive as the local
                    # OOB (== shard rows) and the kernel drops their writes
                    from .pallas_kernels.sparse_adam import sparse_sgd_rows

                    return (sparse_sgd_rows(p_l, lid, rows_l, lr_s,
                                            interpret=interp),)
                return (p_l.at[lid].add(-lr_s * rows_l),)

            (p_new,) = sharded_rows_update(
                (p,), uniq, merged, _upd, mesh, axis, scalars=(lr,),
                alltoall=_use_alltoall(uniq.shape[0], mesh.shape[axis]))
            ctx.set_output("ParamOut", p_new)
            return
        kmode, interp = _kernel_for(p)
        if kmode is not None:
            # one row-DMA kernel instead of the XLA scatter pass
            # (SPARSE_PROFILE.md §1/§4); merge first — the kernel wants
            # unique rows, and XLA drops the merge padding's OOB id just
            # like the scatter would
            from ..core.sparse import merge_rows
            from .pallas_kernels.sparse_adam import sparse_sgd_rows

            uniq, merged = merge_rows(sg.ids, sg.rows.astype(p.dtype),
                                      p.shape[0])
            ctx.set_output("ParamOut", sparse_sgd_rows(
                p, uniq, merged, lr, interpret=interp))
            return
        ctx.set_output("ParamOut", p.at[sg.ids].add(
            -lr * sg.rows.astype(p.dtype)))
        return
    ctx.set_output("ParamOut", p - _lr(ctx).astype(p.dtype) * g.astype(p.dtype))


@register_op("momentum")
def momentum_op(ctx: OpContext):
    p, g, v = ctx.input("Param"), ctx.input("Grad"), ctx.input("Velocity")
    lr = _lr(ctx).astype(p.dtype)
    mu = jnp.asarray(ctx.attr("mu"), p.dtype)
    sg = _sparse(g)
    if sg is not None:
        # lazy rows-only momentum (untouched rows keep stale velocity — the
        # reference's SelectedRows momentum has the same semantics)
        from ..core.sparse import merge_rows

        uniq, merged = merge_rows(sg.ids, sg.rows.astype(p.dtype), p.shape[0])
        v_rows = mu * v[uniq] + merged
        if ctx.attr("use_nesterov", False):
            step_rows = (merged + mu * v_rows) * lr
        else:
            step_rows = lr * v_rows
        ctx.set_output("ParamOut", p.at[uniq].add(-step_rows))
        ctx.set_output("VelocityOut", v.at[uniq].set(v_rows))
        return
    v_new = mu * v + g.astype(p.dtype)
    if ctx.attr("use_nesterov", False):
        p_new = p - (g.astype(p.dtype) + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("VelocityOut", v_new)


@register_op("lars_momentum")
def lars_momentum_op(ctx: OpContext):
    p, g, v = ctx.input("Param"), ctx.input("Grad"), ctx.input("Velocity")
    lr = _lr(ctx).astype(p.dtype)
    mu = jnp.asarray(ctx.attr("mu"), p.dtype)
    coeff = ctx.attr("lars_coeff", 0.001)
    decay = ctx.attr("lars_weight_decay", 0.0005)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0), lr * coeff * pn / (gn + decay * pn), lr
    )
    v_new = mu * v + local_lr * (g + decay * p)
    ctx.set_output("ParamOut", p - v_new)
    ctx.set_output("VelocityOut", v_new)


@register_op("adam")
def adam_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m, v = ctx.input("Moment1"), ctx.input("Moment2")
    b1p, b2p = ctx.input("Beta1Pow"), ctx.input("Beta2Pow")
    lr = _lr(ctx)
    b1 = jnp.asarray(ctx.attr("beta1", 0.9), jnp.float32)
    b2 = jnp.asarray(ctx.attr("beta2", 0.999), jnp.float32)
    eps = jnp.asarray(ctx.attr("epsilon", 1e-8), jnp.float32)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    sg = _sparse(g)
    if sg is not None:
        # lazy-mode sparse adam (reference: adam_op.h SelectedRows branch,
        # lazy_mode): moments decay and params move ONLY on touched rows.
        from ..core.sparse import merge_rows

        uniq, merged = merge_rows(sg.ids, sg.rows.astype(jnp.float32),
                                  p.shape[0])
        ctx.set_output("Beta1PowOut", b1p * b1)
        ctx.set_output("Beta2PowOut", b2p * b2)
        sharded = _table_mesh_sharding(ctx, p)
        b1f = float(ctx.attr("beta1", 0.9))
        b2f = float(ctx.attr("beta2", 0.999))
        epsf = float(ctx.attr("epsilon", 1e-8))
        if sharded is not None:
            # row-sharded table (parallel.sharded_embedding): shard-local
            # rows-only updates — param AND both moments stay [V/n, D] per
            # device, nothing ever gathers the table
            from ..core.sparse import sharded_rows_update

            mesh, axis = sharded
            kmode, interp = _kernel_for(p, m, v)

            def _upd(tabs, lid, rows_l, lr_s):
                p_l, m_l, v_l = tabs
                if kmode is not None:
                    # the two tentpole halves compose: the row-DMA kernel
                    # runs per shard on the local [V/n, D] slices (foreign/
                    # pad ids arrive as the local OOB == shard rows, whose
                    # writes the kernel drops)
                    from .pallas_kernels.sparse_adam import sparse_adam_rows

                    return sparse_adam_rows(p_l, m_l, v_l, lid, rows_l,
                                            lr_s, b1f, b2f, epsf,
                                            interpret=interp)
                m_old, v_old = m_l[lid], v_l[lid]
                m_rows = b1 * m_old + (1 - b1) * rows_l
                v_rows = b2 * v_old + (1 - b2) * jnp.square(rows_l)
                step = lr_s * m_rows / (jnp.sqrt(v_rows) + eps)
                return (p_l.at[lid].add(-step.astype(p_l.dtype)),
                        m_l.at[lid].add(m_rows - m_old),
                        v_l.at[lid].add(v_rows - v_old))

            p_new, m_new, v_new = sharded_rows_update(
                (p, m, v), uniq, merged, _upd, mesh, axis,
                scalars=(lr_t,),
                alltoall=_use_alltoall(uniq.shape[0], mesh.shape[axis]))
            ctx.set_output("ParamOut", p_new)
            ctx.set_output("Moment1Out", m_new)
            ctx.set_output("Moment2Out", v_new)
            return
        kmode, interp = _kernel_for(p, m, v)
        if kmode is not None:
            # one row-DMA Pallas kernel replaces the three ~30 GB/s scatter
            # fusions (SPARSE_PROFILE.md §1 → §4)
            from .pallas_kernels.sparse_adam import sparse_adam_rows

            p_new, m_new, v_new = sparse_adam_rows(
                p, m, v, uniq, merged, lr_t,
                beta1=b1f, beta2=b2f, epsilon=epsf, interpret=interp)
            ctx.set_output("ParamOut", p_new)
            ctx.set_output("Moment1Out", m_new)
            ctx.set_output("Moment2Out", v_new)
            return
        m_old, v_old = m[uniq], v[uniq]
        m_rows = b1 * m_old + (1 - b1) * merged
        v_rows = b2 * v_old + (1 - b2) * jnp.square(merged)
        step = lr_t * m_rows / (jnp.sqrt(v_rows) + eps)
        ctx.set_output("ParamOut", p.at[uniq].add(-step.astype(p.dtype)))
        # express the moment writes as scatter-ADDs of the delta rather than
        # scatter-sets: on v5e the set-combiner scatter kernel measures ~2x
        # the add-combiner on a [1e6,10] table (2.7 vs 1.3 ms per scatter in
        # the DeepFM step), and the old rows are already gathered
        ctx.set_output("Moment1Out", m.at[uniq].add(m_rows - m_old))
        ctx.set_output("Moment2Out", v.at[uniq].add(v_rows - v_old))
        return
    gf = g.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * gf
    v_new = b2 * v + (1 - b2) * jnp.square(gf)
    # Reference adam_op.h: lr_t = lr * sqrt(1-beta2^t)/(1-beta1^t)
    p_new = p.astype(jnp.float32) - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    ctx.set_output("ParamOut", p_new.astype(p.dtype))
    ctx.set_output("Moment1Out", m_new)
    ctx.set_output("Moment2Out", v_new)
    # Fluid updates beta pows in a separate scale op; we fold it here and
    # also expose the outs for parity when wired.
    ctx.set_output("Beta1PowOut", b1p * b1)
    ctx.set_output("Beta2PowOut", b2p * b2)


@register_op("adamw")
def adamw_op(ctx: OpContext):
    p = ctx.input("Param")
    coeff = ctx.attr("weight_decay", 0.01)
    lr = _lr(ctx)
    adam_op(ctx)
    p_out = ctx.env[ctx.output_name("ParamOut")]
    ctx.set_output("ParamOut", (p_out.astype(jnp.float32) - lr * coeff * p.astype(jnp.float32)).astype(p.dtype))


@register_op("adamax")
def adamax_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m, inf = ctx.input("Moment"), ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow")
    lr = _lr(ctx)
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    lr_t = lr / (1 - b1p.reshape(()))
    ctx.set_output("ParamOut", p - lr_t * m_new / (inf_new + eps))
    ctx.set_output("MomentOut", m_new)
    ctx.set_output("InfNormOut", inf_new)
    ctx.set_output("Beta1PowOut", b1p * b1)


@register_op("adagrad")
def adagrad_op(ctx: OpContext):
    p, g, moment = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    lr = _lr(ctx)
    eps = ctx.attr("epsilon", 1e-6)
    sg = _sparse(g)
    if sg is not None:
        from ..core.sparse import merge_rows

        uniq, merged = merge_rows(sg.ids, sg.rows.astype(p.dtype), p.shape[0])
        m_rows = moment[uniq] + jnp.square(merged)
        ctx.set_output("ParamOut", p.at[uniq].add(
            -lr * merged / (jnp.sqrt(m_rows) + eps)))
        ctx.set_output("MomentOut", moment.at[uniq].set(m_rows))
        return
    m_new = moment + jnp.square(g)
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output("MomentOut", m_new)


@register_op("decayed_adagrad")
def decayed_adagrad_op(ctx: OpContext):
    p, g, moment = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    lr = _lr(ctx)
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_new = decay * moment + (1 - decay) * jnp.square(g)
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output("MomentOut", m_new)


@register_op("adadelta")
def adadelta_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    avg_sq_g, avg_sq_u = ctx.input("AvgSquaredGrad"), ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    ctx.set_output("ParamOut", p + update)
    ctx.set_output("AvgSquaredGradOut", g2)
    ctx.set_output("AvgSquaredUpdateOut", u2)


@register_op("rmsprop")
def rmsprop_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    ms, mom = ctx.input("MeanSquare"), ctx.input("Moment")
    lr = _lr(ctx)
    rho = ctx.attr("decay", 0.9)
    eps = ctx.attr("epsilon", 1e-10)
    mu = ctx.attr("momentum", 0.0)
    centered = ctx.attr("centered", False)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg = ctx.input("MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        mom_new = mu * mom + lr * g / jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
        ctx.set_output("MeanGradOut", mg_new)
    else:
        mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    ctx.set_output("ParamOut", p - mom_new)
    ctx.set_output("MeanSquareOut", ms_new)
    ctx.set_output("MomentOut", mom_new)


def _soft_threshold(prox, lr, l1, l2):
    """The proximal-operator shrinkage shared by proximal_gd/adagrad
    (reference: operators/optimizers/proximal_gd_op.h:49): L1 soft-threshold
    then L2 shrink. l1/l2 are static attrs, so the branch folds at trace."""
    if l1 > 0:
        return (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
                / (1.0 + lr * l2))
    return prox / (1.0 + lr * l2)


@register_op("proximal_gd")
def proximal_gd_op(ctx: OpContext):
    """reference: operators/optimizers/proximal_gd_op.cc (dense-only there;
    the sparse rows-only variant here matches sgd's SelectedRows idiom)."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    lr = _lr(ctx).astype(p.dtype)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    sg = _sparse(g)
    if sg is not None:
        from ..core.sparse import merge_rows

        uniq, merged = merge_rows(sg.ids, sg.rows.astype(p.dtype), p.shape[0])
        prox_rows = p[uniq] - lr * merged
        ctx.set_output("ParamOut",
                       p.at[uniq].set(_soft_threshold(prox_rows, lr, l1, l2)))
        return
    prox = p - lr * g.astype(p.dtype)
    ctx.set_output("ParamOut", _soft_threshold(prox, lr, l1, l2))


@register_op("proximal_adagrad")
def proximal_adagrad_op(ctx: OpContext):
    """reference: operators/optimizers/proximal_adagrad_op.h:30."""
    p, g, moment = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    lr = _lr(ctx).astype(p.dtype)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    sg = _sparse(g)
    if sg is not None:
        from ..core.sparse import merge_rows

        uniq, merged = merge_rows(sg.ids, sg.rows.astype(p.dtype), p.shape[0])
        m_rows = moment[uniq] + jnp.square(merged)
        prox_rows = p[uniq] - lr * merged / jnp.sqrt(m_rows)
        ctx.set_output("ParamOut",
                       p.at[uniq].set(_soft_threshold(prox_rows, lr, l1, l2)))
        ctx.set_output("MomentOut", moment.at[uniq].set(m_rows))
        return
    m_new = moment + jnp.square(g.astype(p.dtype))
    prox = p - lr * g.astype(p.dtype) / jnp.sqrt(m_new)
    ctx.set_output("ParamOut", _soft_threshold(prox, lr, l1, l2))
    ctx.set_output("MomentOut", m_new)


@register_op("ftrl")
def ftrl_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    sq_accum, lin_accum = ctx.input("SquaredAccumulator"), ctx.input("LinearAccumulator")
    lr = _lr(ctx)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    new_accum = sq_accum + jnp.square(g)
    if lr_power == -0.5:
        lin_new = lin_accum + g - (jnp.sqrt(new_accum) - jnp.sqrt(sq_accum)) / lr * p
    else:
        lin_new = lin_accum + g - (jnp.power(new_accum, -lr_power) - jnp.power(sq_accum, -lr_power)) / lr * p
    x = l1 * jnp.sign(lin_new) - lin_new
    if lr_power == -0.5:
        y = jnp.sqrt(new_accum) / lr + 2 * l2
    else:
        y = jnp.power(new_accum, -lr_power) / lr + 2 * l2
    p_new = jnp.where(jnp.abs(lin_new) > l1, x / y, jnp.zeros_like(p))
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("SquaredAccumOut", new_accum)
    ctx.set_output("LinearAccumOut", lin_new)


@register_op("lamb")
def lamb_op(ctx: OpContext):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m, v = ctx.input("Moment1"), ctx.input("Moment2")
    b1p, b2p = ctx.input("Beta1Pow"), ctx.input("Beta2Pow")
    lr = _lr(ctx)
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-6)
    wd = ctx.attr("weight_decay", 0.01)
    gf = g.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * gf
    v_new = b2 * v + (1 - b2) * jnp.square(gf)
    m_hat = m_new / (1 - b1p.reshape(()))
    v_hat = v_new / (1 - b2p.reshape(()))
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p.astype(jnp.float32)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
    ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
    ctx.set_output("ParamOut", (p.astype(jnp.float32) - lr * ratio * update).astype(p.dtype))
    ctx.set_output("Moment1Out", m_new)
    ctx.set_output("Moment2Out", v_new)
    ctx.set_output("Beta1PowOut", b1p * b1)
    ctx.set_output("Beta2PowOut", b2p * b2)
