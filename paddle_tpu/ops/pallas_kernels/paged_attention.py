"""Ragged paged-attention Pallas TPU kernel for the serving decode loop.

Motivation (ROADMAP item 1, "Ragged Paged Attention" in PAPERS.md): the
serving engine's hottest inner loop — one decode attention per layer per
fused step — runs as an XLA gather that materializes every slot's KV
context ``[B, max_ctx, H, D]`` in HBM (serving.kv_cache.PagedKVCache
.context) before ops.attention_ops.decode_attention reduces it. That
traffic is ``B * max_ctx * H * D`` elements per layer per step regardless
of how short the ragged sequences actually are. This kernel fuses the page
gather into the attention inner loop: K/V pages stream from the flat page
pool ``[num_pages*page_size, H, D]`` straight into VMEM scratch via
per-page DMAs driven by the device-resident page table, and an
online-softmax accumulator reduces them wave by wave — HBM traffic becomes
``sum_b ctx_len[b] * H * D`` (only the LIVE rows move) and the ``[B,
max_ctx, H, D]`` intermediate never exists.

Design (the sparse_adam batched-DMA pattern applied to attention):

- grid is ``(slots,)``; the page table (flattened) and per-slot ``ctx_len``
  ride in SMEM via ``PrefetchScalarGridSpec`` scalar prefetch, so page
  addresses are known before the body runs;
- per slot, pages stream in waves of ``block_pages`` (the autotunable
  knob, table kernel key ``paged_attention``): each wave starts
  ``2 * block_pages`` row-range DMAs back-to-back (K and V per page), waits
  once, then folds the wave into the online-softmax state ``(m, l, acc)``
  carried through the wave loop in registers;
- the ragged bound: waves whose pages lie entirely at/after ``ctx_len``
  skip their DMAs (``@pl.when``), and the position mask uses
  attention_ops.neg_inf — the SAME masking constant as the gather path —
  so stale rows beyond ``ctx_len`` (retired requests, unreserved pages)
  contribute exactly 0.0, bit-for-bit like the gather path's mask;
- page ids from the table are clamped to the pool, so a corrupt table row
  degrades to wrong-but-safe reads, never an OOB DMA.

``interpret=True`` runs the same kernel through the Pallas interpreter on
CPU — what tier-1 parity tests and the ``--selftest`` CLI use; the
compiled path needs a real TPU. The engine arms the kernel via
``FLAGS_paged_attention_kernel`` (auto = compiled on TPU only; on =
everywhere, interpreted off-TPU; interpret = force the interpreter; off =
gather), resolved by attention_ops.paged_kernel_mode and dispatched from
serving.kv_cache.PagedKVCache.decode_attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only installs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = [
    "paged_decode_attention",
    "gather_reference",
    "paged_attention_supported",
]

_VMEM_WAVE_BUDGET = 2 * 1024 * 1024  # K+V scratch bytes one wave may hold


def paged_attention_supported(dtype) -> bool:
    """Gate: pallas-TPU importable and a float cache dtype."""
    if pltpu is None:
        return False
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def _default_block_pages(page_size: int, pages_per_slot: int, hd: int,
                         itemsize: int = 4) -> int:
    """Largest power-of-two pages-per-wave whose K+V VMEM scratch fits the
    wave budget — the untuned fallback the autotune sweep measures
    against."""
    bp = 1
    while (bp * 2 <= pages_per_slot
           and 2 * (bp * 2) * page_size * hd * itemsize
           <= _VMEM_WAVE_BUDGET):
        bp *= 2
    return bp


def _block_pages(block, page_size: int, pages_per_slot: int, max_ctx: int,
                 hd: int, itemsize: int = 4) -> int:
    """Pages per DMA wave. ``block=None`` (the entry point's default)
    consults the tuned config table (paddle_tpu.tune: kernel
    ``paged_attention``, bucketed by (max_ctx, H*D) + device_kind, with the
    shipped v5e seed) and falls back to the analytic VMEM-budget default —
    an explicit integer is honored verbatim (clamped to the slot's page
    count), which keeps the autotuner's own sweep from looping through the
    table it is writing. The lookup never raises; a corrupt table logs once
    inside tune.table and lands here as the default."""
    if block is None:
        block = _default_block_pages(page_size, pages_per_slot, hd, itemsize)
        try:
            from ...tune import table as _tt

            cfg, _src = _tt.lookup("paged_attention",
                                   _tt.bucket_ctx(max_ctx, hd))
            if cfg and int(cfg.get("block_pages", 0)) > 0:
                block = int(cfg["block_pages"])
        except Exception:
            pass
    return max(1, min(int(block), pages_per_slot))


def _page_dma(table_ref, scr_ref, sem, row, slot_row, ps):
    """Async copy of one page (``ps`` contiguous [H, D] rows) between the
    HBM pool and VMEM scratch."""
    return pltpu.make_async_copy(
        table_ref.at[pl.ds(row, ps)],
        scr_ref.at[pl.ds(slot_row, ps)],
        sem,
    )


def _paged_attn_kernel(pt_ref, len_ref, q_ref, k_hbm, v_hbm, o_ref,
                       k_scr, v_scr, sems, *, block_pages, page_size,
                       pages_per_slot, num_pages, n_waves, sm_scale,
                       mask_value):
    b = pl.program_id(0)
    ps = page_size
    ctx = len_ref[b]
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [H, D]
    h, d = q.shape
    rows = block_pages * ps

    def page_row(i, wave):
        """Pool row offset of wave-local page ``i`` (clamped: a corrupt
        table entry reads a wrong page, never out of bounds)."""
        pidx = jnp.minimum(wave * block_pages + i, pages_per_slot - 1)
        page = pt_ref[b * pages_per_slot + pidx]
        return jnp.clip(page, 0, num_pages - 1) * ps

    def page_valid(i, wave):
        pidx = wave * block_pages + i
        return (pidx < pages_per_slot) & (pidx * ps < ctx)

    def wave_body(w, carry):
        m, l, acc = carry

        def start(i, _):
            @pl.when(page_valid(i, w))
            def _():
                row = page_row(i, w)
                _page_dma(k_hbm, k_scr, sems.at[0, i], row, i * ps, ps).start()
                _page_dma(v_hbm, v_scr, sems.at[1, i], row, i * ps, ps).start()

            return 0

        jax.lax.fori_loop(0, block_pages, start, 0)

        def wait(i, _):
            @pl.when(page_valid(i, w))
            def _():
                row = page_row(i, w)
                _page_dma(k_hbm, k_scr, sems.at[0, i], row, i * ps, ps).wait()
                _page_dma(v_hbm, v_scr, sems.at[1, i], row, i * ps, ps).wait()

            return 0

        jax.lax.fori_loop(0, block_pages, wait, 0)

        # absolute context positions of this wave's scratch rows, and the
        # ragged validity mask (also covers never-DMA'd pages: their
        # positions are >= ctx by construction)
        pos = (w * rows
               + jax.lax.broadcasted_iota(jnp.int32, (1, rows), 1))  # [1,R]
        valid = pos < ctx
        kb = k_scr[...].astype(jnp.float32)  # [R, H, D]
        # invalid rows hold whatever the scratch last held — zero V so the
        # exactly-0 probabilities below cannot meet an Inf/NaN residue
        vb = jnp.where(valid.reshape(-1, 1, 1),
                       v_scr[...].astype(jnp.float32), 0.0)
        s = jnp.sum(q[None, :, :] * kb, axis=-1).T  # [H, R]
        s = jnp.where(valid, s, mask_value)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # [H,1]
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)  # masked lanes underflow to exactly 0.0
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jnp.sum(p.T[:, :, None] * vb, axis=0)
        return m_new, l_new, acc_new

    m0 = jnp.full((h, 1), mask_value, jnp.float32)
    l0 = jnp.zeros((h, 1), jnp.float32)
    acc0 = jnp.zeros((h, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_waves, wave_body, (m0, l0, acc0))
    # ctx_len >= 1 in the engine (position of the current token + 1); the
    # clamp only guards a degenerate ctx_len <= 0 call from dividing 0/0
    out = acc / jnp.maximum(l, jnp.asarray(1e-30, jnp.float32))
    o_ref[0] = out.astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, ctx_len, *,
                           page_size, sm_scale=1.0, block_pages=None,
                           interpret: bool = False):
    """Fused ragged paged decode attention.

    ``q`` [B,H,D] — current position's query per slot. ``k_pages``/
    ``v_pages`` [num_pages*page_size, H, D] — ONE layer of the paged KV
    pool (serving.kv_cache.PagedKVCache state). ``page_table`` [B,
    pages_per_slot] int32 — each slot's ordered page ids. ``ctx_len`` [B] —
    valid leading positions per slot (must be >= 1 for slots whose output
    is consumed). ``block_pages=None`` = tuned-table lookup with the
    analytic VMEM-budget fallback (see ``_block_pages``). Returns [B,H,D],
    matching ``gather_reference`` (the XLA gather + decode_attention path)
    to float32 round-off on live rows and EXACTLY ignoring garbage beyond
    ``ctx_len``.
    """
    if pltpu is None:
        raise RuntimeError(
            "paged_decode_attention: jax.experimental.pallas.tpu unavailable "
            "on this install — gate with paged_attention_supported() (the "
            "XLA gather path is the fallback, "
            "FLAGS_paged_attention_kernel=off)")
    b, h, d = q.shape
    slots, pages_per_slot = page_table.shape
    if slots != b:
        raise ValueError("page_table slots %d != q batch %d" % (slots, b))
    ps = int(page_size)
    num_rows = k_pages.shape[0]
    if num_rows % ps != 0:
        raise ValueError("pool rows %d not a multiple of page_size %d"
                         % (num_rows, ps))
    max_ctx = pages_per_slot * ps
    bp = _block_pages(block_pages, ps, pages_per_slot, max_ctx, h * d,
                      jnp.dtype(k_pages.dtype).itemsize)
    n_waves = -(-pages_per_slot // bp)
    from ..attention_ops import neg_inf_value

    kernel = functools.partial(
        _paged_attn_kernel, block_pages=bp, page_size=ps,
        pages_per_slot=pages_per_slot, num_pages=num_rows // ps,
        n_waves=n_waves, sm_scale=float(sm_scale),
        mask_value=neg_inf_value(jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0)),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),              # K pool
            pl.BlockSpec(memory_space=pltpu.ANY),              # V pool
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bp * ps, h, d), k_pages.dtype),
            pltpu.VMEM((bp * ps, h, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, bp)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(page_table.reshape(-1).astype(jnp.int32),
      ctx_len.astype(jnp.int32), q, k_pages, v_pages)


def gather_reference(q, k_pages, v_pages, page_table, ctx_len, page_size,
                     sm_scale=1.0):
    """The XLA path the kernel replaces, as a standalone reference: the
    PagedKVCache.context gather composed with attention_ops
    .decode_attention (which supplies the SHARED neg_inf masking constant
    — the parity contract the selftest asserts)."""
    ps = int(page_size)
    rows = (page_table * ps)[:, :, None] + jnp.arange(ps)[None, None, :]
    rows = rows.reshape(page_table.shape[0], -1)
    from ..attention_ops import decode_attention

    return decode_attention(q, k_pages[rows], v_pages[rows], ctx_len,
                            sm_scale=sm_scale)


# -- selftest -----------------------------------------------------------------


def _selftest() -> int:
    """CPU interpret-mode parity vs the XLA gather path at mixed ragged
    lengths, including a garbage-page poisoning leg — the CI smoke next to
    sparse_adam --selftest (<5 s)."""
    import time

    t0 = time.time()
    rng = np.random.RandomState(0)
    slots, h, d, ps, pages_per_slot = 5, 2, 16, 8, 8
    num_pages = 24
    max_ctx = pages_per_slot * ps
    sm = 1.0 / float(d) ** 0.5

    # a shared pool with slots owning disjoint page sets, deliberately
    # scrambled so logical order != pool order
    perm = rng.permutation(num_pages)
    pt = np.zeros((slots, pages_per_slot), np.int32)
    for s_i in range(slots):
        pt[s_i] = np.resize(perm[s_i::slots], pages_per_slot)
    # ragged mixed lengths: 1 token, mid-page, page-exact, multi-page, full
    ctx_len = np.array([1, 7, 8, 33, max_ctx], np.int32)

    k_pool = rng.randn(num_pages * ps, h, d).astype(np.float32)
    v_pool = rng.randn(num_pages * ps, h, d).astype(np.float32)
    q = rng.randn(slots, h, d).astype(np.float32)

    def run(kp, vp, block):
        got = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(pt), jnp.asarray(ctx_len), page_size=ps,
            sm_scale=sm, block_pages=block, interpret=True)
        want = gather_reference(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(pt), jnp.asarray(ctx_len), ps, sm_scale=sm)
        return np.asarray(got), np.asarray(want)

    # clean pool, several wave widths (incl. a non-divisor and the tuned
    # default path)
    for block in (1, 3, 4, None):
        got, want = run(k_pool, v_pool, block)
        np.testing.assert_allclose(
            got, want, rtol=1e-6, atol=1e-6,
            err_msg="kernel vs gather mismatch at block_pages=%s" % block)

    # garbage-page poisoning: every pool row NOT covered by a slot's valid
    # prefix gets huge finite garbage (stale retired-request rows). Both
    # paths must be bit-unmoved: their masks zero those contributions
    # exactly. (NaN poisoning is out of contract: the gather path's
    # 0 * NaN would already break.)
    live = np.zeros(num_pages * ps, bool)
    for s_i in range(slots):
        n = int(ctx_len[s_i])
        flat = (pt[s_i].repeat(ps) * ps
                + np.tile(np.arange(ps), pages_per_slot))[:n]
        live[flat] = True
    k_poison = k_pool.copy()
    v_poison = v_pool.copy()
    k_poison[~live] = 1e4 * rng.randn((~live).sum(), h, d)
    v_poison[~live] = -1e4 * np.ones(((~live).sum(), h, d), np.float32)
    got_p, want_p = run(k_poison, v_poison, 2)
    np.testing.assert_allclose(got_p, want_p, rtol=1e-6, atol=1e-6,
                               err_msg="poisoned kernel vs gather mismatch")
    clean, _ = run(k_pool, v_pool, 2)
    np.testing.assert_array_equal(
        got_p, clean,
        err_msg="garbage beyond ctx_len leaked into the kernel output")

    print("paged_attention selftest OK (%.2fs): kernel == gather on %d "
          "ragged slots (ctx %s), garbage pages contribute exactly zero"
          % (time.time() - t0, slots, list(map(int, ctx_len))))
    return 0


if __name__ == "__main__":
    import sys

    if "--selftest" in sys.argv:
        sys.exit(_selftest())
    print("usage: python -m paddle_tpu.ops.pallas_kernels.paged_attention "
          "--selftest")
    sys.exit(2)
