"""Builder-written Pallas TPU kernels for ops where XLA's default lowering
underperforms (the role of the reference's hand-tuned ``operators/jit/`` —
7.2k LoC of JIT-assembled CPU kernels for hot ops).

Kernels:
- softmax_xent: fused softmax + cross-entropy over large vocab
  (forward never materializes the [N, V] probabilities in HBM).

Each kernel has an XLA-composed reference implementation it is numerically
tested against, and ``benchmarks/bench_softmax_xent.py`` measures the win on
real TPU hardware.
"""

from .softmax_xent import fused_softmax_xent, softmax_xent_supported  # noqa: F401
