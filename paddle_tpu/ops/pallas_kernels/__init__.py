"""Builder-written Pallas TPU kernels for ops where XLA's default lowering
underperforms (the role of the reference's hand-tuned ``operators/jit/`` —
7.2k LoC of JIT-assembled CPU kernels for hot ops).

Kernels:
- softmax_xent: fused softmax + cross-entropy over large vocab
  (forward never materializes the [N, V] probabilities in HBM).
- sparse_adam: row-wise sparse Adam/SGD update — batched dynamic-slice row
  DMA replacing the three ~30 GB/s XLA scatter fusions on SelectedRows
  embedding updates (benchmarks/SPARSE_PROFILE.md §1).

Each kernel has an XLA-composed reference implementation it is numerically
tested against, and ``benchmarks/bench_softmax_xent.py`` /
``benchmarks/diag_sparse.py`` measure the win on real TPU hardware.
"""

from .softmax_xent import fused_softmax_xent, softmax_xent_supported  # noqa: F401
from .sparse_adam import (  # noqa: F401
    sparse_adam_rows,
    sparse_rows_supported,
    sparse_sgd_rows,
)
