"""Fused softmax-with-cross-entropy Pallas TPU kernel.

Motivation (SURVEY.md §7 "custom Pallas kernels where XLA underperforms";
reference op: operators/softmax_with_cross_entropy_op.cc, which runs two
separate CUDA kernels — softmax then xent — through a [N, V] intermediate):
with a 30k+ vocabulary the XLA lowering of ``log_softmax + take_along_axis``
materializes [N, V] log-probabilities in HBM on the forward pass and reads
them back in the backward. This kernel streams each [N-tile, V-tile] block
exactly once per pass (online softmax), writing only O(N) outputs forward
(loss + logsumexp residual) and computing ``softmax - onehot`` on the fly in
the backward — HBM traffic drops from ~5·N·V to ~2·N·V elements per
fwd+bwd step.

Layout notes: grid is (N/BN, V/BV) with V minor, so the VMEM scratch
accumulators (running max / sumexp / label logit) persist across a row of V
tiles (TPU grid execution is sequential, last axis fastest). All math in
f32 on the VPU regardless of input dtype (bf16 logits upcast per tile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only installs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

_BN = 256   # batch-tile rows (multiple of 8 for f32 sublanes)
_BV = 2048  # vocab-tile lanes (multiple of 128)

_NEG = -1e30


def _fwd_kernel(labels_ref, logits_ref, loss_ref, lse_ref, m_ref, s_ref, z_ref,
                *, smooth=0.0, v_true=0):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        s_ref[:] = jnp.zeros_like(s_ref)
        z_ref[:] = jnp.zeros_like(z_ref)

    tile = logits_ref[:].astype(jnp.float32)            # [BN, BV]
    m_prev = m_ref[:]                                    # [BN, 1]
    m_new = jnp.maximum(m_prev, jnp.max(tile, axis=1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    s_ref[:] = s_ref[:] * corr + jnp.sum(jnp.exp(tile - m_new), axis=1, keepdims=True)
    m_ref[:] = m_new

    # gather the label logit if it falls inside this vocab tile; with label
    # smoothing, fold in this tile's share of (ε/V)·Σx in the same pass
    # (loss = lse - (1-ε)·x_label - (ε/V)·Σx), masking the -1e30 pad columns
    lab = labels_ref[:].astype(jnp.int32)                # [BN, 1]
    col0 = j * tile.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1) + col0
    hit = cols == lab                                    # [BN, BV]
    zlab = jnp.sum(jnp.where(hit, tile, 0.0), axis=1, keepdims=True)
    if smooth:
        real = cols < v_true
        zsum = jnp.sum(jnp.where(real, tile, 0.0), axis=1, keepdims=True)
        z_ref[:] = z_ref[:] + (1.0 - smooth) * zlab + (smooth / v_true) * zsum
    else:
        z_ref[:] = z_ref[:] + zlab

    @pl.when(j == nv - 1)
    def _():
        lse = m_ref[:] + jnp.log(s_ref[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - z_ref[:]


def _bwd_kernel(labels_ref, logits_ref, lse_ref, g_ref, dlogits_ref,
                *, smooth=0.0, v_true=0):
    j = pl.program_id(1)
    tile = logits_ref[:].astype(jnp.float32)
    p = jnp.exp(tile - lse_ref[:])                       # softmax probs
    lab = labels_ref[:].astype(jnp.int32)
    col0 = j * tile.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1) + col0
    onehot = (cols == lab).astype(jnp.float32)
    if smooth:
        # d/dx[(1-ε)·nll + (ε/V)·Σ(-logp)] = p - (1-ε)·onehot - ε/V
        d = p - (1.0 - smooth) * onehot - (smooth / v_true)
    else:
        d = p - onehot
    dlogits_ref[:] = (g_ref[:] * d).astype(dlogits_ref.dtype)


def softmax_xent_supported(n: int, v: int, dtype) -> bool:
    """Gate: shapes the kernel tiles cleanly and pallas-TPU is importable."""
    if pltpu is None:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return False
    return n >= 8 and v >= 128


def _shrink_tiles(n, v, bn, bv):
    """Clamp requested tiles to the problem: small batches shrink the row
    tile to the next power of two (>=8), small vocabs shrink the lane tile
    to the 128-multiple cover."""
    bn = bn if n >= bn else max(8, 1 << (n - 1).bit_length())
    bv = bv if v >= bv else max(128, -(-v // 128) * 128)
    return bn, bv


def _tile_sizes(n, v):
    """(bn, bv) for this shape: tuned table -> shipped -> the hardcoded
    ``_BN``/``_BV`` defaults (paddle_tpu.tune, kernel key
    ``softmax_xent``). Tuned values are sanitized to the sublane/lane
    multiples the grid needs; the lookup never raises, so a corrupt table
    degrades to the defaults."""
    bn, bv = _BN, _BV
    try:
        from ...tune import table as _tt

        cfg, _src = _tt.lookup("softmax_xent", _tt.bucket_nv(n, v))
        if cfg:
            bn = max(8, (int(cfg.get("block_n", bn)) // 8) * 8)
            bv = max(128, (int(cfg.get("block_v", bv)) // 128) * 128)
    except Exception:
        bn, bv = _BN, _BV
    return _shrink_tiles(n, v, bn, bv)


def _pad_to(logits, labels, bn, bv):
    """Pad [N, V] logits/labels out to the (bn, bv) grid: pad vocab lanes
    carry ``_NEG`` so their exp underflows to exactly 0, pad rows are
    harmless label-0 rows sliced off by the callers."""
    n, v = logits.shape
    n_pad = -(-n // bn) * bn - n
    v_pad = -(-v // bv) * bv - v
    if v_pad:
        logits = jnp.pad(logits, ((0, 0), (0, v_pad)), constant_values=_NEG)
    if n_pad:
        logits = jnp.pad(logits, ((0, n_pad), (0, 0)), constant_values=0.0)
        labels = jnp.pad(labels, ((0, n_pad), (0, 0)), constant_values=0)
    return logits, labels, n_pad, v_pad


def _pad(logits, labels):
    n, v = logits.shape
    bn, bv = _tile_sizes(n, v)
    logits, labels, n_pad, v_pad = _pad_to(logits, labels, bn, bv)
    return logits, labels, bn, bv, n_pad, v_pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_softmax_xent(logits, labels, interpret: bool = False,
                       smooth: float = 0.0):
    """loss[N,1] = CE(softmax(logits), labels) with hard int labels [N,1];
    ``smooth`` applies label smoothing in the same streamed pass."""
    loss, _ = _fwd(logits, labels, interpret, smooth)
    return loss


def _call_fwd(logits, labels, bn, bv, interpret, smooth, v_true):
    n, v = logits.shape
    grid = (n // bn, v // bv)
    acc = lambda: pltpu.VMEM((bn, 1), jnp.float32) if pltpu else None
    return pl.pallas_call(
        functools.partial(_fwd_kernel, smooth=smooth, v_true=v_true),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[acc(), acc(), acc()],
        interpret=interpret,
    )(labels, logits)


def _fwd(logits, labels, interpret, smooth=0.0):
    if pltpu is None and not interpret:
        raise RuntimeError(
            "fused_softmax_xent: pallas TPU backend unavailable on this "
            "build — gate calls with softmax_xent_supported() or pass "
            "interpret=True")
    n, v = logits.shape
    labels = labels.reshape(n, 1)
    plog, plab, bn, bv, n_pad, v_pad = _pad(logits, labels)
    loss, lse = _call_fwd(plog, plab, bn, bv, interpret, float(smooth), v)
    if n_pad:
        loss, lse = loss[:n], lse[:n]
    return loss, lse


def _fused_fwd(logits, labels, interpret, smooth):
    loss, lse = _fwd(logits, labels, interpret, smooth)
    return loss, (logits, labels, lse)


def _fused_bwd(interpret, smooth, res, g):
    logits, labels, lse = res
    n, v = logits.shape
    labels = labels.reshape(n, 1)
    g = g.reshape(n, 1).astype(jnp.float32)
    plog, plab, bn, bv, n_pad, v_pad = _pad(logits, labels)
    if n_pad:
        lse = jnp.pad(lse, ((0, n_pad), (0, 0)), constant_values=0.0)
        g = jnp.pad(g, ((0, n_pad), (0, 0)), constant_values=0.0)
    pn, pv = plog.shape
    grid = (pn // bn, pv // bv)
    dlogits = pl.pallas_call(
        functools.partial(_bwd_kernel, smooth=float(smooth), v_true=v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((pn, pv), logits.dtype),
        interpret=interpret,
    )(plab, plog, lse, g)
    if n_pad or v_pad:
        dlogits = dlogits[:n, :v]
    return dlogits, None


fused_softmax_xent.defvjp(_fused_fwd, _fused_bwd)
