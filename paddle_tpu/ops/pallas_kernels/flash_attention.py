# Vendored from JAX (jax/experimental/pallas/ops/tpu/flash_attention.py,
# jax v0.9.0), under the terms of the Apache License 2.0 below. Vendoring
# makes the FA2 block kernels project-owned: ring attention and sdpa call
# private entry points (_flash_attention_impl, _flash_attention_bwd_dkv/_dq)
# whose upstream signatures/semantics may drift across JAX releases; this
# copy pins them (VERDICT r4 weak #5). Local changes are marked # paddle_tpu.
#
# Copyright 2023 The JAX Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     https://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Flash Attention TPU kernel."""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, NamedTuple

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.dtype("float32")).max)

# paddle_tpu: JAX renamed TPUCompilerParams <-> CompilerParams across
# releases; alias whichever this install lacks so the vendored kernels run
# on both (the container's JAX only has TPUCompilerParams, which broke every
# pallas_call below at import-version skew — found wiring the autotuner's
# flash candidate sweep through the interpreter).
if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover - version skew
  pltpu.CompilerParams = pltpu.TPUCompilerParams

# paddle_tpu: same skew for pl.loop (absent in this install). The kernels
# below only use it with STATIC python-int bounds and unroll=True, for which
# an unrolled python loop over the traced body is semantically identical.
if not hasattr(pl, "loop"):  # pragma: no cover - version skew

  def _compat_loop(lower, upper, *, step=1, unroll=None):
    del unroll  # static bounds; python unrolling IS the unrolled form

    def deco(body):
      for i in range(int(lower), int(upper), int(step)):
        body(jnp.asarray(i, jnp.int32))

    return deco

  pl.loop = _compat_loop

# paddle_tpu: when True, every pallas_call runs in interpret mode so the
# REAL kernel bodies execute on CPU — used by tests/test_ring_flash_parity
# .py to assert flash-vs-composed block parity without TPU hardware.
INTERPRET = False


# paddle_tpu: in-kernel attention-probs dropout ------------------------------
#
# The keep-mask is a pure function of the ABSOLUTE (batch, head, q, k)
# element coordinates and a seed — a counter-based splitmix32-style hash in
# plain jnp u32 ops (pltpu.prng_* has no interpret-mode lowering in this
# JAX). Purity over coordinates means the forward kernel and BOTH backward
# kernels regenerate bit-identical masks regardless of their tile
# partitioning, and the composed reference can reproduce the mask outside
# the kernel for parity tests (tests/test_flash_dropout.py).
#
# Dropout applies to the NORMALIZED probabilities: o = (mask*p/(1-r)) @ v
# with the softmax stats (l, m) computed dropout-free; in the backward,
# dv = pd^T do and ds = p*(g - di) with g = mask*dp/(1-r) and di = rowsum
# (do*o) unchanged (the di term already contracts through the dropped
# probabilities).

def _dropout_keep_tile(dropout_rate, seed, b_idx, h_idx, q_offset, k_offset,
                       shape):
  rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0) + jnp.uint32(q_offset)
  cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1) + jnp.uint32(k_offset)
  x = rows * jnp.uint32(2654435761) ^ cols * jnp.uint32(0x85EBCA6B)
  x = x ^ (jnp.uint32(seed)
           + jnp.uint32(b_idx) * jnp.uint32(0x9E3779B9)
           + jnp.uint32(h_idx) * jnp.uint32(0xC2B2AE35))
  x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
  x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
  x = x ^ (x >> 16)
  threshold = jnp.uint32(min(int(float(dropout_rate) * 4294967296.0),
                             4294967295))
  return x >= threshold

NUM_LANES = 128
NUM_SUBLANES = 8


class SegmentIds(NamedTuple):
  """SegmentIds for Q and KV sequences.

  SegmentIds are used to generate segment mask, which prevents attention between
  different segments in the input sequence. Each array is a list of ids
  (integers).
  Only the token with the same id can attend to each other.

  Attributes:
    q: segment ids along the Q sequence.
    kv: segment ids along the KV sequence.
  """

  q: jax.Array  # [batch_size, q_seq_len]
  kv: jax.Array  # [batch_size, kv_seq_len]


@dataclasses.dataclass(frozen=True)
class BlockSizes:
  """Tile sizes parameterizing FlashAttention kernels.

  Those parameters have negligible effect on numerics, but affect performance
  greatly.
  """
  block_q: int
  block_k_major: int
  block_k: int
  block_b: int

  block_q_major_dkv: int | None = None
  block_k_major_dkv: int | None = None
  block_k_dkv: int | None = None
  block_q_dkv: int | None = None

  block_k_major_dq: int | None = None
  block_k_dq: int | None = None
  block_q_dq: int | None = None

  def __post_init__(self):
    def verify_major_minor(prefix, suffix, major, minor):
      if minor > major:
        raise ValueError(
            f"{prefix}{suffix}={minor} should be smaller than"
            f" {prefix}_major{suffix}={major}"
        )
      if major % minor != 0:
        raise ValueError(
            f"{prefix}{suffix}={minor} should divide"
            f" {prefix}_major{suffix}={major}"
        )

    verify_major_minor("block_k", "", self.block_k_major, self.block_k)
    if self.block_q_major_dkv is not None and self.block_q_dkv is not None:
      verify_major_minor(
          "block_q", "_dkv", self.block_q_major_dkv, self.block_q_dkv
      )
    if self.block_k_major_dkv is not None and self.block_k_dkv is not None:
      verify_major_minor(
          "block_k", "_dkv", self.block_k_major_dkv, self.block_k_dkv
      )
    if self.block_k_major_dq is not None and self.block_k_dq is not None:
      verify_major_minor(
          "block_k", "_dq", self.block_k_major_dq, self.block_k_dq
      )

  @property
  def has_backward_blocks(self) -> bool:
    backward_blocks = (
        self.block_q_major_dkv,
        self.block_k_major_dkv,
        self.block_q_dkv,
        self.block_k_dkv,
        self.block_k_major_dq,
        self.block_k_dq,
        self.block_q_dq,
    )
    return all(b is not None for b in backward_blocks)

  @classmethod
  def get_default(cls, batch_size, num_heads, q_seq_len, kv_len, d_model):
    # TODO(apaszke,sharadmv): Select better parameters based on a heuristic.
    del batch_size, num_heads, q_seq_len, kv_len, d_model  # Unused.
    return BlockSizes(
        block_q=128,
        block_k_major=128,
        block_k=128,
        block_b=1,
        block_q_major_dkv=128,
        block_k_major_dkv=128,
        block_k_dkv=128,
        block_q_dkv=128,
        block_k_major_dq=128,
        block_k_dq=128,
        block_q_dq=128,
    )


@functools.partial(
    jax.jit,
    static_argnames=[
        "causal",
        "sm_scale",
        "block_sizes",
        "debug",
    ],
)
def flash_attention(
    q,  # [batch_size, num_heads, q_seq_len, d_model]
    k,  # [batch_size, num_heads, kv_seq_len, d_model]
    v,  # [batch_size, num_heads, kv_seq_len, d_model]
    ab=None,  # [batch_size, num_heads, q_seq_len, kv_seq_len]
    segment_ids=None,  # q of [batch_size, q_seq_len] and kv of [batch_size, kv_seq_len]
    *,
    causal: bool = False,
    sm_scale: float = 1.0,
    block_sizes: BlockSizes | None = None,
    debug: bool = False,
):
  batch_size, num_heads, q_seq_len, d_model = q.shape
  batch_size_k, num_heads_k, kv_seq_len, d_model_k = k.shape
  batch_size_v, num_heads_v, kv_seq_len_v, d_model_v = v.shape
  if batch_size != batch_size_k or batch_size != batch_size_v:
    raise ValueError(
        f"Batch size mismatch: got {batch_size}, {batch_size_k} and"
        f" {batch_size_v} (for q, k, v respectively)"
    )
  if num_heads != num_heads_k or num_heads != num_heads_v:
    raise ValueError(
        f"Head count mismatch: got {num_heads}, {num_heads_k},"
        f" {num_heads_v} (for q, k, v respectively)"
    )
  if d_model != d_model_k:
    raise ValueError(
        f"Model dimension mismatch: got {d_model} and {d_model_k} (for q and k"
        " respectively)"
    )
  if d_model != d_model_v:
    raise NotImplementedError(
        "V model dimension unequal to KV model dimension unsupported"
    )
  if kv_seq_len != kv_seq_len_v:
    raise ValueError(
        f"KV sequence length mismatch: got {kv_seq_len} and {kv_seq_len_v}"
    )
  if ab is not None:
    if ab.shape != (batch_size, num_heads, q_seq_len, kv_seq_len):
      raise ValueError(
          f"Attention bias shape mismatch: expected ({batch_size=},"
          f" {num_heads=}, {q_seq_len=}, {kv_seq_len=}), got {ab.shape}"
      )
  if segment_ids is not None:
    if segment_ids.q.shape != (batch_size, q_seq_len):
      raise ValueError(
          f"Q segment ids shape mismatch: expected ({batch_size=},"
          f" {q_seq_len=},), got {segment_ids.q.shape}"
      )
    if segment_ids.kv.shape != (batch_size, kv_seq_len):
      raise ValueError(
          f"KV segment ids shape mismatch: expected ({batch_size=},"
          f" {kv_seq_len=},), got {segment_ids.kv.shape}"
      )
  if block_sizes is None:
    block_sizes = BlockSizes.get_default(
        batch_size, num_heads, q_seq_len, kv_seq_len, d_model
    )
  return _flash_attention(
      q, k, v, ab, segment_ids, False, causal, sm_scale, block_sizes, debug
  )


@functools.partial(jax.custom_vjp, nondiff_argnums=range(5, 10))
def _flash_attention(
    q,
    k,
    v,
    ab,
    segment_ids,
    save_residuals,
    causal,
    sm_scale,
    block_sizes,
    debug,
):
  return _flash_attention_impl(
      q,
      k,
      v,
      ab,
      segment_ids,
      save_residuals,
      causal,
      sm_scale,
      block_sizes.block_b,
      block_sizes.block_q,
      block_sizes.block_k_major,
      block_sizes.block_k,
      debug,
  )


def _flash_attention_fwd(
    q,
    k,
    v,
    ab,
    segment_ids,
    save_residuals,
    causal,
    sm_scale,
    block_sizes,
    debug,
):
  if save_residuals:
    raise NotImplementedError("Higher-order AD not supported")
  o, l, m = _flash_attention(
      q, k, v, ab, segment_ids, True, causal, sm_scale, block_sizes, debug
  )
  return o, (q, k, v, ab, segment_ids, o, l, m)


def _flash_attention_bwd(
    save_residuals: bool,
    causal: bool,
    sm_scale: float,
    block_sizes: BlockSizes,
    debug: bool,
    residuals,
    do,
):
  """VJP rule for FlashAttention."""
  if save_residuals:
    raise NotImplementedError("Higher-order AD not supported")
  (q, k, v, ab, segment_ids, o, l, m) = residuals
  if not block_sizes.has_backward_blocks:
    raise ValueError(
        "Program is being differentiated, but not all backward blocks are"
        " specified"
    )

  di = jnp.sum(
      o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1
  )  # [batch_size, num_heads, q_seq_len]

  dk, dv = _flash_attention_bwd_dkv(
      q,
      k,
      v,
      ab,
      segment_ids,
      l,
      m,
      do,
      di,
      block_q_major=block_sizes.block_q_major_dkv,
      block_k_major=block_sizes.block_k_major_dkv,
      block_k=block_sizes.block_k_dkv,
      block_q=block_sizes.block_q_dkv,
      sm_scale=sm_scale,
      causal=causal,
      mask_value=DEFAULT_MASK_VALUE,
      debug=debug,
  )

  dq, ds = _flash_attention_bwd_dq(
      q,
      k,
      v,
      ab,
      segment_ids,
      l,
      m,
      do,
      di,
      block_q_major=block_sizes.block_q_dq,
      block_k_major=block_sizes.block_k_major_dq,
      block_k=block_sizes.block_k_dq,
      sm_scale=sm_scale,
      causal=causal,
      mask_value=DEFAULT_MASK_VALUE,
      debug=debug,
  )
  return dq, dk, dv, ds, None


_flash_attention.defvjp(fwd=_flash_attention_fwd, bwd=_flash_attention_bwd)


MIN_BLOCK_SIZE = 128
TRANS_B_DIM_NUMBERS = (((1,), (1,)), ((), ()))


def below_or_on_diag(r, r_blk_size, c, c_blk_size):
  # A block is considered below or on diagonal as long as the bottom left
  # corner of the block is below or on diagonal.
  return ((r + 1) * r_blk_size - 1) > (c * c_blk_size)


def _flash_attention_kernel(q_tile_ref, *args, **kwargs):
  block_b = q_tile_ref.shape[0]
  # If we're not going to tile the softmax, then we can avoid a bunch of VPU ops.
  if kwargs["block_k"] == kwargs["kv_seq_len"]:
    kernel = _flash_attention_kernel_single_batch_single_step
  else:
    kernel = _flash_attention_kernel_single_batch
  for batch_idx in range(block_b):
    kernel((batch_idx, 0), q_tile_ref, *args, **kwargs)


def _flash_attention_kernel_single_batch(
    batch_idx: tuple[int, ...],
    q_tile_ref,
    k_tile_ref,
    v_tile_ref,
    ab_tile_ref,
    q_segment_ids_tile_ref,
    kv_segment_ids_tile_ref,
    seed_tile_ref,  # paddle_tpu: [1] int32 in SMEM (None without dropout)
    o_tile_ref,  # Output arrays
    l_ref,
    m_ref,
    m_scratch_ref,
    l_scratch_ref,
    acc_scratch_ref,
    *,
    causal,
    sm_scale,
    block_k,
    kv_seq_len,
    mask_value,
    dropout_rate=0.0,  # paddle_tpu
):
  block_k_major = k_tile_ref.shape[2]
  block_q = q_tile_ref.shape[2]
  head_dim = q_tile_ref.shape[-1]

  kv_seq_idx = pl.program_id(3)
  # paddle_tpu: read program ids at kernel top level — inside pl.when/pl.loop
  # bodies the interpret path cannot bind them
  _b_global = pl.program_id(0) * q_tile_ref.shape[0] + batch_idx[0]
  _h_global = pl.program_id(1)
  @pl.when(kv_seq_idx == 0)
  def start_new_sequence():
    m_scratch_ref[batch_idx] = jnp.full(
        m_scratch_ref.shape[2:], -jnp.inf, jnp.float32
    )
    l_scratch_ref[batch_idx] = jnp.zeros(l_scratch_ref.shape[2:], jnp.float32)
    acc_scratch_ref[batch_idx] = jnp.zeros(
        acc_scratch_ref.shape[2:], jnp.float32
    )

  q_seq_idx = pl.program_id(2)
  if causal:
    should_run = below_or_on_diag(q_seq_idx, block_q, kv_seq_idx, block_k_major)
  else:
    should_run = True

  @pl.when(should_run)
  def run():
    @pl.loop(0, block_k_major, step=block_k, unroll=True)
    def _body(start_k):
      m_prev = m_scratch_ref[batch_idx]
      l_prev = l_scratch_ref[batch_idx]
      q = q_tile_ref[batch_idx]  # [block_q, head_dim]
      k = k_tile_ref[
          (*batch_idx, pl.dslice(start_k, block_k), slice(None))
      ]  # [block_k, head_dim]

      s = jax.lax.dot_general(
          q, k, TRANS_B_DIM_NUMBERS, preferred_element_type=jnp.float32
      )  # [block_q, block_k]

      # Add attention bias if needed.
      # TODO(tanburn) Should the attention bias be added before or after
      # multiplication by sm_scale?
      if ab_tile_ref is not None:
        ab = ab_tile_ref[
            (*batch_idx, pl.dslice(None), pl.dslice(start_k, block_k))
        ].astype(jnp.float32)
        s += ab

      if sm_scale != 1.0:
        s *= sm_scale

      mask = None
      if q_segment_ids_tile_ref is not None:
        repeats, rem = divmod(block_k, NUM_LANES)
        if rem:
          raise NotImplementedError(
              f"kv block size must be a multiple of {NUM_LANES}"
          )
        q_segment_ids = jnp.tile(
            q_segment_ids_tile_ref[batch_idx[0]], (1, repeats)
        )  # [block_q, block_k].
        kv_segment_ids = kv_segment_ids_tile_ref[
            batch_idx[0], :1, pl.dslice(start_k, block_k)
        ]  # [1, block_k].
        mask = jnp.equal(q_segment_ids, kv_segment_ids).astype(jnp.bool_)

      if causal:
        mask_shape = (block_q, block_k)
        row_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 0)
        row_ids += q_seq_idx * block_q
        col_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 1)
        col_ids += kv_seq_idx * block_k_major + start_k
        causal_mask = col_ids <= row_ids
        mask = (
            causal_mask if mask is None else jnp.logical_and(mask, causal_mask)
        )

      s = s if mask is None else s + jnp.where(mask, 0.0, mask_value)

      m_curr = jnp.max(s, axis=1)[:, None]  # Row max, shape [block_q, 1].
      m_next = jnp.maximum(m_prev, m_curr)  # Shape [block_q, 128].

      block_k_repeats, rem = divmod(block_k, MIN_BLOCK_SIZE)
      if rem:
        raise NotImplementedError(
            f"{block_k=} should be a multiple of {MIN_BLOCK_SIZE}"
        )
      p = jnp.exp(s - jnp.tile(m_next, (1, block_k_repeats)))

      alpha = jnp.exp(m_prev - m_next)  # Shape [block_q, 128].

      l_corr = alpha * l_prev

      l_next = jnp.sum(p, axis=1)[:, None] + l_corr  # Shape [block_q, 128]

      head_dim_repeats, rem = divmod(head_dim, MIN_BLOCK_SIZE)
      l_broadcast = lambda l: jnp.tile(l, (1, head_dim_repeats))
      if rem:
        if head_dim_repeats == 0:
          l_broadcast = lambda l: l[:, :head_dim]
        else:
          raise NotImplementedError(
              f"{head_dim=} should be a multiple of {MIN_BLOCK_SIZE} if larger"
          )
      l_scratch_ref[batch_idx] = l_next
      m_scratch_ref[batch_idx] = m_next

      l_next_inv_safe = jnp.where(l_next == 0.0, 1.0, 1.0 / l_next)
      acc_scratch_ref[batch_idx] *= l_broadcast(l_corr * l_next_inv_safe)
      v = v_tile_ref[(*batch_idx, pl.dslice(start_k, block_k), slice(None))]
      if dropout_rate > 0.0:  # paddle_tpu: drop probs, stats stay exact
        keep = _dropout_keep_tile(
            dropout_rate, seed_tile_ref[0],
            _b_global, _h_global,
            q_seq_idx * block_q,
            kv_seq_idx * block_k_major + start_k,
            (block_q, block_k))
        p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
      o_curr = jax.lax.dot(
          p.astype(v.dtype), v, preferred_element_type=jnp.float32
      )
      acc_scratch_ref[batch_idx] += o_curr * l_broadcast(l_next_inv_safe)

  @pl.when(kv_seq_idx == (kv_seq_len // block_k_major) - 1)
  def store_output():
    o_tile_ref[batch_idx] = acc_scratch_ref[batch_idx].astype(o_tile_ref.dtype)
    if l_ref is not None:
      l_ref[batch_idx] = l_scratch_ref[batch_idx].astype(l_ref.dtype)
    if m_ref is not None:
      m_ref[batch_idx] = m_scratch_ref[batch_idx].astype(m_ref.dtype)


def _flash_attention_kernel_single_batch_single_step(
    batch_idx: tuple[int, ...],
    q_tile_ref,
    k_tile_ref,
    v_tile_ref,
    ab_tile_ref,
    q_segment_ids_tile_ref,
    kv_segment_ids_tile_ref,
    seed_tile_ref,  # paddle_tpu: [1] int32 in SMEM (None without dropout)
    o_tile_ref,  # Output arrays
    l_ref: Any | None = None,
    m_ref: Any | None = None,
    *,
    causal,
    sm_scale,
    block_k,
    kv_seq_len,
    mask_value,
    dropout_rate=0.0,  # paddle_tpu
):
  block_k_major = k_tile_ref.shape[2]
  block_q = q_tile_ref.shape[2]

  assert kv_seq_len == block_k_major == block_k

  q = q_tile_ref[batch_idx]  # [block_q, head_dim]
  k = k_tile_ref[batch_idx]  # [block_k, head_dim]
  s = jax.lax.dot_general(
      q, k, TRANS_B_DIM_NUMBERS, preferred_element_type=jnp.float32
  )  # [block_q, block_k]

  if ab_tile_ref is not None:
    s += ab_tile_ref[batch_idx].astype(jnp.float32)
  if sm_scale != 1.0:
    s *= sm_scale

  mask = None
  if q_segment_ids_tile_ref is not None:
    repeats, rem = divmod(block_k, NUM_LANES)
    if rem:
      raise NotImplementedError(
          f"kv block size must be a multiple of {NUM_LANES}"
      )
    q_segment_ids = q_segment_ids_tile_ref[
        batch_idx[0]
    ]  # [block_q, NUM_LANES].
    q_segment_ids = jnp.tile(
        q_segment_ids, (1, repeats)
    )  # [block_q, block_k].
    kv_segment_ids = kv_segment_ids_tile_ref[batch_idx[0], :1]  # [1, block_k].
    mask = jnp.equal(q_segment_ids, kv_segment_ids).astype(jnp.bool_)

  if causal:
    q_seq_idx = pl.program_id(2)
    mask_shape = (block_q, block_k)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 0)
    row_ids += q_seq_idx * block_q
    col_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 1)
    causal_mask = col_ids <= row_ids
    mask = causal_mask if mask is None else jnp.logical_and(mask, causal_mask)
  s = s if mask is None else s + jnp.where(mask, 0.0, mask_value)

  m = jnp.max(s, axis=1)[:, None]
  p = jnp.exp(s - m)
  l = jnp.sum(p, axis=1)[:, None]
  p /= l

  if m_ref is not None:
    m_ref[batch_idx] = lax.broadcast_in_dim(m, m_ref.shape[2:], range(2))
  if l_ref is not None:
    l_ref[batch_idx] = lax.broadcast_in_dim(l, l_ref.shape[2:], range(2))

  if dropout_rate > 0.0:  # paddle_tpu: drop normalized probs
    keep = _dropout_keep_tile(
        dropout_rate, seed_tile_ref[0],
        pl.program_id(0) * q_tile_ref.shape[0] + batch_idx[0],
        pl.program_id(1),
        pl.program_id(2) * block_q, 0, (block_q, block_k))
    p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)

  v = v_tile_ref[batch_idx]
  o_tile_ref[batch_idx] = jax.lax.dot(
      p.astype(v.dtype), v, preferred_element_type=jnp.float32
  ).astype(o_tile_ref.dtype)


def _bytes(x: jax.Array | jax.ShapeDtypeStruct) -> int:
  return math.prod(x.shape) * x.dtype.itemsize


def _fwd_cost_estimate(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    ab: jax.Array | None,
    segment_ids: SegmentIds | None,
    *,
    causal: bool,
    sm_scale: jax.Array | None,
    kernel_inputs_specs,
    kernel_outputs_specs,
) -> pl.CostEstimate | None:
  body_cost = pl.estimate_cost(
    mha_reference,
    q, k, v, ab, segment_ids, causal=causal, sm_scale=sm_scale
  )
  input_bytes = sum(_bytes(x) for x in jax.tree.leaves(kernel_inputs_specs))
  output_bytes = sum(_bytes(x) for x in jax.tree.leaves(kernel_outputs_specs))
  return pl.CostEstimate(
      flops=body_cost.flops,
      transcendentals=body_cost.transcendentals,
      bytes_accessed=input_bytes + output_bytes,
  )


def _flash_attention_impl(
    q,
    k,
    v,
    ab,
    segment_ids,
    save_residuals,
    causal,
    sm_scale,
    block_b,
    block_q,
    block_k_major,
    block_k,
    debug,
    dropout_rate=0.0,  # paddle_tpu: in-kernel probs dropout
    dropout_seed=None,  # paddle_tpu: int32 [1] array (traced per step)
):
  batch_size, num_heads, q_seq_len, head_dim = q.shape
  _, _, kv_seq_len, _ = k.shape
  _verify_block("block_q", "q_seq_len", block_q, q_seq_len, should_divide=False)
  _verify_block("block_k_major", "kv_seq_len", block_k_major, kv_seq_len)
  _verify_block("block_k", "kv_seq_len", block_k, kv_seq_len)
  _verify_block("block_b", "batch", block_b, batch_size, should_divide=False)

  # TODO(apaszke): Tile over heads as well.
  grid = (
      pl.cdiv(batch_size, block_b),
      num_heads,
      pl.cdiv(q_seq_len, block_q),
      kv_seq_len // block_k_major,
  )

  def q_index_map(batch_index, head_index, q_seq_index, _):
    return (batch_index, head_index, q_seq_index, 0)

  def kv_index_map(batch_index, head_index, q_seq_index, kv_seq_index):
    if causal:
      # If the kv block is skipped, prefetch the next valid kv block, i.e. the
      # 0th one to be used for the next block_q rows.
      next_kv_index = lax.select(
          below_or_on_diag(q_seq_index, block_q, kv_seq_index, block_k_major),
          kv_seq_index,
          0,
      )
    else:
      next_kv_index = kv_seq_index
    return (batch_index, head_index, next_kv_index, 0)

  def ab_index_map(batch_index, head_index, q_seq_index, kv_seq_index):
    if causal:
      should_run = below_or_on_diag(
          q_seq_index, block_q, kv_seq_index, block_k_major
      )
      # If the ab block is skipped, prefetch the next valid ab block, i.e. the
      # 0th kv to be used for the next block_q rows.
      next_q_index = lax.select(
          should_run,
          q_seq_index,
          lax.select(
              q_seq_index == (q_seq_len // block_q) - 1, 0, q_seq_index + 1
          ),
      )
      next_kv_index = lax.select(should_run, kv_seq_index, 0)
    else:
      next_q_index = q_seq_index
      next_kv_index = kv_seq_index

    return (batch_index, head_index, next_q_index, next_kv_index)

  def o_index_map(batch_index, head_index, q_seq_index, _):
    return (batch_index, head_index, q_seq_index, 0)

  def lm_index_map(batch_index, head_index, q_seq_index, _):
    return (batch_index, head_index, q_seq_index, 0)

  kernel = functools.partial(
      _flash_attention_kernel,
      causal=causal,
      mask_value=DEFAULT_MASK_VALUE,
      sm_scale=sm_scale,
      block_k=block_k,
      kv_seq_len=kv_seq_len,
      dropout_rate=dropout_rate,  # paddle_tpu
  )
  out_shape = jax.ShapeDtypeStruct(shape=q.shape, dtype=q.dtype)
  out_shape = [out_shape]
  out_specs = [pl.BlockSpec((block_b, 1, block_q, head_dim), o_index_map)]

  if block_k != kv_seq_len:
    m_scratch = pltpu.VMEM((block_b, 1, block_q, MIN_BLOCK_SIZE), jnp.float32)
    l_scratch = pltpu.VMEM((block_b, 1, block_q, MIN_BLOCK_SIZE), jnp.float32)
    acc_scratch = pltpu.VMEM((block_b, 1, block_q, head_dim), jnp.float32)
    scratch_shapes = [m_scratch, l_scratch, acc_scratch]
  else:
    scratch_shapes = []

  if save_residuals:
    out_specs = [
        *out_specs,
        pl.BlockSpec((block_b, 1, block_q, MIN_BLOCK_SIZE), lm_index_map),
        pl.BlockSpec((block_b, 1, block_q, MIN_BLOCK_SIZE), lm_index_map),
    ]
    l = jax.ShapeDtypeStruct(
        (batch_size, num_heads, q_seq_len, MIN_BLOCK_SIZE), dtype=jnp.float32
    )
    m = jax.ShapeDtypeStruct(
        (batch_size, num_heads, q_seq_len, MIN_BLOCK_SIZE), dtype=jnp.float32
    )
    out_shape = (*out_shape, l, m)
  else:
    out_specs = [*out_specs, None, None]
    out_shape = (*out_shape, None, None)

  ab_block_spec = (
      pl.BlockSpec((block_b, 1, block_q, block_k_major), ab_index_map)
      if ab is not None else None)

  q_segment_ids_spec = kv_segment_ids_spec = None
  q_segment_ids = kv_segment_ids = None
  if segment_ids is not None:

    def q_segment_ids_index_map(batch_index, head_index, q_seq_index, _):
      del head_index
      return (batch_index, q_seq_index, 0)

    def kv_segment_ids_index_map(
        batch_index, head_index, q_seq_index, kv_seq_index
    ):
      del head_index
      if causal:
        next_kv_index = lax.select(
            below_or_on_diag(q_seq_index, block_q, kv_seq_index, block_k_major),
            kv_seq_index,
            0,
        )
      else:
        next_kv_index = kv_seq_index
      return (batch_index, 0, next_kv_index)

    q_segment_ids_spec = pl.BlockSpec(
        (block_b, block_q, NUM_LANES), q_segment_ids_index_map
    )
    kv_segment_ids_spec = pl.BlockSpec(
        (block_b, NUM_SUBLANES, block_k_major), kv_segment_ids_index_map
    )

    q_segment_ids = jax.lax.broadcast_in_dim(
        segment_ids.q,
        (batch_size, q_seq_len, NUM_LANES),
        (
            0,
            1,
        ),
    )
    kv_segment_ids = jax.lax.broadcast_in_dim(
        segment_ids.kv,
        (batch_size, NUM_SUBLANES, kv_seq_len),
        (
            0,
            2,
        ),
    )

  # paddle_tpu: the per-step dropout seed rides in SMEM (None when off)
  seed_spec = seed_arr = None
  if dropout_rate > 0.0:
    if dropout_seed is None:
      raise ValueError("dropout_rate > 0 requires dropout_seed")
    seed_arr = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

  in_specs = [
      pl.BlockSpec((block_b, 1, block_q, head_dim), q_index_map),
      pl.BlockSpec((block_b, 1, block_k_major, head_dim), kv_index_map),
      pl.BlockSpec((block_b, 1, block_k_major, head_dim), kv_index_map),
      ab_block_spec,
      q_segment_ids_spec,
      kv_segment_ids_spec,
      seed_spec,  # paddle_tpu
  ]

  o, *aux = pl.pallas_call(
      kernel,
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=0,
          grid=grid,
          in_specs=in_specs,
          out_specs=out_specs,
          scratch_shapes=scratch_shapes,
      ),
      out_shape=out_shape,
      debug=debug,
      interpret=INTERPRET,  # paddle_tpu
      compiler_params=pltpu.CompilerParams(
          dimension_semantics=(
              "parallel",
              "parallel",
              "parallel",
              "arbitrary",
          )
      ),
      cost_estimate=_fwd_cost_estimate(
          q,
          k,
          v,
          ab,
          segment_ids,
          causal=causal,
          sm_scale=sm_scale,
          kernel_inputs_specs=(q, k, v, ab, q_segment_ids, kv_segment_ids),
          kernel_outputs_specs=out_shape,
      ),
  )(q, k, v, ab, q_segment_ids, kv_segment_ids, seed_arr)  # paddle_tpu
  if save_residuals:
    l, m = (v[..., 0] for v in aux[-2:])
    return (o, l, m)
  else:
    return o


def _flash_attention_dkv_kernel(
    q_tile_ref,
    k_tile_ref,
    v_tile_ref,
    ab_tile_ref,
    q_segment_ids_tile_ref,
    kv_segment_ids_tile_ref,
    seed_tile_ref,  # paddle_tpu
    l_tile_ref,
    m_tile_ref,
    do_tile_ref,
    di_tile_ref,
    dk_tile_ref,
    dv_tile_ref,
    dk_scratch_ref,
    dv_scratch_ref,
    *,
    sm_scale: float,
    causal: bool,
    mask_value: float,
    q_seq_len: int,
    block_q: int,
    block_k: int,
    dropout_rate: float = 0.0,  # paddle_tpu
):
  _, _, block_q_major, _ = q_tile_ref.shape
  _, _, block_k_major, _ = k_tile_ref.shape

  q_seq_index = pl.program_id(axis=3)
  kv_seq_index = pl.program_id(axis=2)
  _b_global = pl.program_id(0)  # paddle_tpu: top-level read (see fwd note)
  _h_global = pl.program_id(1)

  @pl.when(q_seq_index == 0)
  def start_new_sequence():
    dk_scratch_ref[:, :] = jnp.zeros(dk_scratch_ref.shape, dk_scratch_ref.dtype)
    dv_scratch_ref[:, :] = jnp.zeros(dv_scratch_ref.shape, dv_scratch_ref.dtype)

  def q_body(j, _):
    start_q = j * block_q
    def k_body(i, _):
      start_k = i * block_k
      k = k_tile_ref[0, 0, pl.ds(start_k, block_k), :]
      v = v_tile_ref[0, 0, pl.ds(start_k, block_k), :]
      q = q_tile_ref[0, 0, pl.ds(start_q, block_q), :]  # [block_q, head_dim]
      l = l_tile_ref[0, 0, pl.ds(start_q, block_q), :]  # [block_q, 1]
      m = m_tile_ref[0, 0, pl.ds(start_q, block_q), :]  # [block_q, 1]
      do = do_tile_ref[0, 0, pl.ds(start_q, block_q), :]  # [block_q, head_dim]
      di = di_tile_ref[0, 0, pl.ds(start_q, block_q), :].astype(
          jnp.float32
      )  # [block_q, 1]

      capped_logits = lax.dot_general(
          q, k, TRANS_B_DIM_NUMBERS, preferred_element_type=jnp.float32
      )  # [block_q_major, block_k]

      if ab_tile_ref is not None:
        ab = ab_tile_ref[
            0,
            0,
            pl.dslice(j * block_q, block_q),
            pl.dslice(i * block_k, block_k),
        ].astype(jnp.float32)
        capped_logits += ab

      if sm_scale != 1.0:
        capped_logits *= sm_scale

      mask = None
      if q_segment_ids_tile_ref is not None:
        repeats, rem = divmod(block_k, NUM_LANES)
        if rem:
          raise NotImplementedError(
          )
        q_segment_ids = q_segment_ids_tile_ref[
            0, pl.ds(start_q, block_q), :
        ]  # [block_q, NUM_LANES].
        q_segment_ids = jnp.tile(
            q_segment_ids, (1, repeats)
        )  # [block_q, block_k].
        kv_segment_ids = kv_segment_ids_tile_ref[
            :, 0, pl.ds(start_k, block_k)
        ]  # [1, block_k].
        mask = jnp.equal(q_segment_ids, kv_segment_ids).astype(jnp.bool_)

      if causal:
        mask_shape = (block_q, block_k)
        row_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 0)
        row_ids += q_seq_index * block_q_major + start_q
        col_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 1)
        col_ids += kv_seq_index * block_k_major + start_k
        causal_mask = col_ids <= row_ids
        mask = (
            causal_mask if mask is None else jnp.logical_and(mask, causal_mask)
        )

      capped_logits = (
          capped_logits
          if mask is None
          else capped_logits + jnp.where(mask, 0.0, mask_value)
      )

      p = jnp.exp(capped_logits - m)  # paddle_tpu: [block_q,1] broadcasts
      p = p * (1.0 / l)  # [block_q_major, block_k_major]
      if dropout_rate > 0.0:  # paddle_tpu: regenerate the fwd keep-mask
        keep = _dropout_keep_tile(
            dropout_rate, seed_tile_ref[0],
            _b_global, _h_global,
            q_seq_index * block_q_major + start_q,
            kv_seq_index * block_k_major + start_k,
            (block_q, block_k))
        inv = 1.0 / (1.0 - dropout_rate)
        pd = jnp.where(keep, p * inv, 0.0)
      else:
        keep, inv, pd = None, 1.0, p
      dv = lax.dot(pd.T.astype(do.dtype), do,
                   preferred_element_type=jnp.float32)
      dv_scratch_ref[pl.ds(start_k, block_k), :] += dv.astype(
          dv_scratch_ref.dtype
      )

      # di: [block_q, 128]
      # do: [block_q, head_dim]
      # v: [block_k_major, head_dim]
      dp = lax.dot_general(
          do, v, TRANS_B_DIM_NUMBERS, preferred_element_type=jnp.float32
      )
      if keep is not None:  # paddle_tpu: grad flows through the dropout
        dp = jnp.where(keep, dp * inv, 0.0)
      ds = (dp - di) * p  # paddle_tpu: [block_q,1] di broadcasts

      if sm_scale != 1.0:
        ds = ds * sm_scale

      # ds: [block_q_major, block_k_major]
      # q: [block_q_major, head_dim]
      dk = lax.dot(ds.T.astype(do.dtype), q, preferred_element_type=jnp.float32)
      dk_scratch_ref[pl.ds(start_k, block_k), :] += dk.astype(
          dk_scratch_ref.dtype
      )
    lax.fori_loop(0, block_k_major // block_k, k_body, None, unroll=True)

  if causal:
    should_run = below_or_on_diag(
        q_seq_index, block_q_major, kv_seq_index, block_k_major
    )
  else:
    should_run = True

  @pl.when(should_run)
  def run():
    lax.fori_loop(0, block_q_major // block_q, q_body, None, unroll=True)

  @pl.when(q_seq_index == q_seq_len // block_q_major - 1)
  def end_of_q_sequence():
    dv_tile_ref[0, 0, :, :] = dv_scratch_ref[...].astype(dv_tile_ref.dtype)
    dk_tile_ref[0, 0, :, :] = dk_scratch_ref[...].astype(dk_tile_ref.dtype)


def _flash_attention_bwd_dkv(
    q,
    k,
    v,
    ab,
    segment_ids,
    l,
    m,
    do,
    di,
    *,
    block_q_major: int | None,
    block_q: int | None,
    block_k_major: int | None,
    block_k: int | None,
    sm_scale: float,
    causal: bool = False,
    mask_value: float = DEFAULT_MASK_VALUE,
    debug: bool = False,
    dropout_rate: float = 0.0,  # paddle_tpu
    dropout_seed=None,  # paddle_tpu
):
  batch_size, num_heads, q_seq_len, head_dim = q.shape
  _, _, kv_seq_len, _ = k.shape
  _verify_block("block_q_major_dkv", "q_seq_len", block_q_major, q_seq_len)
  _verify_block("block_q_dkv", "q_seq_len", block_q, q_seq_len)
  _verify_block("block_k_major_dkv", "kv_seq_len", block_k_major, kv_seq_len)
  _verify_block("block_k_dkv", "kv_seq_len", block_k, kv_seq_len)

  # paddle_tpu: [..., 1] is a free reshape; the old broadcast_to 128 lanes
  # materialized ~134 MB per l/m/di per layer pass (~18 ms/step measured on
  # the longseq-LM config) — the kernels broadcast per-row in VMEM instead
  m = m[..., None]
  l = l[..., None]
  di = di[..., None]

  # kv index needs to be before q index since q index is the contractng
  # dimension.
  grid = (
      batch_size,
      num_heads,
      kv_seq_len // block_k_major,
      q_seq_len // block_q_major,
  )

  def qo_index_map(batch_index, head_index, kv_seq_index, q_seq_index):
    if causal:
      # If the q block is skipped, stay at the 0th q block.
      next_q_index = lax.select(
          below_or_on_diag(
              q_seq_index, block_q_major, kv_seq_index, block_k_major
          ),
          q_seq_index,
          0,
      )
    else:
      next_q_index = q_seq_index

    return (batch_index, head_index, next_q_index, 0)

  qo_spec = pl.BlockSpec((1, 1, block_q_major, head_dim), qo_index_map)
  assert qo_spec.block_shape is not None
  assert q.ndim == len(qo_spec.block_shape)
  do_spec = qo_spec
  assert do.ndim == len(qo_spec.block_shape)

  def kv_index_map(batch_index, head_index, kv_seq_index, _):
    return (batch_index, head_index, kv_seq_index, 0)

  kv_spec = pl.BlockSpec((1, 1, block_k_major, head_dim), kv_index_map)
  assert kv_spec.block_shape is not None
  assert k.ndim == len(kv_spec.block_shape)
  assert v.ndim == len(kv_spec.block_shape)

  def lm_index_map(batch_index, head_index, _, q_seq_index):
    return (batch_index, head_index, q_seq_index, 0)

  lm_spec = pl.BlockSpec((1, 1, block_q_major, 1), lm_index_map)  # paddle_tpu
  assert lm_spec.block_shape is not None
  assert l.ndim == len(lm_spec.block_shape)
  assert m.ndim == len(lm_spec.block_shape)

  di_spec = pl.BlockSpec((1, 1, block_q_major, 1), qo_index_map)  # paddle_tpu
  assert di_spec.block_shape is not None
  assert di.ndim == len(di_spec.block_shape)

  def ab_index_map(batch_index, head_index, kv_seq_index, q_seq_index):
    return (batch_index, head_index, q_seq_index, kv_seq_index)

  dab_spec = (
      pl.BlockSpec((1, 1, block_q_major, block_k_major), ab_index_map)
      if ab is not None
      else None
  )

  q_segment_ids_spec = kv_segment_ids_spec = None
  q_segment_ids = kv_segment_ids = None
  if segment_ids is not None:

    def q_segment_ids_index_map(
        batch_index, head_index, kv_seq_index, q_seq_index
    ):
      del head_index
      if causal:
        next_q_index = lax.select(
            below_or_on_diag(
                q_seq_index, block_q_major, kv_seq_index, block_k_major
            ),
            q_seq_index,
            0,
        )
      else:
        next_q_index = q_seq_index
      return (batch_index, next_q_index, 0)

    def kv_segment_ids_index_map(batch_index, head_index, kv_seq_index, _):
      del head_index
      return (batch_index, 0, kv_seq_index)

    q_segment_ids_spec = pl.BlockSpec(
        (1, block_q_major, NUM_LANES), q_segment_ids_index_map
    )
    kv_segment_ids_spec = pl.BlockSpec(
        (1, NUM_SUBLANES, block_k_major), kv_segment_ids_index_map
    )

    q_segment_ids = jax.lax.broadcast_in_dim(
        segment_ids.q,
        (batch_size, q_seq_len, NUM_LANES),
        (
            0,
            1,
        ),
    )
    kv_segment_ids = jax.lax.broadcast_in_dim(
        segment_ids.kv,
        (batch_size, NUM_SUBLANES, kv_seq_len),
        (
            0,
            2,
        ),
    )

  seed_spec = seed_arr = None  # paddle_tpu
  if dropout_rate > 0.0:
    if dropout_seed is None:
      raise ValueError("dropout_rate > 0 requires dropout_seed")
    seed_arr = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

  in_specs = [
      qo_spec,
      kv_spec,
      kv_spec,
      dab_spec,
      q_segment_ids_spec,
      kv_segment_ids_spec,
      seed_spec,  # paddle_tpu
      lm_spec,
      lm_spec,
      do_spec,
      di_spec,
  ]

  out_shapes = [
      jax.ShapeDtypeStruct((batch_size, num_heads, kv_seq_len, head_dim),
                           k.dtype),
      jax.ShapeDtypeStruct((batch_size, num_heads, kv_seq_len, head_dim),
                           v.dtype),
  ]
  def dkv_index_map(batch_index, head_index, kv_seq_index, _):
    return (batch_index, head_index, kv_seq_index, 0)

  dkv_spec = pl.BlockSpec((1, 1, block_k_major, head_dim), dkv_index_map)
  out_specs = [dkv_spec, dkv_spec]
  scratch_shapes = [
      pltpu.VMEM((block_k_major, head_dim), jnp.float32),  # type: ignore
      pltpu.VMEM((block_k_major, head_dim), jnp.float32),  # type: ignore
  ]

  kernel = functools.partial(
      _flash_attention_dkv_kernel,
      block_q=block_q,  # type: ignore
      block_k=block_k,  # type: ignore
      sm_scale=sm_scale,
      causal=causal,
      mask_value=mask_value,
      q_seq_len=q_seq_len,
      dropout_rate=dropout_rate,  # paddle_tpu
  )
  name_scope = f"flash_mha_bwd_dkv_{block_q_major=}_{block_q=}_{block_k_major=}_{block_k=}"
  with jax.named_scope(name_scope):
    dk, dv = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch_shapes,
        ),
        out_shape=out_shapes,
        debug=debug,
        interpret=INTERPRET,  # paddle_tpu
        compiler_params=pltpu.CompilerParams(
                dimension_semantics=(
                    "parallel",
                    "parallel",
                    "parallel",
                    "arbitrary",
                )
        ),
    )(q, k, v, ab, q_segment_ids, kv_segment_ids, seed_arr, l, m, do, di)  # paddle_tpu
    assert dk.shape == k.shape
    assert dv.shape == v.shape
  return dk, dv


def _flash_attention_dq_kernel(
    q_tile_ref,
    k_tile_ref,
    v_tile_ref,
    ab_tile_ref,
    q_segment_ids_tile_ref,
    kv_segment_ids_tile_ref,
    seed_tile_ref,  # paddle_tpu
    l_tile_ref,
    m_tile_ref,
    do_tile_ref,
    di_tile_ref,
    dq_tile_ref,
    ds_tile_ref,
    dq_scratch_ref,
    *,
    sm_scale: float,
    causal: bool,
    mask_value: float,
    kv_seq_len: int,
    block_k: int,
    dropout_rate: float = 0.0,  # paddle_tpu
):
  _, _, block_k_major, _ = k_tile_ref.shape
  _, _, block_q_major, _ = q_tile_ref.shape

  kv_seq_index = pl.program_id(axis=3)
  q_seq_index = pl.program_id(axis=2)
  _b_global = pl.program_id(0)  # paddle_tpu: top-level read (see fwd note)
  _h_global = pl.program_id(1)

  @pl.when(kv_seq_index == 0)
  def start_new_sequence():
    dq_scratch_ref[:, :] = jnp.zeros(dq_scratch_ref.shape, dq_scratch_ref.dtype)

  def body(i, _):
    k_slice = pl.ds(i * block_k, block_k)
    q = q_tile_ref[0, 0, :, :]
    k = k_tile_ref[0, 0, k_slice, :]  # [block_k, head_dim]
    v = v_tile_ref[0, 0, k_slice, :]  # [block_k, head_dim]
    l = l_tile_ref[0, 0, :, :]  # [block_q_major, 1]
    m = m_tile_ref[0, 0, :, :]  # [block_q_major, 1]
    do = do_tile_ref[0, 0, :, :]  # [block_q_major, head_dim]
    di = di_tile_ref[0, 0, :].astype(jnp.float32)  # [block_q_major, 1]

    capped_logits = jax.lax.dot_general(
        q, k, TRANS_B_DIM_NUMBERS, preferred_element_type=jnp.float32
    )

    if ab_tile_ref is not None:
      ab = ab_tile_ref[0, 0, :, pl.dslice(i * block_k, block_k)].astype(
          jnp.float32
      )
      capped_logits += ab

    if sm_scale != 1.0:
      capped_logits *= sm_scale

    mask = None
    if q_segment_ids_tile_ref is not None:
      repeats, rem = divmod(block_k, NUM_LANES)
      if rem:
        raise NotImplementedError(
            f"kv block size must be a multiple of {NUM_LANES}"
        )
      q_segment_ids = jnp.tile(
          q_segment_ids_tile_ref[0], (1, repeats)
      )  # [block_q, block_k].
      kv_segment_ids = kv_segment_ids_tile_ref[:, 0, k_slice]  # [1, block_k].
      mask = jnp.equal(q_segment_ids, kv_segment_ids).astype(jnp.bool_)

    if causal:
      mask_shape = (block_q_major, block_k)
      row_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 0)
      row_ids += q_seq_index * block_q_major
      col_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 1)
      col_ids += kv_seq_index * block_k_major + i * block_k
      causal_mask = col_ids <= row_ids
      mask = causal_mask if mask is None else jnp.logical_and(mask, causal_mask)
    capped_logits = (
        capped_logits
        if mask is None
        else capped_logits + jnp.where(mask, 0.0, mask_value)
    )

    p = jnp.exp(capped_logits - m)  # paddle_tpu: [block_q,1] broadcasts
    p = p * (1.0 / l)  # [block_q_major, block_k]

    # di: [block_q_major, 128]
    # do: [block_q_major, head_dim]
    # v: [block_k_major, head_dim]
    dp = jax.lax.dot_general(
        do,
        v,
        TRANS_B_DIM_NUMBERS,
        preferred_element_type=jnp.float32,
    )
    if dropout_rate > 0.0:  # paddle_tpu: grad flows through the dropout
      keep = _dropout_keep_tile(
          dropout_rate, seed_tile_ref[0],
          _b_global, _h_global,
          q_seq_index * block_q_major,
          kv_seq_index * block_k_major + i * block_k,
          (block_q_major, block_k))
      dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
    ds = (dp - di) * p  # paddle_tpu: [block_q,1] di broadcasts
    # dp = jnp.dot(do, v.T)
    # ds = (dp - (dp * p).sum(axis=1)[:, None]) * p

    if sm_scale != 1.0:
      ds = ds * sm_scale

    if ds_tile_ref is not None:
      ds_tile_ref[0, 0, :, pl.dslice(i * block_k, block_k)] = ds.astype(
          ds_tile_ref.dtype
      )

    # dp: [block_q_major, block_k]
    # k: [block_k, head_dim]
    dq_scratch_ref[:, :] += lax.dot(
        ds.astype(k.dtype),
        k,
        preferred_element_type=jnp.float32,
    ).astype(dq_scratch_ref.dtype)

  if causal:
    should_run = below_or_on_diag(
        q_seq_index, block_q_major, kv_seq_index, block_k_major
    )
    should_not_run = lax.select(should_run, False, True)
  else:
    should_run = True
    should_not_run = False  # type: ignore

  @pl.when(should_run)
  def run():
    lax.fori_loop(0, block_k_major // block_k, body, None, unroll=True)

  @pl.when(should_not_run)
  def zero_out_ds():
    if ds_tile_ref is not None:
      ds_tile_ref[...] = jnp.zeros_like(ds_tile_ref)

  @pl.when(kv_seq_index == kv_seq_len // block_k_major - 1)
  def end_of_kv_sequence():
    dq_tile_ref[0, 0, :, :] = dq_scratch_ref[...].astype(dq_tile_ref.dtype)
    dq_scratch_ref[...] = jnp.zeros_like(dq_scratch_ref)


def _flash_attention_bwd_dq(
    q,
    k,
    v,
    ab,
    segment_ids,
    l,
    m,
    do,
    di,
    *,
    block_q_major: int | None,
    block_k_major: int | None,
    block_k: int | None,
    sm_scale: float,
    causal: bool,
    mask_value: float,
    debug: bool,
    dropout_rate: float = 0.0,  # paddle_tpu
    dropout_seed=None,  # paddle_tpu
):
  batch_size, num_heads, q_seq_len, head_dim = q.shape
  _, _, kv_seq_len, _ = k.shape
  _verify_block("block_q_dq", "q_seq_len", block_q_major, q_seq_len)
  _verify_block("block_k_major_dq", "kv_seq_len", block_k_major, kv_seq_len)
  _verify_block("block_k_dq", "block_k", block_k, kv_seq_len)

  # paddle_tpu: see the dkv wrapper note — last dim 1, kernels broadcast
  m = m[..., None]
  l = l[..., None]
  di = di[..., None]

  grid = (
      batch_size,
      num_heads,
      q_seq_len // block_q_major,
      kv_seq_len // block_k_major,
  )

  def qo_index_map(batch_index, head_index, q_seq_index, _):
    return (batch_index, head_index, q_seq_index, 0)

  qo_spec = pl.BlockSpec((1, 1, block_q_major, head_dim), qo_index_map)
  do_spec = qo_spec

  def kv_index_map(batch_index, head_index, q_seq_index, kv_seq_index):
    if causal:
      # If the kv block is skipped, prefetch the next valid kv block, i.e. the
      # 0th one to be used for the next block_q rows.
      next_kv_index = lax.select(
          below_or_on_diag(
              q_seq_index, block_q_major, kv_seq_index, block_k_major
          ),
          kv_seq_index,
          0,
      )
    else:
      next_kv_index = kv_seq_index
    return (batch_index, head_index, next_kv_index, 0)

  kv_spec = pl.BlockSpec((1, 1, block_k_major, head_dim), kv_index_map)
  assert kv_spec.block_shape is not None
  assert k.ndim == len(kv_spec.block_shape)
  assert v.ndim == len(kv_spec.block_shape)

  def lm_index_map(batch_index, head_index, q_seq_index, _):
    return (batch_index, head_index, q_seq_index, 0)

  lm_spec = pl.BlockSpec((1, 1, block_q_major, 1), lm_index_map)  # paddle_tpu
  assert lm_spec.block_shape is not None
  assert l.ndim == len(lm_spec.block_shape)
  assert m.ndim == len(lm_spec.block_shape)

  di_spec = pl.BlockSpec((1, 1, block_q_major, 1), qo_index_map)  # paddle_tpu
  assert di_spec.block_shape is not None
  assert di.ndim == len(di_spec.block_shape)

  def ab_index_map(batch_index, head_index, q_seq_index, kv_seq_index):
    return (batch_index, head_index, q_seq_index, kv_seq_index)

  dab_spec = (
      pl.BlockSpec((1, 1, block_q_major, block_k_major), ab_index_map)
      if ab is not None
      else None
  )

  q_segment_ids_spec = kv_segment_ids_spec = None
  q_segment_ids = kv_segment_ids = None
  if segment_ids is not None:

    def q_segment_ids_index_map(batch_index, head_index, q_seq_index, _):
      del head_index
      return (batch_index, q_seq_index, 0)

    def kv_segment_ids_index_map(
        batch_index, head_index, q_seq_index, kv_seq_index
    ):
      del head_index
      if causal:
        # If the kv block is skipped, prefetch the next valid kv block, i.e. the
        # 0th one to be used for the next block_q rows.
        next_kv_index = lax.select(
            below_or_on_diag(
                q_seq_index, block_q_major, kv_seq_index, block_k_major
            ),
            kv_seq_index,
            0,
        )
      else:
        next_kv_index = kv_seq_index
      return (batch_index, 0, next_kv_index)

    q_segment_ids_spec = pl.BlockSpec(
        (1, block_q_major, NUM_LANES), q_segment_ids_index_map
    )
    kv_segment_ids_spec = pl.BlockSpec(
        (1, NUM_SUBLANES, block_k_major), kv_segment_ids_index_map
    )

    q_segment_ids = jax.lax.broadcast_in_dim(
        segment_ids.q,
        (batch_size, q_seq_len, NUM_LANES),
        (
            0,
            1,
        ),
    )
    kv_segment_ids = jax.lax.broadcast_in_dim(
        segment_ids.kv,
        (batch_size, NUM_SUBLANES, kv_seq_len),
        (
            0,
            2,
        ),
    )

  seed_spec = seed_arr = None  # paddle_tpu
  if dropout_rate > 0.0:
    if dropout_seed is None:
      raise ValueError("dropout_rate > 0 requires dropout_seed")
    seed_arr = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

  in_specs = [
      qo_spec,
      kv_spec,
      kv_spec,
      dab_spec,
      q_segment_ids_spec,
      kv_segment_ids_spec,
      seed_spec,  # paddle_tpu
      lm_spec,
      lm_spec,
      do_spec,
      di_spec,
  ]

  out_shapes = [
      jax.ShapeDtypeStruct(q.shape, q.dtype),
      jax.ShapeDtypeStruct(ab.shape, ab.dtype) if ab is not None else None,
  ]
  dq_spec = pl.BlockSpec((1, 1, block_q_major, head_dim), qo_index_map)
  out_specs = [
      dq_spec,
      dab_spec,
  ]
  scratch_shapes = [pltpu.VMEM((block_q_major, head_dim), jnp.float32)]  # type: ignore

  kernel = functools.partial(
      _flash_attention_dq_kernel,
      sm_scale=sm_scale,
      causal=causal,
      mask_value=mask_value,
      block_k=block_k,  # type: ignore
      kv_seq_len=kv_seq_len,
      dropout_rate=dropout_rate,  # paddle_tpu
  )
  name_scope = f"flash_mha_bwd_dq_{block_q_major=}_{block_k_major=}_{block_k=}"
  with jax.named_scope(name_scope):
    dq, ds = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch_shapes,
        ),
        out_shape=out_shapes,
        debug=debug,
        interpret=INTERPRET,  # paddle_tpu
        compiler_params=pltpu.CompilerParams(
                dimension_semantics=(
                    "parallel",
                    "parallel",
                    "parallel",
                    "arbitrary",
                )
        ),
    )(q, k, v, ab, q_segment_ids, kv_segment_ids, seed_arr, l, m, do, di)  # paddle_tpu

  # dab is just ds
  return dq, ds


# For autograd testing.
def mha_reference_no_custom_vjp(
    q,
    k,
    v,
    ab: jax.Array | None = None,
    segment_ids: SegmentIds | None = None,
    *,
    causal: bool = False,
    mask_value: float = DEFAULT_MASK_VALUE,
    sm_scale: float = 1.0,
    save_residuals: bool = False,
):
  logits = jnp.einsum("bhqc,bhkc->bhqk", q, k)
  if ab is not None:
    logits += ab
  if sm_scale != 1.0:
    logits *= sm_scale

  mask = None
  if segment_ids is not None:
    mask = segment_ids.q[:, :, None] == segment_ids.kv[:, None, :]
    mask = mask[:, None, :, :]

  if causal:
    _, _, q_seq_len, _ = q.shape
    _, _, kv_seq_len, _ = k.shape
    mask_shape = (q_seq_len, kv_seq_len)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 0)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 1)
    causal_mask = (col_ids <= row_ids)[None, None, :, :]
    mask = causal_mask if mask is None else jnp.logical_and(mask, causal_mask)

  logits = logits if mask is None else logits + jnp.where(mask, 0.0, mask_value)

  m = logits.max(axis=-1)
  unnormalized = jnp.exp(logits - m[..., None])
  l = unnormalized.sum(axis=-1)
  weights = unnormalized / l[..., None]
  out = jnp.einsum("bhqk,bhkc->bhqc", weights, v)
  if save_residuals:
    return out, l, m
  return out


@functools.partial(
    jax.jit, static_argnames=["causal", "mask_value", "sm_scale"]
)
@jax.default_matmul_precision("bfloat16")
def mha_reference(
    q,
    k,
    v,
    ab,
    segment_ids: SegmentIds | None = None,
    causal: bool = False,
    mask_value: float = DEFAULT_MASK_VALUE,
    sm_scale=1.0,
):
  return _mha_reference(
      q,
      k,
      v,
      ab,
      segment_ids,
      causal=causal,
      mask_value=mask_value,
      sm_scale=sm_scale,
      save_residuals=False,
  )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _mha_reference(
    q,
    k,
    v,
    ab,
    segment_ids: SegmentIds | None,
    causal: bool,
    mask_value: float,
    sm_scale: float,
    save_residuals: bool,
):
  return mha_reference_no_custom_vjp(
      q,
      k,
      v,
      ab,
      segment_ids,
      causal=causal,
      mask_value=mask_value,
      sm_scale=sm_scale,
      save_residuals=save_residuals,
  )


def _mha_reference_fwd(
    q,
    k,
    v,
    ab,
    segment_ids: SegmentIds | None,
    causal: bool,
    mask_value: float,
    sm_scale: float,
    save_residuals: bool,
):
  if save_residuals:
    raise NotImplementedError
  res = _mha_reference(
      q,
      k,
      v,
      ab,
      segment_ids,
      causal=causal,
      mask_value=mask_value,
      sm_scale=sm_scale,
      save_residuals=True,
  )
  assert isinstance(res, tuple)
  out, l, m = res
  return out, (q, k, v, ab, segment_ids, out, l, m)


@functools.partial(
    jax.jit,
    static_argnames=[
        "causal",
        "mask_value",
        "sm_scale",
    ],
)
def mha_reference_bwd(
    q,
    k,
    v,
    ab,
    segment_ids: SegmentIds | None,
    o,
    l,
    m,
    do,
    causal: bool = False,
    mask_value: float = DEFAULT_MASK_VALUE,
    sm_scale: float = 1.0,
):
  if sm_scale != 1.0:
    raise NotImplementedError

  logits = jnp.einsum(
      "bhqc,bhkc->bhqk",
      q.astype(jnp.float32),
      k.astype(jnp.float32),
  )
  if ab is not None:
    logits += ab

  mask = None
  if segment_ids is not None:
    mask = segment_ids.q[:, :, None] == segment_ids.kv[:, None, :]
    mask = mask[:, None, :, :]

  if causal:
    _, _, q_seq_len, _ = q.shape
    _, _, kv_seq_len, _ = k.shape
    mask_shape = (q_seq_len, kv_seq_len)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 0)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, mask_shape, 1)
    causal_mask = (col_ids <= row_ids)[None, None, :, :]
    mask = causal_mask if mask is None else jnp.logical_and(mask, causal_mask)

  logits = logits if mask is None else logits + jnp.where(mask, 0.0, mask_value)

  unnormalized = jnp.exp(logits - m[..., None])
  p = unnormalized / l[..., None]
  dv = jnp.einsum("bhpt,bhpd->bhtd", p, do.astype(jnp.float32)).astype(v.dtype)

  dp = jnp.einsum(
      "bhpd,bhtd->bhpt", do.astype(jnp.float32), v.astype(jnp.float32)
  )

  di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)[
      ..., None
  ]  # [batch_size, num_heads, q_seq_len]

  ds = (dp - di) * p
  dk = jnp.einsum("bhsd,bhst->bhtd", q.astype(jnp.float32), ds).astype(k.dtype)
  dq = jnp.einsum("bhst,bhtd->bhsd", ds, k.astype(jnp.float32)).astype(q.dtype)

  # dab is just ds
  dab = ds if ab is not None else None
  return dq, dk, dv, dab


def _mha_reference_bwd(
    causal: bool,
    mask_value: float,
    sm_scale: float,
    save_residuals: bool,
    residuals,
    do,
):
  del save_residuals
  q, k, v, ab, segment_ids, o, l, m = residuals
  dq, dk, dv, dab = mha_reference_bwd(
      q,
      k,
      v,
      ab,
      segment_ids,
      o,
      l,
      m,
      do,
      causal=causal,
      mask_value=mask_value,
      sm_scale=sm_scale,
  )
  return dq, dk, dv, dab, None


_mha_reference.defvjp(fwd=_mha_reference_fwd, bwd=_mha_reference_bwd)


def _verify_block(block_name, dim_name, block, dim, should_divide=True):
  if block > dim:
    raise ValueError(
        f"{block_name}={block} should be smaller or equal to {dim_name}={dim}"
    )
  if should_divide and dim % block != 0:
    raise ValueError(
        f"{dim_name}={dim} should be divisible by {block_name}={block}"
    )
