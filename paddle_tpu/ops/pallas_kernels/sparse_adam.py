"""Row-wise sparse optimizer update Pallas TPU kernel (Adam + SGD).

Motivation (benchmarks/SPARSE_PROFILE.md §1): the SelectedRows-equivalent
sparse path spends its whole overhead in three XLA kCustom scatter fusions —
the param scatter-add plus the two Adam-moment row updates on the [V, D]
tables — which run at ~30 GB/s effective vs ~500 GB/s for a dense
elementwise pass, and at most one of the three tables wins the VMEM
prefetch lottery. XLA's scatter lowering is the cost floor; no graph-level
rewrite moves it (the §1 negative results). This kernel replaces all three
scatters with ONE pass: the merged ``(ids, rows)`` gradient drives
dynamic-slice DMAs that pull only the touched rows of param/m/v from HBM
into VMEM, the Adam math runs vectorized on the VPU, and the updated rows
DMA straight back — so the HBM traffic is 6·N·D elements (3 gathers + 3
writebacks) no matter how large V grows, at row-DMA bandwidth instead of
scatter-pass bandwidth.

Design notes (the naive one-row-per-grid-step kernel priced out at ~20 ms,
SPARSE_PROFILE §4 round-5 residue — this is the batched-DMA design it
called for):

- grid is (N / BLOCK,) with BLOCK ids per step; ids ride in SMEM via
  ``PrefetchScalarGridSpec`` scalar prefetch so row addresses are known
  before the body runs;
- per step, 3·BLOCK row gathers start back-to-back (one DMA semaphore per
  table×row), so the DMA engines pipeline the tiny 4·D-byte transfers
  instead of serializing on a wait per row;
- the tables stay unblocked in ``ANY``/HBM memory space and are
  input/output aliased — untouched rows are never copied;
- merge padding ids (``core/sparse.merge_rows`` pads with ``id == V``)
  gather row 0 (clamped, read-only harmless) but their writeback is
  predicated off, reproducing XLA's OOB-scatter drop semantics.

``interpret=True`` runs the same kernel through the Pallas interpreter on
CPU — that is what tier-1 parity tests and the ``--selftest`` CLI use; the
compiled path needs a real TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only installs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = [
    "sparse_adam_rows",
    "sparse_sgd_rows",
    "sparse_rows_supported",
]

_BLOCK = 128  # ids per grid step = DMAs in flight per gather wave


def sparse_rows_supported(vocab: int, dim: int, dtype) -> bool:
    """Gate: pallas-TPU importable, f32 tables (the CTR workload), and a
    row shape the DMA path handles."""
    if pltpu is None:
        return False
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32):
        return False
    return vocab >= 1 and dim >= 1


def _row_dma(table_ref, scr_ref, sem, row, slot):
    """Async copy of one [1, D] row between an HBM table and VMEM scratch."""
    return pltpu.make_async_copy(
        table_ref.at[pl.ds(row, 1), :],
        scr_ref.at[pl.ds(slot, 1), :],
        sem,
    )


def _row_dma_out(scr_ref, table_ref, sem, slot, row):
    return pltpu.make_async_copy(
        scr_ref.at[pl.ds(slot, 1), :],
        table_ref.at[pl.ds(row, 1), :],
        sem,
    )


def _adam_kernel(ids_ref, scal_ref, p_hbm, m_hbm, v_hbm, rows_ref,
                 p_out, m_out, v_out, p_scr, m_scr, v_scr, sems,
                 *, block, vocab, beta1, beta2, epsilon):
    i = pl.program_id(0)

    def start_gather(j, _):
        row = jnp.minimum(ids_ref[i * block + j], vocab - 1)
        _row_dma(p_hbm, p_scr, sems.at[0, j], row, j).start()
        _row_dma(m_hbm, m_scr, sems.at[1, j], row, j).start()
        _row_dma(v_hbm, v_scr, sems.at[2, j], row, j).start()
        return 0

    jax.lax.fori_loop(0, block, start_gather, 0)

    def wait_gather(j, _):
        row = jnp.minimum(ids_ref[i * block + j], vocab - 1)
        _row_dma(p_hbm, p_scr, sems.at[0, j], row, j).wait()
        _row_dma(m_hbm, m_scr, sems.at[1, j], row, j).wait()
        _row_dma(v_hbm, v_scr, sems.at[2, j], row, j).wait()
        return 0

    jax.lax.fori_loop(0, block, wait_gather, 0)

    # lazy-mode Adam on the touched rows, vectorized over the whole block
    # (identical math to ops/optimizer_ops.adam_op's SelectedRows branch)
    g = rows_ref[:]
    lr_t = scal_ref[0]
    m_new = beta1 * m_scr[:] + (1.0 - beta1) * g
    v_new = beta2 * v_scr[:] + (1.0 - beta2) * jnp.square(g)
    p_scr[:] = p_scr[:] - lr_t * m_new / (jnp.sqrt(v_new) + epsilon)
    m_scr[:] = m_new
    v_scr[:] = v_new

    def start_write(j, _):
        rid = ids_ref[i * block + j]
        row = jnp.minimum(rid, vocab - 1)

        @pl.when(rid < vocab)
        def _():
            _row_dma_out(p_scr, p_out, sems.at[0, j], j, row).start()
            _row_dma_out(m_scr, m_out, sems.at[1, j], j, row).start()
            _row_dma_out(v_scr, v_out, sems.at[2, j], j, row).start()

        return 0

    jax.lax.fori_loop(0, block, start_write, 0)

    def wait_write(j, _):
        rid = ids_ref[i * block + j]
        row = jnp.minimum(rid, vocab - 1)

        @pl.when(rid < vocab)
        def _():
            _row_dma_out(p_scr, p_out, sems.at[0, j], j, row).wait()
            _row_dma_out(m_scr, m_out, sems.at[1, j], j, row).wait()
            _row_dma_out(v_scr, v_out, sems.at[2, j], j, row).wait()

        return 0

    jax.lax.fori_loop(0, block, wait_write, 0)


def _sgd_kernel(ids_ref, scal_ref, p_hbm, rows_ref, p_out, p_scr, sems,
                *, block, vocab):
    i = pl.program_id(0)

    def start_gather(j, _):
        row = jnp.minimum(ids_ref[i * block + j], vocab - 1)
        _row_dma(p_hbm, p_scr, sems.at[0, j], row, j).start()
        return 0

    jax.lax.fori_loop(0, block, start_gather, 0)

    def wait_gather(j, _):
        row = jnp.minimum(ids_ref[i * block + j], vocab - 1)
        _row_dma(p_hbm, p_scr, sems.at[0, j], row, j).wait()
        return 0

    jax.lax.fori_loop(0, block, wait_gather, 0)

    p_scr[:] = p_scr[:] - scal_ref[0] * rows_ref[:]

    def start_write(j, _):
        rid = ids_ref[i * block + j]
        row = jnp.minimum(rid, vocab - 1)

        @pl.when(rid < vocab)
        def _():
            _row_dma_out(p_scr, p_out, sems.at[0, j], j, row).start()

        return 0

    jax.lax.fori_loop(0, block, start_write, 0)

    def wait_write(j, _):
        rid = ids_ref[i * block + j]
        row = jnp.minimum(rid, vocab - 1)

        @pl.when(rid < vocab)
        def _():
            _row_dma_out(p_scr, p_out, sems.at[0, j], j, row).wait()

        return 0

    jax.lax.fori_loop(0, block, wait_write, 0)


def _block_size(block, n_ids, dim=None):
    """ids-per-grid-step, shrunk for small batches and rounded up to the
    f32 sublane multiple so the VMEM scratch tiles cleanly.

    ``block=None`` (the kernel entry points' default) consults the tuned
    config table first (paddle_tpu.tune: shape-bucket + device_kind, with
    the shipped v5e 128-id seed), falling back to the hardcoded ``_BLOCK``
    — an explicit integer is always honored verbatim (modulo the rounding
    below), which is what keeps the autotuner's own sweep from looping
    through the table it is writing. The lookup never raises; a corrupt
    table logs once inside tune.table and lands here as the default."""
    if block is None:
        block = _BLOCK
        try:
            from ...tune import table as _tt

            cfg, _src = _tt.lookup(
                "sparse_adam", _tt.bucket_rows(n_ids, dim or 1))
            if cfg and int(cfg.get("block", 0)) > 0:
                block = int(cfg["block"])
        except Exception:
            pass
    b = min(int(block), max(8, n_ids))
    return -(-b // 8) * 8


def _pad_ids_rows(ids, rows, vocab, block):
    """Pad (ids, rows) to a multiple of ``block``; pad ids carry ``vocab``
    (the merge_rows invalid index) so the kernel's writeback predicate
    drops them."""
    n = ids.shape[0]
    npad = -(-n // block) * block - n
    if npad:
        ids = jnp.concatenate(
            [ids, jnp.full((npad,), vocab, ids.dtype)])
        rows = jnp.concatenate(
            [rows, jnp.zeros((npad,) + rows.shape[1:], rows.dtype)])
    return ids, rows


def sparse_adam_rows(param, moment1, moment2, ids, rows, lr_t,
                     beta1=0.9, beta2=0.999, epsilon=1e-8,
                     interpret: bool = False, block=None):
    """One-kernel lazy Adam over merged sparse rows.

    ``param``/``moment1``/``moment2``: [V, D] f32 tables (aliased in/out —
    untouched rows never move). ``ids``: [N] int32 merged unique row ids,
    padded entries == V. ``rows``: [N, D] f32 merged gradient rows.
    ``lr_t``: bias-corrected scalar step size ``lr·sqrt(1-β2^t)/(1-β1^t)``
    (the same folding adam_op does). ``block=None`` = tuned-table lookup
    with the hardcoded 128 fallback (see ``_block_size``). Returns
    (param, m, v) updated.
    """
    if pltpu is None:
        # the interpreter still needs the TPU grid-spec/memory-space objects
        raise RuntimeError(
            "sparse_adam_rows: jax.experimental.pallas.tpu unavailable on "
            "this install — gate with sparse_rows_supported() (the scatter "
            "path is the fallback, FLAGS_sparse_update_kernel=off)")
    vocab, dim = param.shape
    ids = ids.astype(jnp.int32)
    rows = rows.astype(jnp.float32)
    block = _block_size(block, ids.shape[0], dim)
    ids, rows = _pad_ids_rows(ids, rows, vocab, block)
    n = ids.shape[0]
    scal = jnp.asarray(lr_t, jnp.float32).reshape((1,))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),   # param
            pl.BlockSpec(memory_space=pltpu.ANY),   # moment1
            pl.BlockSpec(memory_space=pltpu.ANY),   # moment2
            pl.BlockSpec((block, dim), lambda i, *_: (i, 0)),  # grad rows
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, dim), jnp.float32),
            pltpu.VMEM((block, dim), jnp.float32),
            pltpu.VMEM((block, dim), jnp.float32),
            pltpu.SemaphoreType.DMA((3, block)),
        ],
    )
    kernel = functools.partial(
        _adam_kernel, block=block, vocab=vocab,
        beta1=float(beta1), beta2=float(beta2), epsilon=float(epsilon))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(param.shape, param.dtype),
            jax.ShapeDtypeStruct(moment1.shape, moment1.dtype),
            jax.ShapeDtypeStruct(moment2.shape, moment2.dtype),
        ],
        # operand order incl. scalar-prefetch args: ids(0) scal(1) p(2)
        # m(3) v(4) rows(5)
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(ids, scal, param, moment1, moment2, rows)


def sparse_sgd_rows(param, ids, rows, lr, interpret: bool = False,
                    block=None):
    """One-kernel SGD over merged sparse rows: rows of ``param`` at ``ids``
    get ``-lr·rows``; padded ids (== V) are dropped. ``block=None`` =
    tuned-table lookup (see ``_block_size``). Returns param."""
    if pltpu is None:
        raise RuntimeError(
            "sparse_sgd_rows: jax.experimental.pallas.tpu unavailable on "
            "this install — gate with sparse_rows_supported() (the scatter "
            "path is the fallback, FLAGS_sparse_update_kernel=off)")
    vocab, dim = param.shape
    ids = ids.astype(jnp.int32)
    rows = rows.astype(jnp.float32)
    block = _block_size(block, ids.shape[0], dim)
    ids, rows = _pad_ids_rows(ids, rows, vocab, block)
    n = ids.shape[0]
    scal = jnp.asarray(lr, jnp.float32).reshape((1,))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((block, dim), lambda i, *_: (i, 0)),
        ],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        scratch_shapes=[
            pltpu.VMEM((block, dim), jnp.float32),
            pltpu.SemaphoreType.DMA((1, block)),
        ],
    )
    kernel = functools.partial(_sgd_kernel, block=block, vocab=vocab)
    (out,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(param.shape, param.dtype)],
        input_output_aliases={2: 0},  # ids(0) scal(1) p(2) rows(3)
        interpret=interpret,
    )(ids, scal, param, rows)
    return out


# -- selftest -----------------------------------------------------------------


def _selftest() -> int:
    """CPU interpret-mode parity vs the XLA scatter formulation — the CI
    smoke next to tools/dump_metrics --selftest (<5 s)."""
    import time

    t0 = time.time()
    rng = np.random.RandomState(0)
    vocab, dim, n = 1000, 10, 96
    raw_ids = rng.randint(0, vocab, (n,)).astype(np.int32)
    raw_ids[: n // 4] = raw_ids[n // 4 : n // 2]  # duplicates
    raw_rows = rng.randn(n, dim).astype(np.float32)

    from ...core.sparse import merge_rows

    uniq, merged = merge_rows(jnp.asarray(raw_ids), jnp.asarray(raw_rows),
                              vocab)
    p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    m = jnp.asarray(rng.randn(vocab, dim).astype(np.float32) * 0.1)
    v = jnp.asarray(np.abs(rng.randn(vocab, dim)).astype(np.float32) * 0.1)
    b1, b2, eps, lr_t = 0.9, 0.999, 1e-8, 0.01

    # scatter reference (adam_op's SelectedRows branch verbatim)
    m_rows = b1 * m[uniq] + (1 - b1) * merged
    v_rows = b2 * v[uniq] + (1 - b2) * jnp.square(merged)
    ref_p = p.at[uniq].add(-(lr_t * m_rows / (jnp.sqrt(v_rows) + eps)))
    ref_m = m.at[uniq].add(m_rows - m[uniq])
    ref_v = v.at[uniq].add(v_rows - v[uniq])

    k_p, k_m, k_v = sparse_adam_rows(p, m, v, uniq, merged, lr_t,
                                     b1, b2, eps, interpret=True)
    for name, a, b in (("param", ref_p, k_p), ("m", ref_m, k_m),
                       ("v", ref_v, k_v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg="adam %s mismatch" % name)

    ref_sgd = p.at[uniq].add(-0.5 * merged)
    k_sgd = sparse_sgd_rows(p, uniq, merged, 0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(ref_sgd), np.asarray(k_sgd),
                               rtol=1e-6, atol=1e-6, err_msg="sgd mismatch")
    print("sparse_adam selftest OK (%.2fs): adam+sgd row-DMA kernel == "
          "scatter path on [%d,%d], %d ids (dups + merge padding)"
          % (time.time() - t0, vocab, dim, n))
    return 0


if __name__ == "__main__":
    import sys

    if "--selftest" in sys.argv:
        sys.exit(_selftest())
    print("usage: python -m paddle_tpu.ops.pallas_kernels.sparse_adam "
          "--selftest")
    sys.exit(2)
