"""NN ops: softmax/losses, normalization, conv/pool, embedding.

Fluid equivalents: ``operators/softmax_op.cc`` (+cudnn),
``softmax_with_cross_entropy_op.cc``, ``batch_norm_op.cc``,
``layer_norm_op.cc``, ``conv_op.cc``/``conv_cudnn_op.cu.cc``,
``pool_op.cc``, ``lookup_table_op.cc``. Convs lower through
``lax.conv_general_dilated`` straight onto the MXU — the role cuDNN plays in
the reference. Data layout is NCHW at the API (Fluid parity); XLA is free to
relayout internally for the TPU's preferred tiling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import OpContext, register_op


@register_op("softmax")
def softmax_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jax.nn.softmax(x, axis=ctx.attr("axis", -1)))


@register_op("log_softmax")
def log_softmax_op(ctx: OpContext):
    ctx.set_output("Out", jax.nn.log_softmax(ctx.input("X"), axis=ctx.attr("axis", -1)))


def _xent_from_probs(probs, label, soft_label, ignore_index=-100):
    if soft_label:
        return -jnp.sum(label * jnp.log(jnp.maximum(probs, 1e-20)), axis=-1, keepdims=True)
    lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
    lbl = lbl.astype(jnp.int32)
    picked = jnp.take_along_axis(probs, jnp.maximum(lbl, 0)[..., None], axis=-1)
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    mask = (lbl != ignore_index)[..., None]
    return jnp.where(mask, loss, jnp.zeros_like(loss))


@register_op("cross_entropy", "cross_entropy2")
def cross_entropy_op(ctx: OpContext):
    probs = ctx.input("X")
    label = ctx.input("Label")
    ctx.set_output(
        "Y",
        _xent_from_probs(
            probs, label, ctx.attr("soft_label", False), ctx.attr("ignore_index", -100)
        ),
    )


def _fused_xent_ok(logits) -> bool:
    """Use the Pallas kernel on TPU for 2D+ float logits with a wide vocab
    (small vocabs gain nothing over the XLA fusion)."""
    if jax.default_backend() in ("cpu", "gpu"):
        return False
    from .pallas_kernels import softmax_xent_supported

    n = 1
    for d in logits.shape[:-1]:
        n *= int(d)
    return (logits.ndim >= 2 and logits.shape[-1] >= 4096
            and softmax_xent_supported(n, logits.shape[-1], logits.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _hard_label_xent(logits, lbl, smooth, ignore):
    """Closed-form CE over hard int labels, with optional label smoothing.

    The residuals are the (bf16) logits + a per-row logsumexp instead of the
    f32 log-probabilities autodiff would save: two exp passes total
    (fwd logsumexp, bwd softmax) and the [N, V]-sized saved buffer stays in
    the input dtype — with a 30k vocab this removes ~2GB of f32 HBM traffic
    per step vs differentiating through jax.nn.log_softmax."""
    loss, _ = _hard_label_xent_fwd(logits, lbl, smooth, ignore)
    return loss


def _hard_label_xent_fwd(logits, lbl, smooth, ignore):
    f = logits.astype(jnp.float32)
    m = jnp.max(f, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(f - m), axis=-1, keepdims=True))
    picked = jnp.take_along_axis(f, jnp.maximum(lbl, 0)[..., None], axis=-1)
    loss = lse - picked
    if smooth:
        k = logits.shape[-1]
        sum_logp = jnp.sum(f, axis=-1, keepdims=True) - k * lse
        loss = (1.0 - smooth) * loss + (smooth / k) * (-sum_logp)
    loss = jnp.where((lbl != ignore)[..., None], loss, jnp.zeros_like(loss))
    return loss, (logits, lbl, lse)


def _hard_label_xent_bwd(smooth, ignore, res, g):
    logits, lbl, lse = res
    f = logits.astype(jnp.float32)
    p = jnp.exp(f - lse)
    k = logits.shape[-1]
    onehot = (jax.lax.broadcasted_iota(jnp.int32, f.shape, f.ndim - 1)
              == lbl[..., None])
    if smooth:
        d = p - (1.0 - smooth) * onehot - (smooth / k)
    else:
        d = p - onehot
    g = jnp.where((lbl != ignore)[..., None], g, jnp.zeros_like(g))
    return (g * d).astype(logits.dtype), None


_hard_label_xent.defvjp(_hard_label_xent_fwd, _hard_label_xent_bwd)


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy_op(ctx: OpContext):
    """One log_softmax pass serves plain CE, soft labels, AND label
    smoothing (``label_smoothing`` attr) — with a wide vocab the logits array
    dominates HBM traffic, so everything is derived from a single read. The
    softmax itself runs in fp32 even under bf16 AMP (logsumexp over 30k
    classes is precision-critical)."""
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    soft_label = ctx.attr("soft_label", False)
    smooth = float(ctx.attr("label_smoothing", 0.0) or 0.0)
    out_dtype = logits.dtype
    if (not soft_label
            and (not smooth or logits.shape[-1] % 128 == 0)
            and ctx.attr("ignore_index", -100) == -100
            and _fused_xent_ok(logits)):
        # Pallas fused path (pallas_kernels/softmax_xent.py): forward writes
        # only O(N) outputs; backward computes softmax-onehot (with the
        # closed-form label-smoothing term) on the fly. Smoothed + ragged
        # vocab stays on the composed path: measured on v5e (16384×30000
        # bf16 fwd+bwd) the pad copy makes pallas 92.8ms vs XLA 82.7ms —
        # XLA fuses the single-pass smoothing formula just as well.
        from .pallas_kernels import fused_softmax_xent

        v = logits.shape[-1]
        lead = logits.shape[:-1]
        lbl2d = label.reshape(-1, 1)
        loss = fused_softmax_xent(logits.reshape(-1, v), lbl2d, False, smooth)
        ctx.set_output("Loss", loss.reshape(*lead, 1).astype(out_dtype))
        if ctx.has_output("Softmax"):
            # derived lazily (reference grad kernel also treats Softmax as a
            # value, not a grad path); dead unless consumed, then XLA DCEs it
            f32 = logits.astype(jnp.float32)
            sm = jnp.exp(f32 - jax.scipy.special.logsumexp(f32, axis=-1, keepdims=True))
            ctx.set_output("Softmax", jax.lax.stop_gradient(sm).astype(out_dtype))
        return
    if not soft_label and not ctx.has_output("Softmax"):
        # hard labels, no softmax requested: closed-form custom-vjp path
        # (residuals are bf16 logits + lse, not f32 log-probs)
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        lbl = lbl.astype(jnp.int32)
        loss = _hard_label_xent(logits, lbl, float(smooth),
                                int(ctx.attr("ignore_index", -100)))
        ctx.set_output("Loss", loss.astype(out_dtype))
        return
    log_p = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    if soft_label:
        loss = -jnp.sum(label * log_p, axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        lbl = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(log_p, jnp.maximum(lbl, 0)[..., None], axis=-1)
        loss = -picked
        if smooth:
            # q = (1-eps)·onehot + eps/K  ⇒  CE = (1-eps)·nll + eps/K·Σ(-logp)
            k = logits.shape[-1]
            loss = (1.0 - smooth) * loss + (smooth / k) * (
                -jnp.sum(log_p, axis=-1, keepdims=True))
        ignore = ctx.attr("ignore_index", -100)
        loss = jnp.where((lbl != ignore)[..., None], loss, jnp.zeros_like(loss))
    if ctx.has_output("Softmax"):
        ctx.set_output("Softmax", jnp.exp(log_p).astype(out_dtype))
    ctx.set_output("Loss", loss.astype(out_dtype))


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_xent_op(ctx: OpContext):
    x = ctx.input("X")
    label = ctx.input("Label")
    # max(x,0) - x*z + log(1+exp(-|x|)) — numerically stable
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = ctx.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, jnp.zeros_like(loss), loss)
    if ctx.attr("normalize", False):
        n = jnp.maximum(jnp.sum((label != ignore).astype(x.dtype)), 1.0)
        loss = loss / n
    ctx.set_output("Out", loss)


@register_op("log_loss")
def log_loss_op(ctx: OpContext):
    p = ctx.input("Predicted")
    y = ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    ctx.set_output("Loss", -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps))


@register_op("huber_loss")
def huber_loss_op(ctx: OpContext):
    x, y = ctx.input("X"), ctx.input("Y")
    d = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * r * r, d * (ar - 0.5 * d))
    ctx.set_output("Residual", r)
    ctx.set_output("Out", loss)


@register_op("smooth_l1_loss")
def smooth_l1_loss_op(ctx: OpContext):
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ctx.has_input("InsideWeight"):
        diff = diff * ctx.input("InsideWeight")
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ctx.has_input("OutsideWeight"):
        loss = loss * ctx.input("OutsideWeight")
    ctx.set_output("Diff", diff)
    ctx.set_output("Out", jnp.sum(loss, axis=tuple(range(1, loss.ndim)), keepdims=False).reshape(x.shape[0], 1))


@register_op("hinge_loss")
def hinge_loss_op(ctx: OpContext):
    logits, labels = ctx.input("Logits"), ctx.input("Labels")
    ctx.set_output("Loss", jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register_op("rank_loss")
def rank_loss_op(ctx: OpContext):
    label = ctx.input("Label")
    left, right = ctx.input("Left"), ctx.input("Right")
    d = left - right
    ctx.set_output("Out", jnp.log1p(jnp.exp(d)) - label * d)


@register_op("bpr_loss")
def bpr_loss_op(ctx: OpContext):
    x = ctx.input("X")
    label = ctx.input("Label").reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, label[:, None], axis=-1)
    diff = x - pos
    loss = jnp.mean(jnp.log1p(jnp.exp(diff)), axis=-1, keepdims=True)
    ctx.set_output("Y", loss)


@register_op("margin_rank_loss")
def margin_rank_loss_op(ctx: OpContext):
    label, x1, x2 = ctx.input("Label"), ctx.input("X1"), ctx.input("X2")
    margin = ctx.attr("margin", 0.0)
    out = jnp.maximum(-label * (x1 - x2) + margin, 0.0)
    ctx.set_output("Out", out)
    ctx.set_output("Activated", (out > 0).astype(x1.dtype))


# -- normalization ------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, scale, bias, reduce_axes, eps):
    y, _ = _bn_train_fwd(x, scale, bias, reduce_axes, eps)
    return y


def _bn_stats(x, reduce_axes):
    # f32 ACCUMULATION directly off the bf16 input — never materializes an
    # f32 copy of the activation (jnp.mean(x.astype(f32)) does, and its VJP
    # then drags f32 [N,C,H,W] cotangents through the whole backward)
    n = 1
    for a in reduce_axes:
        n *= x.shape[a]
    mean = jnp.sum(x, axis=reduce_axes, dtype=jnp.float32) / n
    var = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=reduce_axes,
                  dtype=jnp.float32) / n - jnp.square(mean)
    return mean, var, n


def _bn_train_fwd(x, scale, bias, reduce_axes, eps):
    mean, var, _ = _bn_stats(x, reduce_axes)
    inv = jax.lax.rsqrt(var + eps)
    bshape = [1] * x.ndim
    ch_axis = [a for a in range(x.ndim) if a not in reduce_axes][0]
    bshape[ch_axis] = x.shape[ch_axis]
    xhat = (x - mean.astype(x.dtype).reshape(bshape)) * inv.astype(x.dtype).reshape(bshape)
    y = (xhat * scale.astype(x.dtype).reshape(bshape)
         + bias.astype(x.dtype).reshape(bshape))
    return y, (x, scale, mean, inv)


def _bn_train_bwd(reduce_axes, eps, res, dy):
    # classic fused BN backward (reference: batch_norm_op.cc grad kernel):
    # dx = (γ·inv/N)·(N·dy − Σdy − x̂·Σ(dy·x̂)) — two f32-accumulated
    # reductions and one elementwise pass, all in x.dtype
    x, scale, mean, inv = res
    ch_axis = [a for a in range(x.ndim) if a not in reduce_axes][0]
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    n = 1
    for a in reduce_axes:
        n *= x.shape[a]
    xhat = (x - mean.astype(x.dtype).reshape(bshape)) * inv.astype(x.dtype).reshape(bshape)
    dy_sum = jnp.sum(dy, axis=reduce_axes, dtype=jnp.float32)
    dyxhat_sum = jnp.sum((dy * xhat).astype(jnp.float32), axis=reduce_axes,
                         dtype=jnp.float32)
    dscale = dyxhat_sum
    dbias = dy_sum
    coef = (scale.astype(jnp.float32) * inv / n).astype(x.dtype)
    dx = coef.reshape(bshape) * (
        n * dy
        - dy_sum.astype(x.dtype).reshape(bshape)
        - xhat * dyxhat_sum.astype(x.dtype).reshape(bshape))
    return dx, dscale.astype(scale.dtype), dbias.astype(scale.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("batch_norm")
def batch_norm_op(ctx: OpContext):
    """Reference: operators/batch_norm_op.cc. NCHW/NHWC via data_layout attr.

    Training: normalize by batch stats; MeanOut/VarianceOut are the running
    stats updated with momentum (Fluid aliases them onto Mean/Variance — here
    the functional env rebinds the same names).
    """
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var = ctx.input("Mean"), ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    layout = ctx.attr("data_layout", "NCHW")
    use_global = ctx.attr("use_global_stats", False) or ctx.is_test

    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    cdt = jnp.float32
    if use_global:
        use_mean, use_var = mean.astype(cdt), var.astype(cdt)
        ctx.set_output("MeanOut", mean)
        ctx.set_output("VarianceOut", var)
        inv = jax.lax.rsqrt(use_var + eps).astype(x.dtype)
        y = (x - use_mean.astype(x.dtype).reshape(bshape)) * inv.reshape(bshape)
        y = (y * scale.astype(x.dtype).reshape(bshape)
             + bias.astype(x.dtype).reshape(bshape))
        ctx.set_output("Y", y)
    else:
        # custom-vjp fused path: f32-accumulated stats straight off the bf16
        # input and the closed-form BN backward — autodiff through the stats
        # otherwise drags f32 [N,C,H,W] cotangents through the graph
        # (measured ~30% of ResNet-50 step HBM traffic)
        bmean, bvar, _ = _bn_stats(x, reduce_axes)
        bmean = jax.lax.stop_gradient(bmean)
        bvar = jax.lax.stop_gradient(bvar)
        ctx.set_output("MeanOut", (momentum * mean.astype(cdt) + (1 - momentum) * bmean).astype(mean.dtype))
        ctx.set_output("VarianceOut", (momentum * var.astype(cdt) + (1 - momentum) * bvar).astype(var.dtype))
        ctx.set_output("SavedMean", bmean.astype(mean.dtype))
        ctx.set_output("SavedVariance", bvar.astype(var.dtype))
        ctx.set_output("Y", _bn_train(x, scale, bias, reduce_axes, eps))


def _ln_stats(x, axes):
    n = 1
    for a in axes:
        n *= x.shape[a]
    mean = jnp.sum(x, axis=axes, keepdims=True, dtype=jnp.float32) / n
    var = (jnp.sum(jnp.square(x.astype(jnp.float32)), axis=axes,
                   keepdims=True, dtype=jnp.float32) / n - jnp.square(mean))
    return mean, var, n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln_train(x, scale, bias, axis, eps):
    """Layer norm with the closed-form backward — same HBM rationale as
    _bn_train: f32 accumulation off the bf16 input, residuals in x.dtype."""
    y, _ = _ln_train_fwd(x, scale, bias, axis, eps)
    return y


def _ln_train_fwd(x, scale, bias, axis, eps):
    axes = tuple(range(axis, x.ndim))
    mean, var, _ = _ln_stats(x, axes)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    norm_shape = x.shape[axis:]
    y = xhat
    if scale is not None:
        y = y * scale.astype(x.dtype).reshape(norm_shape)
    if bias is not None:
        y = y + bias.astype(x.dtype).reshape(norm_shape)
    return y, (x, scale, bias, mean, inv)


def _ln_train_bwd(axis, eps, res, dy):
    x, scale, bias, mean, inv = res
    axes = tuple(range(axis, x.ndim))
    lead = tuple(range(axis))
    n = 1
    for a in axes:
        n *= x.shape[a]
    norm_shape = x.shape[axis:]
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    dscale = (jnp.sum((dy * xhat).astype(jnp.float32), axis=lead)
              .reshape(-1) if scale is not None else None)
    dbias = (jnp.sum(dy, axis=lead, dtype=jnp.float32).reshape(-1)
             if bias is not None else None)
    dyh = dy * scale.astype(dy.dtype).reshape(norm_shape) if scale is not None else dy
    s1 = jnp.sum(dyh, axis=axes, keepdims=True, dtype=jnp.float32)
    s2 = jnp.sum((dyh * xhat).astype(jnp.float32), axis=axes, keepdims=True,
                 dtype=jnp.float32)
    coef = (inv / n).astype(x.dtype)
    dx = coef * (n * dyh - s1.astype(x.dtype) - xhat * s2.astype(x.dtype))
    return (dx,
            dscale.astype(scale.dtype) if scale is not None else None,
            dbias.astype(bias.dtype) if bias is not None else None)


_ln_train.defvjp(_ln_train_fwd, _ln_train_bwd)


@register_op("layer_norm")
def layer_norm_op(ctx: OpContext):
    """Reference: operators/layer_norm_op.cc — normalize over dims >= begin_norm_axis."""
    x = ctx.input("X")
    axis = ctx.attr("begin_norm_axis", 1)
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(axis, x.ndim))
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var, _ = _ln_stats(x, axes)
    ctx.set_output("Y", _ln_train(x, scale, bias, axis, eps))
    ctx.set_output("Mean", jax.lax.stop_gradient(
        mean.reshape(x.shape[:axis]).reshape(-1)))
    ctx.set_output("Variance", jax.lax.stop_gradient(
        var.reshape(x.shape[:axis]).reshape(-1)))


@register_op("group_norm")
def group_norm_op(ctx: OpContext):
    x = ctx.input("X")  # NCHW
    groups = ctx.attr("groups")
    eps = ctx.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape(n, groups, c // groups, *x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    ctx.set_output("Y", y)
    ctx.set_output("Mean", mean.reshape(n, groups))
    ctx.set_output("Variance", var.reshape(n, groups))


@register_op("instance_norm")
def instance_norm_op(ctx: OpContext):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    if scale is not None:
        bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        y = y * scale.reshape(bshape) + bias.reshape(bshape)
    ctx.set_output("Y", y)


@register_op("lrn")
def lrn_op(ctx: OpContext):
    x = ctx.input("X")  # NCHW
    n_size = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n_size // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    acc = jnp.zeros_like(x)
    for i in range(n_size):
        acc = acc + pad[:, i : i + x.shape[1]]
    mid = k + alpha * acc
    ctx.set_output("MidOut", mid)
    ctx.set_output("Out", x / jnp.power(mid, beta))


@register_op("data_norm")
def data_norm_op(ctx: OpContext):
    x = ctx.input("X")
    size = ctx.input("BatchSize")
    bsum = ctx.input("BatchSum")
    bsq = ctx.input("BatchSquareSum")
    means = bsum / size
    scales = jax.lax.rsqrt(bsq / size - jnp.square(means) + 1e-4)
    ctx.set_output("Means", means)
    ctx.set_output("Scales", scales)
    ctx.set_output("Y", (x - means) * scales)


@register_op("affine_channel")
def affine_channel_op(ctx: OpContext):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    layout = ctx.attr("data_layout", "NCHW")
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    ctx.set_output("Out", x * scale.reshape(bshape) + bias.reshape(bshape))


# -- conv / pool --------------------------------------------------------------


def _conv_nd(ctx: OpContext, nd: int, transpose: bool = False):
    x = ctx.input("Input")
    w = ctx.input("Filter")  # OIHW (layout-independent param storage)
    strides = tuple(ctx.attr("strides", [1] * nd))
    paddings = ctx.attr("paddings", [0] * nd)
    dilations = tuple(ctx.attr("dilations", [1] * nd))
    groups = ctx.attr("groups", 1) or 1
    pad = [(p, p) for p in paddings]
    spatial = "DHW"[-nd:]
    # NHWC is the TPU-preferred activation layout (channels on the 128-lane
    # minor dim); params stay OIHW so checkpoints are layout-portable
    fmt = ctx.attr("data_format", "NCHW")
    lhs_spec = ("N" + spatial + "C") if fmt in ("NHWC", "NDHWC") else "NC" + spatial
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, (lhs_spec, rhs_spec, lhs_spec))
    if not transpose:
        # No preferred_element_type widening: the TPU MXU already accumulates
        # bf16 convs in fp32 internally, and the f32 hint breaks jax.grad
        # (the transpose conv then mixes a f32 cotangent with bf16 operands).
        out = jax.lax.conv_general_dilated(
            x,
            w,
            window_strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
    else:
        # conv_transpose: fluid filter layout is [in_c, out_c/g, H, W]
        w_t = jnp.swapaxes(w, 0, 1)  # → [out_c/g, in_c, H, W]
        w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + nd)))
        out = jax.lax.conv_general_dilated(
            x,
            w_t,
            window_strides=(1,) * nd,
            padding=[
                (d * (k - 1) - p, d * (k - 1) - p)
                for k, p, d in zip(w.shape[2:], paddings, dilations)
            ],
            lhs_dilation=strides,
            rhs_dilation=dilations,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
    ctx.set_output("Output", out)


@register_op("conv2d", "depthwise_conv2d")
def conv2d_op(ctx):
    _conv_nd(ctx, 2)


@register_op("conv3d")
def conv3d_op(ctx):
    _conv_nd(ctx, 3)


@register_op("conv2d_transpose", "depthwise_conv2d_transpose")
def conv2d_transpose_op(ctx):
    _conv_nd(ctx, 2, transpose=True)


@register_op("conv3d_transpose")
def conv3d_transpose_op(ctx):
    _conv_nd(ctx, 3, transpose=True)


def _pool_nd(ctx: OpContext, nd: int):
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    ksize = list(ctx.attr("ksize", [1] * nd))
    strides = list(ctx.attr("strides", [1] * nd))
    paddings = list(ctx.attr("paddings", [0] * nd))
    nhwc = ctx.attr("data_format", "NCHW") in ("NHWC", "NDHWC")
    sp0 = 1 if nhwc else 2  # first spatial axis
    red = jnp.max if ptype == "max" else jnp.mean
    if ctx.attr("global_pooling", False) or (
            ctx.attr("adaptive", False) and all(k == 1 for k in ksize)):
        axes = tuple(range(sp0, sp0 + nd))
        ctx.set_output("Out", red(x, axis=axes, keepdims=True))
        return
    if ctx.attr("adaptive", False):
        # Adaptive pooling (reference: nn.py adaptive_pool2d/3d lowering to
        # pool ops with adaptive=True): ksize holds the OUTPUT sizes; window
        # d covers [floor(i·in/out), ceil((i+1)·in/out)). Divisible dims use
        # a reshape+reduce (one fused XLA op); ragged dims unroll a static
        # per-output-slice loop (output sizes are small, e.g. 7).
        out = x
        for d, osize in enumerate(int(k) for k in ksize):
            axis = sp0 + d
            insize = out.shape[axis]
            if insize % osize == 0:
                k = insize // osize
                shp = out.shape[:axis] + (osize, k) + out.shape[axis + 1:]
                out = red(out.reshape(shp), axis=axis + 1)
            else:
                sl = [slice(None)] * out.ndim
                pieces = []
                for i in range(osize):
                    sl[axis] = slice((i * insize) // osize,
                                     -((-(i + 1) * insize) // osize))
                    pieces.append(red(out[tuple(sl)], axis=axis))
                out = jnp.stack(pieces, axis=axis)
        ctx.set_output("Out", out)
        return
    if nhwc:
        window = (1,) + tuple(ksize) + (1,)
        stride = (1,) + tuple(strides) + (1,)
        pad = ((0, 0),) + tuple((p, p) for p in paddings) + ((0, 0),)
    else:
        window = (1, 1) + tuple(ksize)
        stride = (1, 1) + tuple(strides)
        pad = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, stride, pad)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, pad)
        if ctx.attr("exclusive", True) and any(p > 0 for p in paddings):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, pad)
            out = summed / counts
        else:
            out = summed / float(np.prod(ksize))
    ctx.set_output("Out", out)


@register_op("pool2d")
def pool2d_op(ctx):
    _pool_nd(ctx, 2)


@register_op("pool3d")
def pool3d_op(ctx):
    _pool_nd(ctx, 3)


# -- embedding ----------------------------------------------------------------


@register_op("lookup_table", "lookup_table_v2")
def lookup_table_op(ctx: OpContext):
    """Reference: operators/lookup_table_op.cc. Ids [..., 1] int → [..., D].

    ``is_sparse=True`` reproduces the SelectedRows gradient path
    (core/sparse.py): the table is read through ``stop_gradient`` and a
    zero "virtual rows" tensor [N, D] (an extra differentiated input the
    executor threads in) is added to the gathered rows, so the backward
    yields an O(N·D) rows gradient and the O(V·D) dense scatter-add never
    exists in the graph. Dense mode keeps the plain differentiable gather.
    Sharded embeddings live in paddle_tpu/parallel.
    """
    w = ctx.input("W")
    ids = ctx.input("Ids")
    squeeze_last = ids.ndim > 1 and ids.shape[-1] == 1 and ctx.op.type == "lookup_table"
    if squeeze_last:
        ids = ids.reshape(ids.shape[:-1])
    ids = ids.astype(jnp.int32)
    padding_idx = ctx.attr("padding_idx", -1)

    w_name = ctx.op.inputs["W"][0]
    env = ctx.env
    collect = env.get("__sparse_collect__")
    if collect is not None and ctx.attr("is_sparse", False):
        d = w.shape[1]
        if w_name in collect:
            raise NotImplementedError(
                "sparse embedding table %r is looked up more than once in one "
                "program — use is_sparse=False for shared tables" % w_name)
        collect[w_name] = ((int(np.prod(ids.shape)), d), w.dtype)
    # clamp BOTH ends for the gather: jnp.take's single-device default
    # clips, but a row-sharded table turns the gather into per-shard gathers
    # where XLA's out-of-bounds semantics are undefined (garbage/NaN) —
    # explicit clipping keeps mesh and single-device behavior identical for
    # stray ids
    virtuals = env.get("__sparse_virtual__") or {}
    if w_name in virtuals:
        flat_raw = ids.reshape(-1)
        flat_ids = jnp.clip(flat_raw, 0, w.shape[0] - 1)
        gathered = jnp.take(jax.lax.stop_gradient(w), flat_ids, axis=0)
        gathered = gathered.astype(virtuals[w_name].dtype) + virtuals[w_name]
        out = gathered.reshape(ids.shape + (w.shape[1],))
        # the optimizer-facing id list maps masked ids (< 0, output zeroed
        # below ⇒ zero grad row) to V — the merge_rows invalid index — so
        # the row-wise update DROPS them instead of lazily decaying row 0's
        # moments every step
        env["__sparse_ids__" + w_name] = jnp.where(
            flat_raw < 0, jnp.asarray(w.shape[0], flat_ids.dtype), flat_ids)
    else:
        out = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)
    out = jnp.where((ids >= 0)[..., None], out, jnp.zeros_like(out))
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], jnp.zeros_like(out), out)
    ctx.set_output("Out", out)


# -- metrics ------------------------------------------------------------------


@register_op("accuracy")
def accuracy_op(ctx: OpContext):
    """Reference: operators/metrics/accuracy_op.cc — takes top-k Indices + Label."""
    indices = ctx.input("Indices")
    label = ctx.input("Label")
    lbl = label.reshape(-1, 1)
    correct = jnp.any(indices == lbl, axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.asarray(lbl.shape[0], jnp.int32)
    ctx.set_output("Accuracy", num_correct.astype(jnp.float32) / lbl.shape[0])
    ctx.set_output("Correct", num_correct)
    ctx.set_output("Total", total)


@register_op("auc")
def auc_op(ctx: OpContext):
    """Streaming AUC via histogram stats (reference: operators/metrics/auc_op.cc)."""
    preds = ctx.input("Predict")
    label = ctx.input("Label").reshape(-1)
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    num_buckets = stat_pos.shape[-1]
    pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else preds.reshape(-1)
    bucket = jnp.clip((pos_prob * num_buckets).astype(jnp.int32), 0, num_buckets - 1)
    is_pos = (label > 0).astype(stat_pos.dtype)
    new_pos = stat_pos.reshape(-1).at[bucket].add(is_pos)
    new_neg = stat_neg.reshape(-1).at[bucket].add(1 - is_pos)
    # AUC = P(score_pos > score_neg): for each neg bucket, count positives in
    # strictly higher buckets plus half the same-bucket ties.
    tot_pos = jnp.sum(new_pos)
    pos_below_incl = jnp.cumsum(new_pos)
    pos_above = tot_pos - pos_below_incl
    auc_sum = jnp.sum(new_neg * (pos_above + new_pos * 0.5))
    tot_neg = jnp.sum(new_neg)
    auc = jnp.where(tot_pos * tot_neg > 0, auc_sum / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0)
    ctx.set_output("AUC", auc.astype(jnp.float32))
    ctx.set_output("StatPosOut", new_pos.reshape(stat_pos.shape))
    ctx.set_output("StatNegOut", new_neg.reshape(stat_neg.shape))


@register_op("mean_iou")
def mean_iou_op(ctx: OpContext):
    preds = ctx.input("Predictions").reshape(-1)
    labels = ctx.input("Labels").reshape(-1)
    num_classes = ctx.attr("num_classes")
    cm = jnp.zeros((num_classes, num_classes), jnp.float32).at[labels, preds].add(1.0)
    inter = jnp.diag(cm)
    union = jnp.sum(cm, 0) + jnp.sum(cm, 1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    ctx.set_output("OutMeanIou", jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1))


# -- interpolation ------------------------------------------------------------


def _interp(ctx: OpContext, method: str):
    x = ctx.input("X")  # NCHW
    out_h = ctx.attr("out_h", 0)
    out_w = ctx.attr("out_w", 0)
    if ctx.has_input("OutSize"):
        sz = np.asarray(ctx.input("OutSize"))
        out_h, out_w = int(sz[0]), int(sz[1])
    scale = ctx.attr("scale", 0.0)
    if (not out_h or out_h <= 0) and scale:
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    out = jax.image.resize(x, (x.shape[0], x.shape[1], out_h, out_w), method=method)
    ctx.set_output("Out", out.astype(x.dtype))


@register_op("bilinear_interp")
def bilinear_interp_op(ctx):
    _interp(ctx, "bilinear")


@register_op("nearest_interp")
def nearest_interp_op(ctx):
    _interp(ctx, "nearest")
