"""Core math ops.

Fluid equivalents: ``operators/mul_op.cc``, ``matmul_op.cc``,
``elementwise/*``, ``scale_op.cc``, ``sum_op.cc``, ``mean_op.cc`` etc. —
each a hand-written CPU/CUDA kernel pair. Here each is a few lines of
jax.numpy that XLA lowers onto the MXU/VPU and fuses with neighbors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import to_jnp_dtype
from ..core.registry import OpContext, register_op


def _dim_prod(dims):
    """Product of dims that stays symbolic under jax.export shape polymorphism
    (int()/np.prod would force symbolic dims to constants)."""
    p = 1
    for d in dims:
        p = p * d
    return p


def _flatten_to_2d(x, num_col_dims: int):
    lead = _dim_prod(x.shape[:num_col_dims]) if num_col_dims > 0 else 1
    return x.reshape(lead, -1)


@register_op("mul")
def mul_op(ctx: OpContext):
    """Flattened matmul (reference: operators/mul_op.cc). FC's engine.

    TPU-first: ONE dot_general contracting x's trailing dims against y's
    leading dims — no 2D reshape round-trip. The explicit flatten the
    reference's kernel does (and this op did pre-r4) inserted [B*S, D]
    bitcasts around every fc that broke XLA layout propagation through the
    attention combine-heads transpose, materializing 36 physical-layout
    copies (+4.4 GB/step) on the Transformer-base bench (diag_hlo_traffic).
    """
    x, y = ctx.input("X"), ctx.input("Y")
    xd = ctx.attr("x_num_col_dims", 1)
    yd = ctx.attr("y_num_col_dims", 1)
    if tuple(x.shape[xd:]) != tuple(y.shape[:yd]):
        # contraction matches in product, not per-dim (flatten semantics):
        # reshape the WEIGHT side (small, layout-free) so the activation
        # never round-trips through a 2D flatten
        y = y.reshape(tuple(x.shape[xd:]) + tuple(y.shape[yd:]))
        yd = x.ndim - xd
    out = jax.lax.dot_general(
        x, y,
        dimension_numbers=((tuple(range(xd, x.ndim)), tuple(range(yd))),
                           ((), ())))
    ctx.set_output("Out", out)


@register_op("matmul")
def matmul_op(ctx: OpContext):
    x, y = ctx.input("X"), ctx.input("Y")
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = ctx.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    ctx.set_output("Out", out)


def _elementwise(ctx: OpContext, fn):
    x, y = ctx.input("X"), ctx.input("Y")
    # AMP autocast (torch-autocast rule): a mixed bf16/f32 binary op computes
    # in the AMP dtype instead of numpy-promoting to f32. Without this, one
    # f32 constant entering the residual stream (e.g. a positional-encoding
    # table) silently upcasts every downstream activation — measured 56% extra
    # HBM traffic on the Transformer-base bench.
    prog = getattr(ctx.trace, "program", None)
    amp = getattr(prog, "_amp_dtype", None) if prog is not None else None
    if amp is not None and hasattr(x, "dtype") and hasattr(y, "dtype"):
        from ..core.dtypes import to_jnp_dtype

        def castable(slot):
            # Only ACTIVATIONS autocast. Persistable vars (parameters, AMP
            # master weights, user state) keep their deliberate f32 — the
            # rule targets accidental promotions (an f32 constant entering
            # the bf16 stream), not user-pinned precision.
            names = ctx.op.inputs.get(slot)
            if not names:
                return True
            block = getattr(ctx.op, "block", None)
            if block is None or not block.has_var(names[0]):
                return True  # op-test harness vars: no Variable metadata
            return not block.var(names[0]).persistable

        adt = jnp.dtype(to_jnp_dtype(amp))
        if x.dtype == adt and y.dtype == jnp.float32 and castable("Y"):
            y = y.astype(adt)
        elif y.dtype == adt and x.dtype == jnp.float32 and castable("X"):
            x = x.astype(adt)
    axis = ctx.attr("axis", -1)
    if x.shape != y.shape and axis != -1 and y.ndim < x.ndim:
        # Fluid axis semantics: y's dims align with x's dims starting at axis.
        new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
        y = y.reshape(new_shape)
    elif x.shape != y.shape and axis == -1 and y.ndim < x.ndim:
        # Default: align trailing dims; pad leading 1s only when the trailing
        # alignment fails under numpy broadcasting but the "subsequence from
        # the back" interpretation works — numpy semantics already cover it.
        pass
    ctx.set_output("Out", fn(x, y))


@register_op("elementwise_add")
def elementwise_add(ctx):
    _elementwise(ctx, jnp.add)


@register_op("elementwise_sub")
def elementwise_sub(ctx):
    _elementwise(ctx, jnp.subtract)


@register_op("elementwise_mul")
def elementwise_mul(ctx):
    _elementwise(ctx, jnp.multiply)


@register_op("elementwise_div")
def elementwise_div(ctx):
    _elementwise(ctx, jnp.divide)


@register_op("elementwise_max")
def elementwise_max(ctx):
    _elementwise(ctx, jnp.maximum)


@register_op("elementwise_min")
def elementwise_min(ctx):
    _elementwise(ctx, jnp.minimum)


@register_op("elementwise_pow")
def elementwise_pow(ctx):
    _elementwise(ctx, jnp.power)


@register_op("elementwise_mod")
def elementwise_mod(ctx):
    _elementwise(ctx, jnp.mod)


@register_op("elementwise_floordiv")
def elementwise_floordiv(ctx):
    _elementwise(ctx, jnp.floor_divide)


@register_op("scale")
def scale_op(ctx: OpContext):
    x = ctx.input("X")
    scale = jnp.asarray(ctx.attr("scale", 1.0), x.dtype)
    bias = jnp.asarray(ctx.attr("bias", 0.0), x.dtype)
    if ctx.attr("bias_after_scale", True):
        ctx.set_output("Out", x * scale + bias)
    else:
        ctx.set_output("Out", (x + bias) * scale)


@register_op("sum")
def sum_op(ctx: OpContext):
    """add_n over inputs (reference: operators/sum_op.cc)."""
    xs = ctx.inputs("X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output("Out", out)


@register_op("mean")
def mean_op(ctx: OpContext):
    ctx.set_output("Out", jnp.mean(ctx.input("X")))


@register_op("sign")
def sign_op(ctx):
    ctx.set_output("Out", jnp.sign(ctx.input("X")))


@register_op("clip")
def clip_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.clip(x, ctx.attr("min"), ctx.attr("max")))


@register_op("clip_by_norm")
def clip_by_norm(ctx: OpContext):
    x = ctx.input("X")
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    ctx.set_output("Out", x * scale.astype(x.dtype))


@register_op("cumsum")
def cumsum_op(ctx: OpContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    rev = ctx.attr("reverse", False)
    excl = ctx.attr("exclusive", False)
    if rev:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis, dtype=x.dtype)
    if excl:
        out = out - x
    if rev:
        out = jnp.flip(out, axis)
    ctx.set_output("Out", out)


@register_op("norm")
def norm_op(ctx: OpContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    ctx.set_output("Out", x / norm)
    ctx.set_output("Norm", norm)


@register_op("l1_norm")
def l1_norm_op(ctx):
    ctx.set_output("Out", jnp.sum(jnp.abs(ctx.input("X"))))


@register_op("squared_l2_norm")
def squared_l2_norm_op(ctx):
    ctx.set_output("Out", jnp.sum(jnp.square(ctx.input("X"))))


@register_op("squared_l2_distance")
def squared_l2_distance_op(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    diff = x - y
    ctx.set_output("sub_result", diff)
    ctx.set_output("Out", jnp.sum(jnp.square(diff), axis=tuple(range(1, diff.ndim)), keepdims=True).reshape(x.shape[0], 1))


@register_op("cos_sim")
def cos_sim_op(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    ctx.set_output("XNorm", xn)
    ctx.set_output("YNorm", yn)
    ctx.set_output("Out", jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn))


@register_op("cast")
def cast_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", x.astype(to_jnp_dtype(ctx.attr("out_dtype", "float32"))))


@register_op("minus")
def minus_op(ctx):
    ctx.set_output("Out", ctx.input("X") - ctx.input("Y"))


@register_op("increment")
def increment_op(ctx: OpContext):
    x = ctx.input("X")
    ctx.set_output("Out", x + jnp.asarray(ctx.attr("step", 1.0), x.dtype))


@register_op("bilinear_tensor_product")
def bilinear_tensor_product_op(ctx: OpContext):
    x, y, w = ctx.input("X"), ctx.input("Y"), ctx.input("Weight")
    # w: [out, dx, dy]; out[b,o] = x[b]·W[o]·y[b]
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    bias = ctx.input("Bias")
    if bias is not None:
        out = out + bias
    ctx.set_output("Out", out)


@register_op("dot")
def dot_op(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    ctx.set_output("Out", jnp.sum(x * y, axis=-1, keepdims=True))


@register_op("isfinite")
def isfinite_op(ctx):
    ctx.set_output("Out", jnp.all(jnp.isfinite(ctx.input("X"))))


@register_op("has_inf")
def has_inf_op(ctx):
    ctx.set_output("Out", jnp.any(jnp.isinf(ctx.input("X"))))


@register_op("has_nan")
def has_nan_op(ctx):
    ctx.set_output("Out", jnp.any(jnp.isnan(ctx.input("X"))))
