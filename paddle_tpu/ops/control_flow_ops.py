"""Control-flow ops: while / cond / recurrent (StaticRNN).

Fluid runs sub-blocks through the C++ executor recursively
(``operators/controlflow/while_op.cc``, ``conditional_block_op.cc``,
``recurrent_op.cc``) with scope inheritance. The TPU-native equivalents are
XLA-structured control flow — ``lax.while_loop``, ``lax.cond``, ``lax.scan``
— with the sub-block interpreted inside the body and the written-variable set
threaded as the functional carry (replacing Fluid's kid-scope mutation,
executor.cc:447-456).

Notes:
- ``recurrent`` (StaticRNN) uses lax.scan and is fully differentiable — the
  training path for RNNs.
- ``while`` uses lax.while_loop: forward-only under autodiff (XLA's reverse
  rule limitation); use recurrent/scan for trainable loops, while for
  inference-style loops (beam search, generation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.interpreter import run_block_ops
from ..core.registry import OpContext, register_op


def _sub_block(ctx: OpContext, attr_name: str):
    return ctx.trace.program.blocks[ctx.attr(attr_name)]


@register_op("while")
def while_op(ctx: OpContext):
    block = _sub_block(ctx, "sub_block")
    cond_name = ctx.op.inputs["Condition"][0]
    carry_names = list(ctx.attr("carry_vars"))
    env = ctx.env

    def cond_fn(carry):
        return carry[cond_name].reshape(())

    def body_fn(carry):
        local = dict(env)
        local.update(carry)
        run_block_ops(block.ops, local, ctx.trace, offset=10_000 * block.idx)
        return {n: local[n] for n in carry_names}

    init = {n: env[n] for n in carry_names}
    for n, v in init.items():
        from .beam_search_ops import EMPTY_ARRAY

        if isinstance(v, tuple) and v == EMPTY_ARRAY:
            raise ValueError(
                "TensorArray %r is carried through a While loop but was never "
                "written before it — its buffer has no shape yet, which breaks "
                "the loop's fixed carry structure. array_write an init element "
                "(e.g. at index 0) before entering the loop." % n)
    out = jax.lax.while_loop(cond_fn, body_fn, init)
    # the op's Out slot lists the carry names themselves — rebind them
    for n in carry_names:
        env[n] = out[n]


@register_op("conditional_block")
def conditional_block_op(ctx: OpContext):
    """Two-branch cond: true_block / false_block attrs, shared output names."""
    pred = ctx.input("Cond").reshape(())
    true_block = _sub_block(ctx, "true_block")
    false_idx = ctx.attr("false_block", -1)
    out_names = ctx.output_names("Out")
    env = ctx.env

    def run_branch(block):
        def fn(_):
            local = dict(env)
            run_block_ops(block.ops, local, ctx.trace, offset=10_000 * block.idx)
            return tuple(local[n] for n in out_names)

        return fn

    if false_idx >= 0:
        false_block = ctx.trace.program.blocks[false_idx]
        outs = jax.lax.cond(pred, run_branch(true_block), run_branch(false_block), None)
    else:
        # no else branch: outputs must already exist; keep them unchanged
        def identity(_):
            return tuple(env[n] for n in out_names)

        outs = jax.lax.cond(pred, run_branch(true_block), identity, None)
    for n, v in zip(out_names, outs):
        env[n] = v


@register_op("recurrent")
def recurrent_op(ctx: OpContext):
    """StaticRNN via lax.scan (reference: operators/recurrent_op.cc).

    attrs: sub_block, step_inputs [(outer_name, inner_name)], memories
    [(inner_prev_name, updated_inner_name, init_outer_name)], step_outputs
    [inner_name...]; outputs stacked on axis 0 (time-major).
    """
    block = _sub_block(ctx, "sub_block")
    step_inputs = ctx.attr("step_inputs")
    memories = ctx.attr("memories")
    step_outputs = ctx.attr("step_outputs")
    env = ctx.env

    xs = {inner: env[outer] for outer, inner in step_inputs}
    init = {prev: env[init_name] for prev, _, init_name in memories}
    seq_len = env[step_inputs[0][0]].shape[0] if step_inputs else 0

    def body(carry, inp):
        x_t, t_idx = inp
        local = dict(env)
        local.update(x_t)
        local.update(carry)
        from ..core.interpreter import PerStepTrace

        run_block_ops(block.ops, local, PerStepTrace(ctx.trace, t_idx),
                      offset=10_000 * block.idx)
        new_carry = {prev: local[updated] for prev, updated, _ in memories}
        ys = tuple(local[n] for n in step_outputs)
        return new_carry, ys

    final_carry, ys = jax.lax.scan(body, init, (xs, jnp.arange(seq_len)))
    ctx.set_outputs("Out", list(ys))
    for n, v in zip(ctx.output_names("Out"), ys):
        env[n] = v
    for (prev, updated, _), name in zip(memories, ctx.output_names("FinalStates")):
        env[name] = final_carry[prev]
