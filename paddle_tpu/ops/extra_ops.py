"""Op-parity sweep batch (reference files noted per op): the remaining
generally-useful forward ops from the reference's operator inventory that
had no TPU implementation yet.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import OpContext, register_op


@register_op("add_position_encoding")
def add_position_encoding_op(ctx: OpContext):
    """reference: operators/add_position_encoding_op.cc — sinusoidal PE
    scaled into the input: out = alpha·x + beta·PE."""
    x = ctx.input("X")  # [B, T, D]
    alpha = float(ctx.attr("alpha", 1.0))
    beta = float(ctx.attr("beta", 1.0))
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    half = d // 2
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    if pe.shape[1] < d:
        pe = jnp.pad(pe, ((0, 0), (0, d - pe.shape[1])))
    ctx.set_output("Out", alpha * x + beta * pe[None].astype(x.dtype))


@register_op("affine_grid")
def affine_grid_op(ctx: OpContext):
    """reference: operators/affine_grid_op.cc — theta [N, 2, 3] → sampling
    grid [N, H, W, 2] in [-1, 1] coords (pairs with grid_sampler for STN)."""
    theta = ctx.input("Theta")
    if ctx.has_input("OutputShape"):
        shp = ctx.input("OutputShape")
        n, _, h, w = (int(s) for s in np.asarray(shp))
    else:
        n, _, h, w = ctx.attr("output_shape")
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)      # [H*W, 3]
    out = jnp.einsum("nij,pj->npi", theta.astype(jnp.float32), base)
    ctx.set_output("Output", out.reshape(theta.shape[0], h, w, 2).astype(theta.dtype))


@register_op("modified_huber_loss")
def modified_huber_loss_op(ctx: OpContext):
    """reference: operators/modified_huber_loss_op.cc (labels {0,1} → ±1)."""
    x = ctx.input("X")
    y = ctx.input("Y")
    t = 2.0 * y.astype(jnp.float32) - 1.0
    z = x.astype(jnp.float32) * t
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    ctx.set_output("IntermediateVal", z.astype(x.dtype))
    ctx.set_output("Out", loss.astype(x.dtype))


@register_op("teacher_student_sigmoid_loss")
def teacher_student_sigmoid_loss_op(ctx: OpContext):
    """reference: operators/teacher_student_sigmoid_loss_op.cc — CTR
    distillation loss over a blended teacher/student label."""
    x = ctx.input("X").astype(jnp.float32).reshape(-1)
    label = ctx.input("Label").astype(jnp.float32).reshape(-1)
    # label packing (teacher_student_sigmoid_loss_op.h:38): -2 = no-teacher
    # no-click; -1 = no-teacher click; [0,1) = teacher-q no-click;
    # [1,2] = 1 + teacher-q, click.
    relu_x = jnp.maximum(x, 0.0)
    softplus = jnp.log1p(jnp.exp(-jnp.abs(x)))
    bce = relu_x + softplus           # -log(1 - sigmoid(x))·… the z=0 case
    bce_click = relu_x - x + softplus  # z=1 case
    loss = jnp.where(
        label < -1.0, bce,
        jnp.where(label < 0.0, bce_click,
        jnp.where(label < 1.0, bce + relu_x - x * label + softplus,
                  bce_click + relu_x - x * (label - 1.0) + softplus)))
    ctx.set_output("Y", loss.reshape(-1, 1).astype(ctx.input("X").dtype))


@register_op("sampling_id")
def sampling_id_op(ctx: OpContext):
    """reference: operators/sampling_id_op.cc — sample one column index per
    row of a probability matrix."""
    x = ctx.input("X")
    key = ctx.rng()
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1)
    ctx.set_output("Out", ids.astype(jnp.int32))


@register_op("random_crop")
def random_crop_op(ctx: OpContext):
    """reference: operators/random_crop_op.cc — crop the trailing dims to
    ``shape`` at a random offset (train) / center (test)."""
    x = ctx.input("X")
    shape = [int(s) for s in ctx.attr("shape")]
    nd = len(shape)
    lead = x.ndim - nd
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        if ctx.is_test or limit <= 0:
            starts.append(limit // 2 if limit > 0 else 0)
        else:
            key, sub = jax.random.split(key)
            starts.append(jax.random.randint(sub, (), 0, limit + 1))
    out = jax.lax.dynamic_slice(
        x, tuple([0] * lead) + tuple(starts), tuple(x.shape[:lead]) + tuple(shape))
    ctx.set_output("Out", out)


@register_op("sequence_conv")
def sequence_conv_op(ctx: OpContext):
    """reference: operators/sequence_conv_op.cc — conv over the time axis
    with a context window. X [B, T, D] (+ Length), Filter
    [ctx_len·D, filters]."""
    x = ctx.input("X")
    filt = ctx.input("Filter")
    length = ctx.input("Length")
    ctx_len = int(ctx.attr("contextLength", 3))
    ctx_start = int(ctx.attr("contextStart", -(ctx_len // 2)))
    b, t, d = x.shape
    # mask padding positions so context windows don't leak across Length
    if length is not None:
        mask = (jnp.arange(t)[None, :] < length.astype(jnp.int32)[:, None])
        x = jnp.where(mask[..., None], x, 0.0)
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        cols.append(jnp.roll(x, -off, axis=1) * (
            ((jnp.arange(t) + off >= 0) & (jnp.arange(t) + off < t))
            [None, :, None].astype(x.dtype)))
    ctx_mat = jnp.concatenate(cols, axis=-1)            # [B, T, ctx_len*D]
    ctx.set_output("Out", jnp.einsum("btc,cf->btf", ctx_mat, filt))


@register_op("sequence_reshape")
def sequence_reshape_op(ctx: OpContext):
    """reference: operators/sequence_reshape_op.cc — re-chunk the feature
    dim: [B, T, D] → [B, T·D/new_dim, new_dim] (padded convention keeps the
    batch axis; Length scales by D/new_dim)."""
    x = ctx.input("X")
    new_dim = int(ctx.attr("new_dim"))
    b, t, d = x.shape
    ctx.set_output("Out", x.reshape(b, t * d // new_dim, new_dim))
    length = ctx.input("Length")
    if length is not None:
        ctx.set_output("OutLength", (length * d) // new_dim)


@register_op("spectral_norm")
def spectral_norm_op(ctx: OpContext):
    """reference: operators/spectral_norm_op.cc — weight / sigma_max via
    power iteration on persistent U/V vectors."""
    w = ctx.input("Weight")
    u = ctx.input("U").reshape(-1)
    v = ctx.input("V").reshape(-1)
    dim = int(ctx.attr("dim", 0))
    power_iters = int(ctx.attr("power_iters", 1))
    eps = float(ctx.attr("eps", 1e-12))
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
    mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)  # [H, WflatRest]

    def it(_, uv):
        u_, v_ = uv
        v_ = mat.T @ u_
        v_ = v_ / (jnp.linalg.norm(v_) + eps)
        u_ = mat @ v_
        u_ = u_ / (jnp.linalg.norm(u_) + eps)
        return u_, v_

    u, v = jax.lax.fori_loop(0, power_iters, it, (u, v))
    sigma = u @ (mat @ v)
    ctx.set_output("Out", w / sigma)
    ctx.set_output("UOut", u)
    ctx.set_output("VOut", v)


@register_op("conv_shift")
def conv_shift_op(ctx: OpContext):
    """reference: operators/conv_shift_op.cc — circular correlation
    (NTM-style shift): X [B, D], Y [B, M] (M odd) → [B, D]."""
    x = ctx.input("X")
    y = ctx.input("Y")
    b, d = x.shape
    m = y.shape[1]
    half = m // 2
    out = jnp.zeros_like(x)
    for j in range(m):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    ctx.set_output("Out", out)


@register_op("similarity_focus")
def similarity_focus_op(ctx: OpContext):
    """reference: operators/similarity_focus_op.cc — for each selected
    channel, mark the (h, w) argmax-per-row/col pattern with 1."""
    x = ctx.input("X")  # [B, C, H, W]
    axis = int(ctx.attr("axis", 1))
    indexes = [int(i) for i in ctx.attr("indexes")]
    if axis != 1:
        raise NotImplementedError("similarity_focus: only axis=1 (channel)")
    b, c, h, w = x.shape
    out = jnp.zeros_like(x)
    for ci in indexes:
        ch = x[:, ci]                                 # [B, H, W]
        row_max = ch == jnp.max(ch, axis=2, keepdims=True)
        col_max = ch == jnp.max(ch, axis=1, keepdims=True)
        mark = (row_max | col_max).astype(x.dtype)    # [B, H, W]
        out = out + mark[:, None, :, :] * (jnp.arange(c)[None, :, None, None] == ci)
    ctx.set_output("Out", jnp.minimum(out, 1.0))


@register_op("fused_embedding_seq_pool")
def fused_embedding_seq_pool_op(ctx: OpContext):
    """reference: operators/fused_embedding_seq_pool_op.cc — lookup + sum
    pool in one op (XLA fuses it anyway; kept for graph parity).
    Ids [B, L] + Length → [B, D]."""
    w = ctx.input("W")
    ids = ctx.input("Ids").astype(jnp.int32)
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    length = ctx.input("Length")
    emb = w[ids]                                      # [B, L, D]
    if length is not None:
        mask = (jnp.arange(ids.shape[1])[None, :]
                < length.astype(jnp.int32)[:, None])[..., None]
        emb = jnp.where(mask, emb, 0.0)
    ctx.set_output("Out", jnp.sum(emb, axis=1))


@register_op("max_pool3d_with_index")
def max_pool3d_with_index_op(ctx: OpContext):
    """reference: pool_with_index_op.cc 3-D variant."""
    x = ctx.input("X")  # [N, C, D, H, W]
    ksize = list(ctx.attr("ksize", [2, 2, 2]))
    strides = list(ctx.attr("strides", ksize))
    n, c, d, h, w = x.shape
    kd, kh, kw = ksize
    sd, sh, sw = strides
    od, oh, ow = (d - kd) // sd + 1, (h - kh) // sh + 1, (w - kw) // sw + 1
    iz = (jnp.arange(od) * sd)[:, None, None, None, None, None] + \
        jnp.arange(kd)[None, None, None, :, None, None]
    iy = (jnp.arange(oh) * sh)[None, :, None, None, None, None] + \
        jnp.arange(kh)[None, None, None, None, :, None]
    ix = (jnp.arange(ow) * sw)[None, None, :, None, None, None] + \
        jnp.arange(kw)[None, None, None, None, None, :]
    shp = (od, oh, ow, kd, kh, kw)
    iz, iy, ix = (jnp.broadcast_to(a, shp) for a in (iz, iy, ix))
    vals = x[:, :, iz, iy, ix].reshape(n, c, od, oh, ow, -1)
    out = jnp.max(vals, axis=-1)
    arg = jnp.argmax(vals, axis=-1)
    az = arg // (kh * kw)
    ay = (arg // kw) % kh
    ax = arg % kw
    gz = (jnp.arange(od) * sd)[None, None, :, None, None] + az
    gy = (jnp.arange(oh) * sh)[None, None, None, :, None] + ay
    gx = (jnp.arange(ow) * sw)[None, None, None, None, :] + ax
    ctx.set_output("Out", out)
    ctx.set_output("Mask", (gz * h * w + gy * w + gx).astype(jnp.int32))


@register_op("lod_reset")
def lod_reset_op(ctx: OpContext):
    """reference: operators/lod_reset_op.cc — under the padded+Length
    convention this swaps the Length descriptor: data passes through, the
    new per-row lengths come from Y (or the target_lod attr)."""
    x = ctx.input("X")
    ctx.set_output("Out", x)
    y = ctx.input("Y")
    if y is not None:
        ctx.set_output("OutLength", y)
    else:
        tl = ctx.attr("target_lod", [])
        lens = jnp.diff(jnp.asarray(tl, jnp.int32))
        ctx.set_output("OutLength", lens)


@register_op("fill")
def fill_op(ctx: OpContext):
    """reference: operators/fill_op.cc — fill with an explicit value list."""
    from ..core.dtypes import convert_dtype, to_jnp_dtype

    shape = [int(s) for s in ctx.attr("shape")]
    dtype = to_jnp_dtype(convert_dtype(ctx.attr("dtype", "float32")))
    value = ctx.attr("value")
    ctx.set_output("Out", jnp.asarray(value, dtype).reshape(shape))


@register_op("average_accumulates")
def average_accumulates_op(ctx: OpContext):
    """reference: operators/average_accumulates_op.cc — the running sums
    behind the ModelAverage optimizer: three cascaded accumulators with
    window rollover."""
    param = ctx.input("Param")
    sum1 = ctx.input("InSum1")
    sum2 = ctx.input("InSum2")
    sum3 = ctx.input("InSum3")
    num_acc = ctx.input("InNumAccumulates").reshape(()).astype(jnp.int32)
    old_num = ctx.input("InOldNumAccumulates").reshape(()).astype(jnp.int32)
    num_upd = ctx.input("InNumUpdates").reshape(()).astype(jnp.int32)
    avg_window = float(ctx.attr("average_window", 0.0))
    max_avg = int(ctx.attr("max_average_window", 10000))
    min_avg = int(ctx.attr("min_average_window", 10000))

    k_max_acc = 16384  # reference kMaxNumAccumulates (precision spill)
    num_upd = num_upd + 1
    num_acc = num_acc + 1
    sum1 = sum1 + param
    spill = num_upd % k_max_acc == 0
    sum2 = jnp.where(spill, sum2 + sum1, sum2)
    sum1 = jnp.where(spill, jnp.zeros_like(sum1), sum1)
    # window rollover (average_accumulates_op.h:57): current window done →
    # it BECOMES sum3 (discarding the previous sum3), counts shift.
    window = jnp.minimum(
        jnp.asarray(max_avg, jnp.int32),
        (num_upd.astype(jnp.float32) * avg_window).astype(jnp.int32))
    roll = (num_acc >= min_avg) & (num_acc >= window)
    sum3 = jnp.where(roll, sum1 + sum2, sum3)
    sum1 = jnp.where(roll, jnp.zeros_like(sum1), sum1)
    sum2 = jnp.where(roll, jnp.zeros_like(sum2), sum2)
    old_num = jnp.where(roll, num_acc, old_num)
    num_acc = jnp.where(roll, jnp.zeros((), jnp.int32), num_acc)

    ctx.set_output("OutSum1", sum1)
    ctx.set_output("OutSum2", sum2)
    ctx.set_output("OutSum3", sum3)
    ctx.set_output("OutNumAccumulates", num_acc.reshape(1))
    ctx.set_output("OutOldNumAccumulates", old_num.reshape(1))
    ctx.set_output("OutNumUpdates", num_upd.reshape(1))


def _tree_patch_matrices(edges, max_nodes, max_depth):
    """Host-side tree2col: per-sample [3, Nmax, Nmax] coefficient matrices
    (eta_t, eta_l, eta_r per patch membership), reference:
    operators/math/tree2col.cc construct_patch. Runs under pure_callback —
    tree traversal is data-dependent preprocessing; the conv FLOPs stay on
    device."""
    edges = np.asarray(edges)
    out = np.zeros((edges.shape[0], 3, max_nodes, max_nodes), np.float32)
    for b in range(edges.shape[0]):
        # DIRECTED parent→child edges; a row with any zero endpoint
        # terminates the list (reference construct_tree: `else break`)
        adj = {}
        n_nodes = 1
        for u, v in edges[b]:
            u, v = int(u), int(v)
            if u == 0 or v == 0:
                break
            adj.setdefault(u, []).append(v)
            n_nodes += 1
        for root in range(1, n_nodes + 1):
            # iterative DFS matching the reference's stack traversal
            visited = {root}
            stack = [(root, 1, 1, 0)]  # (node, index, pclen, depth)
            patch = [(root, 1, 1, 0)]
            while stack:
                node, idx, pclen, depth = stack[-1]
                progressed = False
                kids = adj.get(node, [])
                for i, v in enumerate(kids):
                    if v not in visited and depth + 1 < max_depth:
                        visited.add(v)
                        stack.append((v, i, len(kids), depth + 1))
                        patch.append((v, i + 1, len(kids), depth + 1))
                        progressed = True
                if not progressed:
                    stack.pop()
            for node, idx, pclen, depth in patch:
                eta_t = (max_depth - depth) / max_depth
                tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
                eta_l = (1.0 - eta_t) * tmp
                eta_r = (1.0 - eta_t) * (1.0 - eta_l)
                # node ids are 1-based; direction order (l, r, t) matches
                # the Filter's dim-1 layout (tree2col.cc: i*3 -> l, +1 -> r,
                # +2 -> t)
                out[b, 0, root - 1, node - 1] += eta_l
                out[b, 1, root - 1, node - 1] += eta_r
                out[b, 2, root - 1, node - 1] += eta_t
    return out


@register_op("tree_conv")
def tree_conv_op(ctx: OpContext):
    """Tree-based convolution (reference: tree_conv_op.cc, TBCNN).

    NodesVector [B, Nmax, F], EdgeSet [B, E, 2] int32 (1-based node ids,
    (0,0) rows pad), Filter [F, 3, output_size, num_filters] →
    Out [B, Nmax, output_size, num_filters]. The traversal runs on host
    (pure_callback, constant wrt gradients — matching the reference where
    EdgeSet carries no grad); the batched coefficient-matrix × feature
    matmuls run on device.
    """
    nodes = ctx.input("NodesVector")
    edges = ctx.input("EdgeSet").astype(jnp.int32)
    filt = ctx.input("Filter")
    max_depth = int(ctx.attr("max_depth", 2))
    b, nmax, f = nodes.shape
    coef_shape = jax.ShapeDtypeStruct((b, 3, nmax, nmax), np.dtype("float32"))
    coefs = jax.pure_callback(
        lambda e: _tree_patch_matrices(e, nmax, max_depth), coef_shape, edges)
    coefs = jax.lax.stop_gradient(coefs)
    # patch features per direction: [B, 3, Nmax, F]
    col = jnp.einsum("bdnm,bmf->bdnf", coefs, nodes.astype(jnp.float32))
    out = jnp.einsum("bdnf,fdok->bnok", col, filt.astype(jnp.float32))
    ctx.set_output("Out", out.astype(nodes.dtype))


@register_op("hash")
def hash_op(ctx: OpContext):
    """reference: operators/hash_op.cc — per-row integer hash of the id
    vector into [0, mod_by), one value per hash seed. The reference uses
    xxhash over the raw bytes; the TPU-native impl uses a murmur3-style
    uint32 finalizer folded over the row (same contract: deterministic,
    well-mixed, mod_by-bounded). X [N, D] int → Out [N, num_hash, 1]."""
    x = ctx.input("X").astype(jnp.uint32)
    num_hash = int(ctx.attr("num_hash", 1))
    mod_by = int(ctx.attr("mod_by", 100000))

    def _mix(h):
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    outs = []
    for seed in range(num_hash):
        h = jnp.full(x.shape[:1], seed + 1, jnp.uint32)
        for j in range(x.shape[1]):  # static fold over the id row
            h = _mix(h ^ _mix(x[:, j] + jnp.uint32(0x9E3779B9)))
        # int32 is exact here (values < mod_by); requesting int64 under
        # x64-disabled JAX would silently truncate with a warning
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int32))
    ctx.set_output("Out", jnp.stack(outs, axis=1)[:, :, None])
