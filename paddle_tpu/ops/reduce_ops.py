"""Reduce ops (reference: operators/reduce_ops/ — reduce_sum/mean/max/min/prod)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import OpContext, register_op


def _reduce(ctx: OpContext, fn):
    x = ctx.input("X")
    dims = ctx.attr("dim", [0])
    keep_dim = ctx.attr("keep_dim", False)
    if ctx.attr("reduce_all", False):
        ctx.set_output("Out", fn(x))
        return
    axes = tuple(d % x.ndim for d in dims)
    ctx.set_output("Out", fn(x, axis=axes, keepdims=keep_dim))


@register_op("reduce_sum")
def reduce_sum_op(ctx):
    _reduce(ctx, jnp.sum)


@register_op("reduce_mean")
def reduce_mean_op(ctx):
    _reduce(ctx, jnp.mean)


@register_op("reduce_max")
def reduce_max_op(ctx):
    _reduce(ctx, jnp.max)


@register_op("reduce_min")
def reduce_min_op(ctx):
    _reduce(ctx, jnp.min)


@register_op("reduce_prod")
def reduce_prod_op(ctx):
    _reduce(ctx, jnp.prod)


@register_op("reduce_all")
def reduce_all_op(ctx):
    _reduce(ctx, jnp.all)


@register_op("reduce_any")
def reduce_any_op(ctx):
    _reduce(ctx, jnp.any)


@register_op("logsumexp")
def logsumexp_op(ctx):
    from jax.scipy.special import logsumexp

    _reduce(ctx, logsumexp)
