"""Sequence/classification metric ops (reference: operators/chunk_eval_op.h,
edit_distance_op.cc, precision_recall_op.cc).

TPU-first: the reference walks sequences with host loops; here chunk
detection is a pair of vectorized begin/end boundary predicates (two chunks
are identical iff same begin ∧ same end ∧ same type — so correctness counts
reduce to mask conjunctions), and edit distance is one ``lax.scan`` DP over
the padded hypothesis axis, vmapped over the batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op

_SCHEMES = {
    # scheme: (num_tag_types, begin, inside, end, single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_bounds(labels, lens, num_chunk_types, scheme):
    """labels [B, T] → (is_begin, end_pos_for_begin, type) masks.

    Implements the reference's ChunkBegin/ChunkEnd predicates
    (chunk_eval_op.h:83,96) positionally over the padded batch.
    """
    ntag, t_begin, t_inside, t_end, t_single = _SCHEMES[scheme]
    other = num_chunk_types
    b, t = labels.shape
    tag = labels % ntag
    typ = labels // ntag
    valid = jnp.arange(t)[None, :] < lens[:, None]
    typ = jnp.where(valid, typ, other)  # padding behaves like Outside

    prev_tag = jnp.pad(tag, ((0, 0), (1, 0)))[:, :t]
    prev_typ = jnp.pad(typ, ((0, 0), (1, 0)), constant_values=other)[:, :t]
    next_tag = jnp.pad(tag, ((0, 0), (0, 1)))[:, 1:]
    next_typ = jnp.pad(typ, ((0, 0), (0, 1)), constant_values=other)[:, 1:]

    def begin(ptag, ptyp, ctag, ctyp):
        r = jnp.where(ptyp == other, ctyp != other,
            jnp.where(ctyp == other, False,
            jnp.where(ctyp != ptyp, True,
            jnp.where(ctag == t_begin, True,
            jnp.where(ctag == t_inside, (ptag == t_end) | (ptag == t_single),
            jnp.where(ctag == t_end, (ptag == t_end) | (ptag == t_single),
            jnp.where(ctag == t_single, True, False)))))))
        return r & (ctyp != other)

    def end(ctag, ctyp, ntag_, ntyp):
        # chunk ends AT position i iff ChunkEnd(prev=i, cur=i+1)
        return jnp.where(ctyp == other, False,
               jnp.where(ntyp == other, True,
               jnp.where(ntyp != ctyp, True,
               jnp.where(ctag == t_begin, (ntag_ == t_begin) | (ntag_ == t_single),
               jnp.where(ctag == t_inside, (ntag_ == t_begin) | (ntag_ == t_single),
               jnp.where(ctag == t_end, True,
               jnp.where(ctag == t_single, True, False)))))))

    is_begin = begin(prev_tag, prev_typ, tag, typ)
    is_end = end(tag, typ, next_tag, next_typ)

    # end position of the chunk open at/after position i: reverse cummin of
    # end indices
    idx = jnp.arange(t)[None, :]
    end_idx = jnp.where(is_end, idx, t + 1)
    end_pos = jax.lax.associative_scan(jnp.minimum, end_idx[:, ::-1], axis=1)[:, ::-1]
    return is_begin, end_pos, typ


@register_op("chunk_eval")
def chunk_eval_op(ctx: OpContext):
    """Inference [B, T] + Label [B, T] (+ Length [B]) → Precision, Recall,
    F1-Score, NumInferChunks, NumLabelChunks, NumCorrectChunks."""
    inf = ctx.input("Inference").astype(jnp.int32)
    lab = ctx.input("Label").astype(jnp.int32)
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    lens = ctx.input("Length")
    nct = int(ctx.attr("num_chunk_types"))
    scheme = ctx.attr("chunk_scheme", "IOB")
    excluded = list(ctx.attr("excluded_chunk_types", []) or [])
    b, t = inf.shape
    if lens is None:
        lens = jnp.full((b,), t, jnp.int32)
    lens = lens.astype(jnp.int32)

    ib_i, ep_i, ty_i = _chunk_bounds(inf, lens, nct, scheme)
    ib_l, ep_l, ty_l = _chunk_bounds(lab, lens, nct, scheme)

    def not_excluded(ty):
        ok = jnp.ones_like(ty, bool)
        for e in excluded:
            ok &= ty != e
        return ok

    n_inf = jnp.sum((ib_i & not_excluded(ty_i)).astype(jnp.int32))
    n_lab = jnp.sum((ib_l & not_excluded(ty_l)).astype(jnp.int32))
    correct = (ib_i & ib_l & (ty_i == ty_l) & (ep_i == ep_l)
               & not_excluded(ty_i))
    n_cor = jnp.sum(correct.astype(jnp.int32))

    p = jnp.where(n_inf > 0, n_cor / jnp.maximum(n_inf, 1), 0.0).astype(jnp.float32)
    r = jnp.where(n_lab > 0, n_cor / jnp.maximum(n_lab, 1), 0.0).astype(jnp.float32)
    f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    ctx.set_output("Precision", p.reshape(1))
    ctx.set_output("Recall", r.reshape(1))
    ctx.set_output("F1-Score", f1.reshape(1))
    ctx.set_output("NumInferChunks", n_inf.reshape(1))
    ctx.set_output("NumLabelChunks", n_lab.reshape(1))
    ctx.set_output("NumCorrectChunks", n_cor.reshape(1))


@register_op("edit_distance")
def edit_distance_op(ctx: OpContext):
    """Levenshtein distance (reference: edit_distance_op.cc). Hyps [B, Lh] +
    HypsLength, Refs [B, Lr] + RefsLength → Out [B, 1], SequenceNum [1]."""
    hyps = ctx.input("Hyps").astype(jnp.int32)
    refs = ctx.input("Refs").astype(jnp.int32)
    hl = ctx.input("HypsLength")
    rl = ctx.input("RefsLength")
    b, lh = hyps.shape
    lr = refs.shape[1]
    if hl is None:
        hl = jnp.full((b,), lh, jnp.int32)
    if rl is None:
        rl = jnp.full((b,), lr, jnp.int32)
    hl = hl.astype(jnp.int32)
    rl = rl.astype(jnp.int32)

    def one(h, r, hn, rn):
        row0 = jnp.arange(lr + 1, dtype=jnp.float32)

        def step(row, ht):
            ins = row[:-1] + (ht != r).astype(jnp.float32)  # substitution cost
            base = jnp.minimum(row[1:] + 1.0, ins)

            def inner(carry, b_):
                v = jnp.minimum(b_, carry + 1.0)  # new[j+1] = min(base[j], new[j]+1)
                return v, v

            _, rest = jax.lax.scan(inner, row[0] + 1.0, base)
            new = jnp.concatenate([jnp.array([row[0] + 1.0]), rest])
            return new, new

        _, rows = jax.lax.scan(step, row0, h)
        all_rows = jnp.concatenate([row0[None], rows], axis=0)  # [Lh+1, Lr+1]
        return all_rows[hn, rn]

    dist = jax.vmap(one)(hyps, refs, hl, rl)
    if ctx.attr("normalized", False):
        dist = dist / jnp.maximum(rl.astype(jnp.float32), 1.0)
    ctx.set_output("Out", dist[:, None])
    ctx.set_output("SequenceNum", jnp.asarray([b], jnp.int32))


@register_op("precision_recall")
def precision_recall_op(ctx: OpContext):
    """Multi-class precision/recall/F1 (reference: precision_recall_op.cc).

    Indices [B, 1] predicted class, Labels [B, 1], optional Weights [B, 1],
    optional StatesInfo [C, 4] accumulator (TP, FP, TN, FN per class) →
    BatchMetrics [6] (macro-P/R/F1, micro-P/R/F1), AccumMetrics [6],
    AccumStatesInfo [C, 4]."""
    idx = ctx.input("Indices").reshape(-1).astype(jnp.int32)
    lab = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    w = ctx.input("Weights")
    states = ctx.input("StatesInfo")
    c = int(ctx.attr("class_number"))
    b = idx.shape[0]
    w = jnp.ones((b,), jnp.float32) if w is None else w.reshape(-1).astype(jnp.float32)

    onehot_p = jax.nn.one_hot(idx, c, dtype=jnp.float32) * w[:, None]
    onehot_l = jax.nn.one_hot(lab, c, dtype=jnp.float32) * w[:, None]
    tp = jnp.sum(onehot_p * (idx == lab)[:, None].astype(jnp.float32), axis=0)
    fp = jnp.sum(onehot_p, axis=0) - tp
    fn = jnp.sum(onehot_l, axis=0) - tp
    tn = jnp.sum(w) - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)  # [C, 4]

    def metrics(st):
        tp_, fp_, _tn, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        stp, sfp, sfn = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mp = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1e-12), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    accum = batch_states if states is None else states.astype(jnp.float32) + batch_states
    ctx.set_output("BatchMetrics", metrics(batch_states))
    ctx.set_output("AccumMetrics", metrics(accum))
    ctx.set_output("AccumStatesInfo", accum)


@register_op("positive_negative_pair")
def positive_negative_pair_op(ctx: OpContext):
    """Ranking-pair metric (reference: operators/positive_negative_pair_op.h).

    For every within-query document pair with different labels: a pair is
    positive when score order agrees with label order, negative when it
    disagrees, neutral on a score tie (the reference's own python oracle,
    test_positive_negative_pair_op.py:44, counts ties as neutral ONLY; its
    C++ kernel also bumps `negative` on a tie — we follow the oracle).

    TPU-first: the reference buckets rows into per-query hash-map lists and
    walks pair combinations on the host; here the whole thing is one dense
    [N, N] pairwise mask reduction (same-query ∧ label-differs ∧ upper
    triangle) — O(N²) elementwise on the VPU, no host loops, jit-safe.
    """
    score = ctx.input("Score")
    label = ctx.input("Label").reshape(-1).astype(jnp.float32)
    query = ctx.input("QueryID").reshape(-1)
    weight = ctx.input("Weight")
    col = int(ctx.attr("column", -1))
    s = score[:, col].astype(jnp.float32)
    n = s.shape[0]
    w = (jnp.ones((n,), jnp.float32) if weight is None
         else weight.reshape(-1).astype(jnp.float32))

    upper = jnp.triu(jnp.ones((n, n), bool), k=1)
    valid = upper & (query[:, None] == query[None, :]) \
        & (label[:, None] != label[None, :])
    pair_w = jnp.where(valid, (w[:, None] + w[None, :]) * 0.5, 0.0)
    prod = (s[:, None] - s[None, :]) * (label[:, None] - label[None, :])
    tie = s[:, None] == s[None, :]
    pos = jnp.sum(jnp.where(~tie & (prod > 0), pair_w, 0.0))
    neg = jnp.sum(jnp.where(~tie & (prod <= 0), pair_w, 0.0))
    neu = jnp.sum(jnp.where(tie, pair_w, 0.0))

    for nm, base in (("PositivePair", pos), ("NegativePair", neg),
                     ("NeutralPair", neu)):
        acc = ctx.input("Accumulate" + nm)
        if acc is not None:
            base = base + acc.reshape(()).astype(jnp.float32)
        ctx.set_output(nm, base.reshape(1))
