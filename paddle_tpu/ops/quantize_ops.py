"""Fake-quantization ops for QAT (reference: operators/fake_quantize_op.cc —
fake_quantize_abs_max, fake_quantize_range_abs_max,
fake_channel_wise_quantize_abs_max, fake_dequantize_max_abs,
fake_channel_wise_dequantize_max_abs; plus the later
moving_average_abs_max variant).

Quantized values are kept in float storage (int grid, float dtype) exactly
like the reference's simulated-quantization path. Gradients use the
straight-through estimator: the reference registers an identity grad functor
(FakeQuantGradFunctor), reproduced here with jax.custom_vjp so AD through
the traced program matches.

State (running scales, window buffers) flows through the in-place output
convention the executor already uses for BN running stats: the op writes
OutScale/OutScales to the same persistable var names, and the jitted step
returns them as updated state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op


@jax.custom_vjp
def _ste(x, q):
    """Forward: q(x); backward: identity into x (reference FakeQuantGradFunctor)."""
    return q


def _ste_fwd(x, q):
    return q, None


def _ste_bwd(_, g):
    return g, None


_ste.defvjp(_ste_fwd, _ste_bwd)


def _qrange(bits: int) -> float:
    return float((1 << (bits - 1)) - 1)


def quantize_abs_max(x, bits: int):
    """→ (quantized int-grid values in float, scale)."""
    r = _qrange(bits)
    scale = jnp.max(jnp.abs(x))
    # the scale's own gradient is defined to be zero (reference
    # FakeQuantGradFunctor is pure identity) — stop_gradient it everywhere
    safe = jax.lax.stop_gradient(jnp.maximum(scale, 1e-8))
    q = jnp.round(jnp.clip(x / safe, -1.0, 1.0) * r)
    return _ste(x * (r / safe), q), scale


@register_op("fake_quantize_abs_max")
def fake_quantize_abs_max_op(ctx: OpContext):
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    out, scale = quantize_abs_max(x, bits)
    ctx.set_output("Out", out)
    ctx.set_output("OutScale", scale.reshape(1))


@register_op("fake_channel_wise_quantize_abs_max")
def fake_channel_wise_quantize_abs_max_op(ctx: OpContext):
    """Per-output-channel (dim 0) scales — conv/mul weight quantization."""
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    r = _qrange(bits)
    axes = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=axes)
    safe = jax.lax.stop_gradient(
        jnp.maximum(scale, 1e-8)).reshape((-1,) + (1,) * (x.ndim - 1))
    q = jnp.round(jnp.clip(x / safe, -1.0, 1.0) * r)
    ctx.set_output("Out", _ste(x * (r / safe), q))
    ctx.set_output("OutScale", scale)


@register_op("fake_quantize_range_abs_max")
def fake_quantize_range_abs_max_op(ctx: OpContext):
    """Windowed max scale (reference FakeQuantizeRangeAbsMaxOp): a
    [window_size] buffer of per-step abs-maxes; OutScale = max(window).
    Test mode uses the frozen InScale."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale")          # [1] persistable
    it = ctx.input("Iter")                   # [1] int64 persistable
    window = ctx.input("OutScales")          # [window_size] persistable
    bits = int(ctx.attr("bit_length", 8))
    window_size = int(ctx.attr("window_size", 10000))
    r = _qrange(bits)

    if ctx.is_test:
        scale = in_scale.reshape(())
    else:
        cur = jnp.max(jnp.abs(x))
        pos = (it.reshape(()).astype(jnp.int32)) % window_size
        window = window.at[pos].set(cur)
        scale = jnp.max(window)
        ctx.set_output("OutScales", window)
        ctx.set_output("OutScale", scale.reshape(1))
    safe = jax.lax.stop_gradient(jnp.maximum(scale, 1e-8))
    q = jnp.round(jnp.clip(x / safe, -1.0, 1.0) * r)
    ctx.set_output("Out", _ste(x * (r / safe), q))


@register_op("fake_quantize_moving_average_abs_max")
def fake_quantize_moving_average_abs_max_op(ctx: OpContext):
    """EMA scale: state = accum/state counters (reference
    FakeQuantizeMovingAverageAbsMaxOp)."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale")
    in_accum = ctx.input("InAccum")
    in_state = ctx.input("InState")
    bits = int(ctx.attr("bit_length", 8))
    rho = float(ctx.attr("moving_rate", 0.9))
    r = _qrange(bits)
    if ctx.is_test:
        scale = in_scale.reshape(())
    else:
        cur = jnp.max(jnp.abs(x))
        accum = (in_accum.reshape(()) if in_accum is not None else 0.0) * rho + cur
        state = (in_state.reshape(()) if in_state is not None else 0.0) * rho + 1.0
        scale = accum / state
        ctx.set_output("OutAccum", accum.reshape(1))
        ctx.set_output("OutState", state.reshape(1))
        ctx.set_output("OutScale", scale.reshape(1))
    safe = jax.lax.stop_gradient(jnp.maximum(scale, 1e-8))
    q = jnp.round(jnp.clip(x / safe, -1.0, 1.0) * r)
    ctx.set_output("Out", _ste(x * (r / safe), q))


@register_op("fake_dequantize_max_abs")
def fake_dequantize_max_abs_op(ctx: OpContext):
    """Out = X * Scale / max_range (reference FakeDequantizeMaxAbsOp)."""
    x = ctx.input("X")
    scale = ctx.input("Scale").reshape(())
    max_range = float(ctx.attr("max_range"))
    ctx.set_output("Out", x * (scale / max_range))


@register_op("fake_channel_wise_dequantize_max_abs")
def fake_channel_wise_dequantize_max_abs_op(ctx: OpContext):
    x = ctx.input("X")
    scales = ctx.inputs("Scales")
    bits = ctx.attr("quant_bits", [8])
    out = x
    for s, b in zip(scales, bits):
        if s.ndim >= 1 and s.shape[0] == x.shape[0] and s.size > 1:
            shp = (-1,) + (1,) * (x.ndim - 1)
            out = out * (s.reshape(shp) / _qrange(int(b)))
        else:
            out = out * (s.reshape(()) / _qrange(int(b)))
    ctx.set_output("Out", out)


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def fake_qdq_moving_average_op(ctx: OpContext):
    """Fused quant+dequant (activation QAT in later reference versions)."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale")
    bits = int(ctx.attr("bit_length", 8))
    rho = float(ctx.attr("moving_rate", 0.9))
    r = _qrange(bits)
    in_accum, in_state = ctx.input("InAccum"), ctx.input("InState")
    if ctx.is_test:
        scale = in_scale.reshape(())
    else:
        cur = jnp.max(jnp.abs(x))
        accum = (in_accum.reshape(()) if in_accum is not None else 0.0) * rho + cur
        state = (in_state.reshape(()) if in_state is not None else 0.0) * rho + 1.0
        scale = accum / state
        ctx.set_output("OutAccum", accum.reshape(1))
        ctx.set_output("OutState", state.reshape(1))
        ctx.set_output("OutScale", scale.reshape(1))
    safe = jax.lax.stop_gradient(jnp.maximum(scale, 1e-8))
    q = jnp.round(jnp.clip(x / safe, -1.0, 1.0) * r) * (safe / r)
    ctx.set_output("Out", _ste(x, q))
