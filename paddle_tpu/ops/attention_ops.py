"""Fused attention op.

The reference has NO fused attention — transformer models compose it from
primitive ops in Python (reference: tests/unittests/dist_transformer.py,
SURVEY.md §5.7). On TPU the fused kernel is the single most important op for
transformer throughput: this op lowers to the project-vendored Pallas TPU
flash-attention kernel (ops/pallas_kernels/flash_attention.py) when running
on TPU hardware, with an XLA-composed fallback elsewhere (CPU tests, odd
shapes, attention dropout). Segment-ids support is the XLA-native replacement for
Fluid's LoD variable-length batching.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.registry import OpContext, register_op


@functools.lru_cache(maxsize=1)
def _flash_fn():
    try:
        # project-owned vendored kernels (ops/pallas_kernels/flash_attention
        # .py) — a JAX upgrade can no longer change the kernels under us
        from .pallas_kernels.flash_attention import (
            SegmentIds,
            flash_attention,
        )

        return flash_attention, SegmentIds
    except Exception:  # pragma: no cover - pallas unavailable
        return None, None


def _on_tpu() -> bool:
    return jax.default_backend() not in ("cpu", "gpu")


def neg_inf(dtype) -> jnp.ndarray:
    """THE masking constant for every attention implementation in this
    package (composed sdpa, decode_attention, and the paged-attention
    Pallas kernel's reference check share it, so bf16/f32 masking semantics
    cannot drift between them). Scaled to the dtype — ``-0.7 * finfo.max``,
    the same convention as the vendored flash kernel's DEFAULT_MASK_VALUE —
    so it stays finite in bf16/f16 (a raw ``-1e30`` overflows f16 to -inf
    and then ``-inf - max`` NaNs the softmax) while ``exp()`` of it still
    underflows to exactly 0.0: masked positions contribute exactly nothing.
    """
    dt = jnp.dtype(dtype)
    return jnp.asarray(neg_inf_value(dt), dt)


def neg_inf_value(dtype) -> float:
    """:func:`neg_inf` as a host-side Python float — for call sites that
    bake the constant into a kernel as a static parameter (the paged-
    attention Pallas kernel), where a traced array would not do."""
    return -0.7 * float(jnp.finfo(jnp.dtype(dtype)).max)


def paged_kernel_mode():
    """Resolve ``FLAGS_paged_attention_kernel`` for this trace: None = the
    XLA gather + :func:`decode_attention` path, "compiled"/"interpret" =
    the ragged paged-attention Pallas kernel
    (pallas_kernels/paged_attention.py). "auto" compiles on TPU and keeps
    the gather path elsewhere — the interpreter is a correctness tool, not
    a fast CPU path (mirrors optimizer_ops._sparse_kernel_mode)."""
    from ..flags import flags

    mode = str(flags.paged_attention_kernel).lower()
    if mode in ("0", "off", "false", "no"):
        return None
    if mode == "interpret":
        return "interpret"
    on_tpu = jax.default_backend() == "tpu"
    if mode in ("1", "on", "true", "yes"):
        return "compiled" if on_tpu else "interpret"
    return "compiled" if on_tpu else None  # auto


def _pick_block(s: int):
    """Largest v5e-tuned tile (512 optimal, r4 sweep) that divides ``s``.
    Single source of truth for both sdpa and ring-attention block compute."""
    for b in (512, 256, 128):
        if s % b == 0:
            return b
    raise ValueError(
        "flash-attention sequence length %d is not a multiple of 128 "
        "(the caller's gate should have rejected it)" % s)


def _divisor_block(want: int, s: int, fallback: int) -> int:
    """Largest power-of-two tile <= ``want`` that divides ``s`` (>=128);
    ``fallback`` when none does. Tuned entries are bucketed coarsely, so a
    512 tuned for s=8192 must legally serve s=384 by clamping to 128."""
    b = 1 << (max(int(want), 128).bit_length() - 1)
    while b >= 128:
        if s % b == 0:
            return b
        b //= 2
    return fallback


def _block_sizes_for(bq: int, bk: int):
    """The (bq, bk) -> full BlockSizes mapping (fwd + both backward
    kernels share the same tiles) — ONE definition, used by the trace-time
    lookup below AND the autotuner's flash candidate builds, so tuned
    entries are always measured under the exact block assignment they will
    later serve."""
    from .pallas_kernels.flash_attention import BlockSizes

    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )


def _tuned_block_sizes(sq: int, sk: int):
    """Tile sizes for the Pallas flash kernel: tuned table -> shipped
    seeds -> hardcoded fallback (paddle_tpu.tune).

    The hardcoded fallback encodes the round-4 hand sweep on the real v5e
    chip (benchmarks/sweep_flash_blocks.py): 512x512 optimal — 2.65 ms vs
    17.2 ms all-128 default vs 12.8 ms composed at b1 h8 s8192 d64 causal
    bf16 fwd+bwd, a 4.8x win; larger tiles amortize grid/DMA overhead and
    keep the MXU fed, beyond 512 the VMEM working set thrashes. The same
    numbers now also live in ``tune/shipped.json`` keyed tpu-v5e, and
    ``tools/autotune.py`` re-derives them per (shape-bucket, device_kind)
    by measurement — so other device kinds get their own optimum instead
    of inheriting v5e's. Blocks must divide the sequence lengths, so
    tuned/shorter shapes clamp to the largest working divisor; a corrupt
    or missing table silently yields the fallback (lookup never raises).
    """
    bq, bk = _pick_block(sq), _pick_block(sk)
    try:
        from .. import tune

        cfg, _src = tune.lookup("flash_attention", tune.bucket_seq(sq, sk))
        if cfg:
            bq = _divisor_block(int(cfg.get("block_q", bq)), sq, bq)
            bk = _divisor_block(int(cfg.get("block_k", bk)), sk, bk)
    except Exception:  # table layer must never take down a training trace
        pass
    return _block_sizes_for(bq, bk)


def _flash_ok(q, k, causal) -> bool:
    """Gate for the Pallas kernel: blocking constraints (seq multiples of
    128) AND a measured perf crossover. With the v5e-tuned BlockSizes (see
    _tuned_block_sizes) the round-4 sweep (benchmarks/sweep_flash_crossover.py,
    b* h8 d64 causal bf16 fwd+bwd, loop-difference timing) measured flash
    speedup over composed: S=1024 0.80x, S=2048 1.61x, S=4096 3.46x,
    S=8192 4.15x, S=16384 3.25x. The crossover is ~S=2048, which is the
    FLAGS_flash_attention_min_seq default; below it the composed path's
    single fused HLO beats the kernel's fixed grid overhead, above it the
    O(S) memory AND the tiling win compound. (The composed path OOMs around
    S~24k single-chip, so flash is also the only viable path there.)"""
    flash, _ = _flash_fn()
    if flash is None or not _on_tpu():
        return False
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if causal and sq != sk:
        # the kernel's causal masking assumes square q/k lengths
        return False
    from ..flags import get_flag

    if max(sq, sk) < int(get_flag("flash_attention_min_seq")):
        return False
    return sq % 128 == 0 and sk % 128 == 0 and q.dtype in (jnp.float32, jnp.bfloat16)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_dropout(q, k, v, seed, causal, sm_scale, rate):
    """Flash attention WITH in-kernel attention-probs dropout (r5).

    The vendored kernels regenerate the keep-mask from a counter-based hash
    of absolute (b, h, q, k) coordinates (_dropout_keep_tile), so forward
    and both backward kernels agree without materializing the [B,H,S,S]
    mask — the capability the stock kernels lack and the reason sdpa
    previously fell back to composed O(S^2) attention whenever attention
    dropout was on."""
    out, _ = _flash_dropout_fwd(q, k, v, seed, causal, sm_scale, rate)
    return out


def _flash_dropout_fwd(q, k, v, seed, causal, sm_scale, rate):
    from .pallas_kernels import flash_attention as fa

    bq = _pick_block(q.shape[2])
    bk = _pick_block(k.shape[2])
    o, l, m = fa._flash_attention_impl(
        q, k, v, None, None, True, causal, sm_scale, 1, bq, bk, bk, False,
        dropout_rate=rate, dropout_seed=seed)
    return o, (q, k, v, o, l, m, seed)


def _flash_dropout_bwd(causal, sm_scale, rate, res, do):
    import numpy as np

    from .pallas_kernels import flash_attention as fa

    q, k, v, o, l, m, seed = res
    bq = _pick_block(q.shape[2])
    bk = _pick_block(k.shape[2])
    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    do = do.astype(q.dtype)
    dk, dv = fa._flash_attention_bwd_dkv(
        q, k, v, None, None, l, m, do, di,
        block_q_major=bq, block_q=bq, block_k_major=bk, block_k=bk,
        sm_scale=sm_scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False,
        dropout_rate=rate, dropout_seed=seed)
    dq, _ = fa._flash_attention_bwd_dq(
        q, k, v, None, None, l, m, do, di,
        block_q_major=bq, block_k_major=bk, block_k=bk,
        sm_scale=sm_scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False,
        dropout_rate=rate, dropout_seed=seed)
    seed_ct = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, seed_ct


_flash_dropout.defvjp(_flash_dropout_fwd, _flash_dropout_bwd)


def sdpa(q, k, v, bias=None, segment_ids_q=None, segment_ids_kv=None,
         causal=False, sm_scale=1.0, dropout_rate=0.0, dropout_rng=None):
    """Scaled dot-product attention over [B, H, S, D] tensors."""
    use_flash = dropout_rate == 0.0 and _flash_ok(q, k, causal)
    if (dropout_rate > 0.0 and dropout_rng is not None and bias is None
            and segment_ids_q is None and segment_ids_kv is None
            and _flash_ok(q, k, causal)):
        # in-kernel dropout path: same gate as flash, tight scope (no
        # bias/segments); seed derives from the op's per-step key
        seed = jax.lax.bitcast_convert_type(
            jax.random.bits(dropout_rng, (1,), jnp.uint32), jnp.int32)
        try:
            return _flash_dropout(q, k, v, seed, causal, float(sm_scale),
                                  float(dropout_rate))
        except Exception as e:
            # honor the same never-hide contract as the no-dropout path:
            # falling back means an ~S^2 memory/perf cliff (note the try
            # wraps the forward TRACE; the custom-vjp backward compiles
            # from the same kernels, so a trace-time pass here covers it)
            from ..flags import get_flag

            if get_flag("strict_fused_attention"):
                raise RuntimeError(
                    "Pallas flash-with-dropout failed for shapes q=%s k=%s "
                    "(causal=%s): %s" % (q.shape, k.shape, causal, e)) from e
            import warnings

            warnings.warn(
                "flash-with-dropout failed (%s: %s); composed fallback. Set "
                "FLAGS_strict_fused_attention=1 to make this an error."
                % (type(e).__name__, e), RuntimeWarning, stacklevel=2)
    if use_flash:
        flash, SegmentIds = _flash_fn()
        seg = None
        if segment_ids_q is not None:
            seg = SegmentIds(q=segment_ids_q, kv=segment_ids_kv)
        try:
            bs = _tuned_block_sizes(q.shape[2], k.shape[2])
            return flash(q, k, v, ab=bias, segment_ids=seg, causal=causal,
                         sm_scale=sm_scale, block_sizes=bs)
        except Exception as e:
            # A failed flash call means a ~S² perf regression — never hide it.
            from ..flags import get_flag

            if get_flag("strict_fused_attention"):
                raise RuntimeError(
                    "Pallas flash-attention failed for shapes q=%s k=%s "
                    "(causal=%s): %s" % (q.shape, k.shape, causal, e)) from e
            import warnings

            warnings.warn(
                "Pallas flash-attention failed (%s: %s); falling back to the "
                "composed O(S^2) attention. Set FLAGS_strict_fused_attention=1 "
                "to make this an error." % (type(e).__name__, e),
                RuntimeWarning, stacklevel=2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if bias is not None:
        scores = scores + bias
    if segment_ids_q is not None:
        mask = segment_ids_q[:, None, :, None] == segment_ids_kv[:, None, None, :]
        scores = jnp.where(mask, scores, neg_inf(scores.dtype))
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(cm, scores, neg_inf(scores.dtype))
    # dtype-preserving softmax by default: every f32-accumulation variant
    # measured COSTS HBM on the Transformer bench (diag_overhead.py, r4) —
    # forcing bf16-probs residuals via custom_vjp +1.9 GB/step, f32-cast
    # softmax +5 GB (XLA saves the f32 output for the backward) — while
    # XLA's own residual choice beats both. FLAGS_attention_softmax_f32
    # buys the f32 softmax at that cost for accuracy-sensitive runs;
    # per-op agreement vs f32 is ~1e-2 either way (ADVICE r3).
    from ..flags import get_flag

    if get_flag("attention_softmax_f32"):
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1) \
            .astype(scores.dtype)
    else:
        probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        # where-on-pred keeps the saved residual at 1 byte/element (see
        # tensor_ops.dropout_op)
        probs = jnp.where(
            keep, probs * jnp.asarray(1.0 / (1.0 - dropout_rate), probs.dtype),
            jnp.zeros((), probs.dtype))
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def decode_attention(q, ctx_k, ctx_v, ctx_len, sm_scale=1.0):
    """Single-position attention for autoregressive decode over a gathered
    KV context (paddle_tpu.serving).

    ``q`` [B,H,D] is the current position's query per batch slot; ``ctx_k``/
    ``ctx_v`` [B,L,H,D] is the slot's cache context — a paged gather
    (serving.kv_cache.PagedKVCache.context) or a contiguous cache slice feed
    the SAME math here, which is what makes the two layouts bit-comparable.
    ``ctx_len`` [B] counts the valid leading positions (prompt + generated,
    INCLUDING the current token, whose k/v the caller wrote before calling).
    Invalid positions are masked with :func:`neg_inf` (exp underflows to
    exactly 0.0), so cache garbage beyond ``ctx_len`` (stale rows from a
    retired request, unreserved pages) contributes exactly nothing —
    independent of layout. Returns [B,H,D].

    This is the XLA fallback path of the serving stack's ragged paged
    attention; pallas_kernels/paged_attention.py fuses the page gather into
    the attention inner loop behind the same signature contract (armed via
    ``FLAGS_paged_attention_kernel``, see :func:`paged_kernel_mode` and
    ``serving.kv_cache.PagedKVCache.decode_attention``).
    """
    scores = jnp.einsum("bhd,blhd->bhl", q, ctx_k) * sm_scale
    mask = jnp.arange(ctx_k.shape[1])[None, None, :] < ctx_len[:, None, None]
    scores = jnp.where(mask, scores, neg_inf(scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhl,blhd->bhd", probs, ctx_v)


def verify_attention(q, ctx_k, ctx_v, ctx_len, sm_scale=1.0):
    """Multi-position verify-window attention for speculative decode.

    The k-token-window generalization of :func:`decode_attention`: ``q``
    [B,W,H,D] holds the window's queries per slot (window position ``j``
    is logical position ``ctx_len[b] - 1 + j`` — the caller wrote the
    whole window's K/V first, exactly as decode writes before attending),
    and ``ctx_len`` [B] counts valid positions INCLUDING window position
    0 only. Causality inside the window falls out of per-row ragged
    masking: row ``j`` sees ``ctx_len + j`` positions, i.e. everything up
    to and including its own token, nothing after. Row 0 with W=1 is
    :func:`decode_attention` (same masking, same softmax, same
    :func:`neg_inf` constant — numerically equal; XLA batches the window
    contraction differently, so low mantissa bits may move). The
    speculative path's TOKEN bit-parity survives that: accept/sample
    decisions are keyed draws over logit ranks, robust to contraction
    order. Returns [B,W,H,D].
    """
    w = q.shape[1]
    lens = ctx_len[:, None] + jnp.arange(w)[None, :]  # [B,W]
    scores = jnp.einsum("bwhd,blhd->bwhl", q, ctx_k) * sm_scale
    mask = (jnp.arange(ctx_k.shape[1])[None, None, None, :]
            < lens[:, :, None, None])
    scores = jnp.where(mask, scores, neg_inf(scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bwhl,blhd->bwhd", probs, ctx_v)


@register_op("scaled_dot_product_attention")
def sdpa_op(ctx: OpContext):
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    bias = ctx.input("Bias")
    seg_q = ctx.input("SegmentIdsQ")
    seg_kv = ctx.input("SegmentIdsKV")
    causal = ctx.attr("causal", False)
    sm_scale = ctx.attr("sm_scale", 1.0)
    p = 0.0 if ctx.is_test else ctx.attr("dropout_rate", 0.0)
    rng = ctx.rng() if p > 0.0 else None
    ctx.set_output("Out", sdpa(q, k, v, bias, seg_q, seg_kv, causal, sm_scale, p, rng))
