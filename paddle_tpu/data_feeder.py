"""DataFeeder (reference: python/paddle/fluid/data_feeder.py:100).

Converts reader minibatches (lists of per-example tuples) into the feed dict
the Executor consumes. LoD-style nested sequences are padded to the batch max
length with an accompanying ``<name>_mask`` array when requested — the
segment-ids/packing replacement for LoDTensor (SURVEY §5.7).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.dtypes import convert_dtype
from .core.framework import Variable

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list: Sequence[Variable], place=None, program=None,
                 pad_sequences: bool = False, emit_masks: bool = False):
        self.feed_vars = list(feed_list)
        self.place = place
        self.pad_sequences = pad_sequences
        self.emit_masks = emit_masks

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        """iterable: list of examples, each a tuple/list with one entry per
        feed var. Returns {var_name: batched ndarray}."""
        rows = list(iterable)
        if not rows:
            raise ValueError("DataFeeder.feed got an empty minibatch")
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            col = [r[i] for r in rows]
            dtype = np.dtype(convert_dtype(var.dtype)) if convert_dtype(var.dtype) != "bfloat16" else np.float32
            first = np.asarray(col[0])
            ragged = any(np.asarray(c).shape != first.shape for c in col)
            if ragged:
                if not self.pad_sequences:
                    raise ValueError(
                        "feed var %r has ragged examples; construct DataFeeder "
                        "with pad_sequences=True to pad to batch max length"
                        % var.name)
                maxlen = max(np.asarray(c).shape[0] for c in col)
                tail = np.asarray(col[0]).shape[1:]
                batch = np.zeros((len(col), maxlen) + tail, dtype=dtype)
                mask = np.zeros((len(col), maxlen), dtype=np.float32)
                for j, c in enumerate(col):
                    c = np.asarray(c, dtype=dtype)
                    batch[j, : c.shape[0]] = c
                    mask[j, : c.shape[0]] = 1.0
                out[var.name] = batch
                if self.emit_masks:
                    out[var.name + "_mask"] = mask
            else:
                arr = np.asarray(col, dtype=dtype)
                # Fluid convention: int64 label columns become [N, 1]
                shape = var.shape or ()
                if len(shape) > arr.ndim and shape[-1] == 1:
                    arr = arr.reshape(arr.shape + (1,))
                out[var.name] = arr
        return out
