"""Text/sequence dataset family (reference: python/paddle/dataset/ — imdb.py,
imikolov.py, conll05.py, wmt14.py/wmt16.py, movielens.py).

Synthetic, deterministic, zero-egress — same reader contracts (yield
structure, dtypes, dict helpers) as the download-backed reference modules;
see dataset/synthetic.py for the substitution rationale.
"""

from __future__ import annotations

import numpy as np

__all__ = ["imdb", "imikolov", "conll05", "wmt16", "movielens"]


class _Synth:
    pass


def _seq(rng, vocab, lo=4, hi=30):
    return rng.randint(2, vocab, size=rng.randint(lo, hi)).astype("int64").tolist()


# -- imdb: sentiment classification ------------------------------------------


class imdb(_Synth):
    """reference: dataset/imdb.py — (word-id sequence, 0/1 label)."""

    VOCAB = 5147  # reference word_dict size ballpark

    @staticmethod
    def word_dict():
        return {("w%d" % i).encode(): i for i in range(imdb.VOCAB)}

    @staticmethod
    def _reader(n, seed):
        def reader():
            rng = np.random.RandomState(seed)
            # label leaks into token distribution so models can learn
            for _ in range(n):
                y = int(rng.randint(2))
                base = _seq(rng, imdb.VOCAB)
                marker = 3 if y else 4
                seq = [marker if rng.rand() < 0.3 else t for t in base]
                yield seq, y

        return reader

    @staticmethod
    def train(word_idx=None):
        return imdb._reader(2000, seed=11)

    @staticmethod
    def test(word_idx=None):
        return imdb._reader(400, seed=12)


# -- imikolov: language-model n-grams -----------------------------------------


class imikolov(_Synth):
    """reference: dataset/imikolov.py — n-gram windows for word2vec/NNLM."""

    VOCAB = 2074

    @staticmethod
    def build_dict(min_word_freq=50):
        return {("w%d" % i).encode(): i for i in range(imikolov.VOCAB)}

    @staticmethod
    def _reader(n, ngram, seed):
        def reader():
            rng = np.random.RandomState(seed)
            # Markov-ish chain: next word correlated with previous
            for _ in range(n):
                start = int(rng.randint(2, imikolov.VOCAB - ngram - 3))
                window = [(start + i * 3) % imikolov.VOCAB for i in range(ngram)]
                yield tuple(window)

        return reader

    @staticmethod
    def train(word_idx=None, n=5):
        return imikolov._reader(4000, n, seed=21)

    @staticmethod
    def test(word_idx=None, n=5):
        return imikolov._reader(800, n, seed=22)


# -- conll05: semantic role labeling ------------------------------------------


class conll05(_Synth):
    """reference: dataset/conll05.py — SRL: (word, ctx-ngrams×5, predicate,
    mark, IOB label) sequences. Synthetic grammar keeps tags learnable."""

    WORD_VOCAB = 4000
    PRED_VOCAB = 300
    NUM_LABELS = 9  # IOB over 4 chunk types + O

    @staticmethod
    def get_dict():
        word_dict = {("w%d" % i).encode(): i for i in range(conll05.WORD_VOCAB)}
        verb_dict = {("v%d" % i).encode(): i for i in range(conll05.PRED_VOCAB)}
        label_dict = {("l%d" % i).encode(): i for i in range(conll05.NUM_LABELS)}
        return word_dict, verb_dict, label_dict

    @staticmethod
    def _reader(n, seed):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                t = int(rng.randint(5, 20))
                words = rng.randint(0, conll05.WORD_VOCAB, t).astype("int64")
                pred = int(rng.randint(conll05.PRED_VOCAB))
                pred_pos = int(rng.randint(t))
                mark = np.zeros(t, "int64")
                mark[pred_pos] = 1
                # labels depend on distance to predicate — learnable
                labels = np.minimum(np.abs(np.arange(t) - pred_pos),
                                    conll05.NUM_LABELS - 1).astype("int64")
                ctx = [np.roll(words, s) for s in (-2, -1, 0, 1, 2)]
                yield (words.tolist(), *[c.tolist() for c in ctx],
                       [pred] * t, mark.tolist(), labels.tolist())

        return reader

    @staticmethod
    def test():
        return conll05._reader(200, seed=32)

    @staticmethod
    def train():
        return conll05._reader(1000, seed=31)


# -- wmt16: translation pairs --------------------------------------------------


class wmt16(_Synth):
    """reference: dataset/wmt16.py — (src ids, trg ids, trg_next ids)."""

    SRC_VOCAB = 3000
    TRG_VOCAB = 3000
    BOS, EOS, UNK = 0, 1, 2

    @staticmethod
    def _reader(n, seed):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                src = _seq(rng, wmt16.SRC_VOCAB, 4, 20)
                # target = reversed source offset by 7 → learnable mapping
                trg_core = [(t + 7) % wmt16.TRG_VOCAB for t in reversed(src)]
                trg = [wmt16.BOS] + trg_core
                trg_next = trg_core + [wmt16.EOS]
                yield src, trg, trg_next

        return reader

    @staticmethod
    def train(src_dict_size=SRC_VOCAB, trg_dict_size=TRG_VOCAB, src_lang="en"):
        return wmt16._reader(2000, seed=41)

    @staticmethod
    def test(src_dict_size=SRC_VOCAB, trg_dict_size=TRG_VOCAB, src_lang="en"):
        return wmt16._reader(400, seed=42)


# -- movielens: ratings --------------------------------------------------------


class movielens(_Synth):
    """reference: dataset/movielens.py — (user feats, movie feats, rating)."""

    N_USERS = 944
    N_MOVIES = 1683
    N_AGES = 7
    N_JOBS = 21
    N_CATEGORIES = 19

    @staticmethod
    def max_user_id():
        return movielens.N_USERS - 1

    @staticmethod
    def max_movie_id():
        return movielens.N_MOVIES - 1

    @staticmethod
    def max_job_id():
        return movielens.N_JOBS - 1

    @staticmethod
    def _reader(n, seed):
        def reader():
            rng = np.random.RandomState(seed)
            # latent-factor ground truth → ratings are learnable
            ru = np.random.RandomState(7).randn(movielens.N_USERS, 4)
            rm = np.random.RandomState(8).randn(movielens.N_MOVIES, 4)
            for _ in range(n):
                u = int(rng.randint(movielens.N_USERS))
                m = int(rng.randint(movielens.N_MOVIES))
                gender = u % 2
                age = u % movielens.N_AGES
                job = u % movielens.N_JOBS
                title = [(m + i) % 5000 for i in range(3)]
                categories = [m % movielens.N_CATEGORIES]
                score = float(np.clip(2.5 + ru[u] @ rm[m], 1.0, 5.0))
                yield [u], [gender], [age], [job], [m], categories, title, [score]

        return reader

    @staticmethod
    def train():
        return movielens._reader(4000, seed=51)

    @staticmethod
    def test():
        return movielens._reader(800, seed=52)
