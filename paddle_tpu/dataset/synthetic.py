"""Synthetic dataset substrate.

The reference datasets (python/paddle/dataset/: mnist, cifar, uci_housing, …)
download real archives at import time. This environment is zero-egress, so
each dataset is a deterministic synthetic generator with the SAME reader
interface (``train()``/``test()`` returning example iterators with identical
shapes/dtypes/ranges). Swap in real loaders by pointing the loaders at local
files; the reader contract is unchanged.
"""

from __future__ import annotations

import numpy as np


def class_clusters(n, dim, classes, seed, noise=0.25, flatten=True, image_shape=None):
    """Separable class-conditional Gaussian clusters, deterministically."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim).astype("float32") * 2.0

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            y = int(r.randint(classes))
            x = centers[y] + r.randn(dim).astype("float32") * noise
            if image_shape is not None and not flatten:
                x = x.reshape(image_shape)
            yield x.astype("float32"), y

    return reader


def linear_regression(n, dim, seed, noise=0.1):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim).astype("float32")
    b = float(rng.randn())

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            x = r.randn(dim).astype("float32")
            y = float(x @ w + b + r.randn() * noise)
            yield x, np.asarray([y], dtype="float32")

    return reader
