"""CIFAR-shaped dataset (reference: python/paddle/dataset/cifar.py).

Synthetic (zero-egress): 3x32x32 float32 images, int label — same reader
contract as the reference.
"""

from .synthetic import class_clusters

TRAIN_SIZE = 4096
TEST_SIZE = 512


def train10():
    return class_clusters(TRAIN_SIZE, 3 * 32 * 32, 10, seed=3, flatten=False,
                          image_shape=(3, 32, 32))


def test10():
    return class_clusters(TEST_SIZE, 3 * 32 * 32, 10, seed=4, flatten=False,
                          image_shape=(3, 32, 32))


def train100():
    return class_clusters(TRAIN_SIZE, 3 * 32 * 32, 100, seed=5, flatten=False,
                          image_shape=(3, 32, 32))


def test100():
    return class_clusters(TEST_SIZE, 3 * 32 * 32, 100, seed=6, flatten=False,
                          image_shape=(3, 32, 32))
