"""UCI-housing-shaped regression dataset (reference:
python/paddle/dataset/uci_housing.py). Synthetic (zero-egress): 13 features,
scalar target — same reader contract (the fit_a_line book workload)."""

from .synthetic import linear_regression

TRAIN_SIZE = 404
TEST_SIZE = 102


def train():
    return linear_regression(TRAIN_SIZE, 13, seed=7)


def test():
    return linear_regression(TEST_SIZE, 13, seed=8)
