"""MNIST-shaped dataset (reference: python/paddle/dataset/mnist.py).

Synthetic (zero-egress): 784-dim float32 in [-1, 1]-ish, int label 0-9 —
identical reader contract to the reference's download-backed version.
"""

from .synthetic import class_clusters

TRAIN_SIZE = 8192
TEST_SIZE = 1024


def train():
    return class_clusters(TRAIN_SIZE, 784, 10, seed=1)


def test():
    return class_clusters(TEST_SIZE, 784, 10, seed=2)
