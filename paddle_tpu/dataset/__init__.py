from . import cifar, mnist, uci_housing  # noqa: F401
