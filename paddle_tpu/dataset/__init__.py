from . import cifar, mnist, uci_housing  # noqa: F401
from .text import conll05, imdb, imikolov, movielens, wmt16  # noqa: F401
