"""SE-ResNeXt (reference: benchmark/fluid/models/se_resnext.py — ResNeXt
bottlenecks with cardinality-grouped 3x3 convs + squeeze-and-excitation
channel gating)."""

from __future__ import annotations

from .. import layers


def _conv_bn(x, num_filters, filter_size, stride=1, groups=1, act="relu"):
    conv = layers.conv2d(x, num_filters=num_filters, filter_size=filter_size,
                         stride=stride, padding=(filter_size - 1) // 2,
                         groups=groups, bias_attr=False)
    return layers.batch_norm(conv, act=act)


def _squeeze_excitation(x, num_channels, reduction_ratio=16):
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, size=max(num_channels // reduction_ratio, 1),
                        act="relu")
    excitation = layers.fc(squeeze, size=num_channels, act="sigmoid")
    # scale channels: [N, C] → [N, C, 1, 1] broadcast over H, W
    from ..layers import tensor as tensor_layers

    exc = tensor_layers.reshape(excitation, shape=[0, num_channels, 1, 1])
    return layers.elementwise_mul(x, exc)


def _shortcut(x, ch_out, stride):
    if x.shape[1] != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, act=None)
    return x


def _bottleneck(x, num_filters, stride, cardinality=32, reduction_ratio=16):
    conv0 = _conv_bn(x, num_filters, 1)
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride, groups=cardinality)
    conv2 = _conv_bn(conv1, num_filters * 2, 1, act=None)
    scaled = _squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = _shortcut(x, num_filters * 2, stride)
    return layers.relu(layers.elementwise_add(short, scaled))


def se_resnext(img, label, class_num: int = 1000, layers_cfg=(3, 4, 6, 3),
               cardinality: int = 32, base_filters=(128, 256, 512, 1024)):
    """SE-ResNeXt-50 by default; (avg_loss, logits)."""
    x = _conv_bn(img, 64, 7, stride=2)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    for block, n in enumerate(layers_cfg):
        for i in range(n):
            # reference passes num_filters=[128,256,512,1024] straight into
            # the bottleneck (conv2 doubles it → stage outputs 256..2048)
            x = _bottleneck(x, base_filters[block],
                            stride=2 if i == 0 and block > 0 else 1,
                            cardinality=cardinality)
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.2)
    logits = layers.fc(drop, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return loss, logits
