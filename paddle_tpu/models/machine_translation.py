"""Attention seq2seq machine translation (reference:
benchmark/fluid/models/machine_translation.py + the book's
test_machine_translation.py): GRU encoder + dot-product-attention
DynamicRNN decoder over padded+Length batches."""

from __future__ import annotations

from .. import layers


def seq_to_seq_net(src, src_len, trg, trg_len, labels, dict_size: int,
                   embedding_dim: int = 512, encoder_size: int = 512,
                   decoder_size: int = 512):
    """src/trg [B, T] int64 with lengths, labels [B, Tt, 1] →
    (masked avg loss, decoder logits [B, Tt, V])."""
    src_emb = layers.embedding(src, size=[dict_size, embedding_dim])
    enc_proj = layers.fc(src_emb, size=3 * encoder_size, num_flatten_dims=2)
    enc_out = layers.dynamic_gru(enc_proj, size=encoder_size, length=src_len)

    ts = int(src.shape[1])
    # padded encoder rows are zero vectors (masked scan), but zero scores
    # would still win softmax mass — mask them to -1e9 before normalizing
    src_mask = layers.sequence.sequence_mask(src_len, maxlen=ts, dtype="float32")
    neg_bias = layers.scale(src_mask, scale=1e9, bias=-1e9)  # 0 valid, -1e9 pad

    trg_emb = layers.embedding(trg, size=[dict_size, embedding_dim])
    drnn = layers.DynamicRNN()
    with drnn.block():
        y_t = drnn.step_input(trg_emb, length=trg_len)
        enc = drnn.static_input(enc_out)
        att_bias = drnn.static_input(neg_bias)
        prev = drnn.memory(shape=[decoder_size], value=0.0)
        query = layers.fc(prev, size=encoder_size, bias_attr=False)
        scores = layers.matmul(enc, layers.unsqueeze(query, axes=[2]))
        att = layers.softmax(layers.elementwise_add(
            layers.squeeze(scores, axes=[2]), att_bias))
        ctx_vec = layers.squeeze(
            layers.matmul(layers.unsqueeze(att, axes=[1]), enc), axes=[1])
        gates = layers.fc([y_t, ctx_vec], size=3 * decoder_size)
        h_t, _, _ = layers.gru_unit(gates, prev, size=3 * decoder_size)
        drnn.update_memory(prev, h_t)
        drnn.output(h_t)
    dec_out = drnn()
    logits = layers.fc(dec_out, size=dict_size, num_flatten_dims=2)
    ce = layers.softmax_with_cross_entropy(logits, labels)
    tt = int(trg.shape[1])
    mask = layers.unsqueeze(
        layers.sequence.sequence_mask(trg_len, maxlen=tt, dtype="float32"),
        axes=[2])
    loss = layers.elementwise_div(
        layers.reduce_sum(layers.elementwise_mul(ce, mask)),
        layers.reduce_sum(mask))
    return loss, logits
